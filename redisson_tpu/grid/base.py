"""GridObject — shared RObject plumbing for data-grid objects.

→ org/redisson/RedissonObject.java + RedissonExpirable.java: every object
is name-addressed, codec-encoded, supports delete/rename/exists/TTL and
``dump()/restore()`` (here: codec-pickled state round-trip).  camelCase
aliases ride the same CamelCompatMixin as the sketch objects.
"""

from __future__ import annotations

import pickle
from typing import Any, Optional

from redisson_tpu.objects.base import CamelCompatMixin


def _journal_wrap(fn):
    """After the wrapped mutator returns, journal the object's full
    current state through the store (capture is atomic under the store
    lock, so seq order equals state order even when the method already
    released the lock — see GridStore._journal_capture)."""
    import functools

    @functools.wraps(fn)
    def wrapper(self, *args, **kwargs):
        res = fn(self, *args, **kwargs)
        store = self._store
        if store.on_journal is not None and not store.journal_suspended:
            store.journal_entry(self._name)
        return res

    return wrapper


def journaled(*method_names):
    """Class decorator: route the named MUTATOR methods through the op
    journal (ISSUE 18 satellite — grid mutations previously bypassed
    it, so replicas and crash recovery could not mirror them).  Grid
    records are full-entry-state and idempotent; read-only methods must
    NOT be listed (every record costs an encode + a journal append).
    The ``_async`` twins wrap the sync methods via ``__getattr__``, so
    decorating the sync form covers both."""

    def deco(cls):
        for n in method_names:
            setattr(cls, n, _journal_wrap(getattr(cls, n)))
        return cls

    return deco


class GridObject(CamelCompatMixin):
    KIND: str = ""

    def __init__(self, name: str, client):
        self._name = name
        self._client = client
        self._store = client._grid
        self._codec = client.config.codec

    # -- identity ----------------------------------------------------------

    def get_name(self) -> str:
        return self._name

    @property
    def name(self) -> str:
        return self._name

    # -- codec helpers -----------------------------------------------------

    def _enc(self, obj: Any) -> bytes:
        return self._codec.encode(obj)

    def _dec(self, data: bytes) -> Any:
        return self._codec.decode(data)

    def _enc_key(self, obj: Any) -> bytes:
        return self._codec.encode_key(obj)

    def _dec_key(self, data: bytes) -> Any:
        return self._codec.decode_key(data)

    # -- near-cache reach (ISSUE 14 satellite) -----------------------------
    #
    # Hot grid SCALAR reads (XLEN, GEOPOS/GEODIST-class) ride the sketch
    # engine's epoch-guarded near cache: grid keys live under a
    # ``grid:``-prefixed tenant so they can never collide with a sketch
    # tenant, every mutator bumps the write epoch, and the store-level
    # delete/rename/expiry paths invalidate through GridStore's hook.
    # Reads and writes both run under the one grid store lock, so the
    # capture-before-compute / install-if-unmoved discipline is exactly
    # the engine's (cache/nearcache.py module doc).

    def _nc_store(self):
        return getattr(
            getattr(self._client, "_engine", None), "nearcache", None
        )

    def _nc_bump(self, structural: bool = False) -> None:
        nc = self._nc_store()
        if nc is not None:
            note = nc.note_structural if structural else nc.note_write
            note("grid:" + self._name)

    def _nc_scalar(self, kind: str, key, compute):
        """Epoch-tagged scalar read-through; falls straight through to
        ``compute()`` when the tier is off.

        Cached values carry the key's TTL DEADLINE: a probe past it
        recomputes (which lazily reaps) instead of serving the
        pre-expiry value for up to a sweep interval — expiry is
        observed at read time, exactly like an uncached read.  TTL
        *changes* (EXPIRE/PERSIST) invalidate through the store hook,
        so a stale deadline can never outlive the command that moved
        it."""
        nc = self._nc_store()
        if nc is None or not nc.active(1):
            return compute()
        import time as _time

        from redisson_tpu.cache.lru import MISS

        tenant = "grid:" + self._name
        captured = nc.epochs(tenant)
        hit = nc.probe(tenant, key)
        if hit is not MISS:
            v, deadline = hit
            if deadline is None or _time.time() < deadline:
                nc._count(kind, 1, 0)
                return v
        nc._count(kind, 0, 1)
        v = compute()
        # Deadline AFTER compute: an EXPIRE landing between the two
        # bumps the epoch (store hook) and retires this install.
        deadline = self._store.peek_expire_at(self._name)
        nc.install(
            tenant, key, (v, deadline), captured=captured, monotone=False
        )
        return v

    # -- keyspace ops (→ RedissonObject) -----------------------------------

    def is_exists(self) -> bool:
        return self._store.exists(self._name)

    def delete(self) -> bool:
        return self._store.delete(self._name)

    def rename(self, new_name: str) -> None:
        """→ RedissonObject#rename: raises when the source key does not
        exist (Redis RENAME semantics); the facade only re-points on
        success."""
        if not self._store.rename(self._name, new_name):
            raise RuntimeError(f"object {self._name!r} does not exist")
        self._name = new_name

    def touch(self) -> bool:
        return self._store.exists(self._name)

    def unlink(self) -> bool:
        return self.delete()

    # -- TTL (→ RedissonExpirable) -----------------------------------------

    def expire(self, ttl_seconds: float) -> bool:
        return self._store.expire(self._name, float(ttl_seconds))

    def expire_at(self, epoch_seconds: float) -> bool:
        return self._store.expire_at(self._name, float(epoch_seconds))

    def clear_expire(self) -> bool:
        return self._store.clear_expire(self._name)

    def remain_time_to_live(self) -> int:
        return self._store.remain_ttl_ms(self._name)

    # -- dump/restore (→ RObject#dump/restore over DUMP/RESTORE) -----------

    def dump(self) -> bytes:
        e = self._store.get_entry(self._name, self.KIND)
        if e is None:
            raise RuntimeError(f"object {self._name!r} does not exist")
        return pickle.dumps((self.KIND, e.value), protocol=pickle.HIGHEST_PROTOCOL)

    def restore(self, data: bytes, replace: bool = False) -> None:
        kind, value = pickle.loads(data)
        if kind != self.KIND:
            raise TypeError(f"dump holds a {kind}, not a {self.KIND}")
        with self._store.lock:
            if not replace and self._store.exists(self._name):
                raise RuntimeError(f"object {self._name!r} already exists")
            self._store.put_entry(self._name, self.KIND, value)

    # -- internals ---------------------------------------------------------

    def _entry(self, create: bool = True):
        if create:
            return self._store.ensure_entry(self._name, self.KIND, self._new_value)
        return self._store.get_entry(self._name, self.KIND)

    @staticmethod
    def _new_value() -> Any:
        raise NotImplementedError

    def __getattr__(self, item):
        # RFuture idiom parity (→ every reference object's *Async twin):
        # ``fooAsync``/``foo_async`` works for EVERY grid method, running
        # off the caller thread.  Methods whose NAME can block (queue
        # take/poll, lock waits — _may_block) get a dedicated thread per
        # call, because on a shared bounded pool blocked ops occupy every
        # worker and the op that would unblock them queues behind
        # (deadlock); everything else runs on ONE bounded shared pool so
        # thousands of concurrent async gets cost pool-width threads,
        # not one thread each.  Like the reference's async facade,
        # ordering across independent async calls is not guaranteed;
        # Batch provides the ordered pipeline.
        if item.endswith("_async") and not item.startswith("_"):
            sync = getattr(self, item[: -len("_async")], None)
            if callable(sync):

                def async_form(*args, **kwargs):
                    return _spawn_future(sync, args, kwargs)

                return async_form
        return super().__getattr__(item)


# Method-name tokens that can legitimately BLOCK (waiting on another
# grid op to unblock them): these MUST run on dedicated threads — on a
# shared bounded pool they occupy every worker and the op that would
# release them queues behind (classic pool deadlock).  False positives
# (a non-blocking 'put') merely cost one extra thread; a false NEGATIVE
# deadlocks, so the list errs broad.
_BLOCKING_TOKENS = (
    "take", "poll", "lock", "acquire", "wait", "await", "transfer",
    "offer", "put", "pop", "read", "drain", "subscribe", "listen",
    "publish", "invoke", "remove",
)


def _may_block(name: str) -> bool:
    n = name.lower()
    return any(t in n for t in _BLOCKING_TOKENS)


import threading as _threading

from redisson_tpu.analysis import witness as _witness

_shared_pool = None
# Module-scope lock: creating it lazily raced — two first callers could
# each install a different lock and build two executors.
# Witness-named (ISSUE 9 satellite: grid-tier lock coverage); identity
# when the witness is off.
_shared_pool_lock = _witness.named(_threading.Lock(), "grid.shared_pool")


def _get_shared_pool():
    """ONE bounded pool per process for non-blocking async twins (the
    reference's shared executor role)."""
    global _shared_pool
    with _shared_pool_lock:
        if _shared_pool is None:
            import concurrent.futures
            import os

            _shared_pool = concurrent.futures.ThreadPoolExecutor(
                max_workers=min(32, (os.cpu_count() or 4) + 4),
                thread_name_prefix="rtpu-async-pool",
            )
        return _shared_pool


def _spawn_future(fn, args, kwargs):
    """Run ``fn`` off-thread; returns a concurrent-style future
    (result/get/done).  Possibly-blocking methods (by name — see
    _may_block) get a dedicated daemon thread so they can never starve
    each other; everything else shares one bounded pool, so 5k
    concurrent async map gets cost pool-width threads, not 5k."""
    import concurrent.futures
    import threading

    if not _may_block(getattr(fn, "__name__", "")):
        return _PoolFuture(_get_shared_pool().submit(fn, *args, **kwargs))

    fut: "concurrent.futures.Future" = concurrent.futures.Future()

    def run():
        if not fut.set_running_or_notify_cancel():
            return
        try:
            fut.set_result(fn(*args, **kwargs))
        except BaseException as e:
            fut.set_exception(e)

    threading.Thread(target=run, daemon=True, name="rtpu-grid-async").start()
    return _PoolFuture(fut)


class _PoolFuture:
    """concurrent.futures adapter with the RFuture-ish get/done surface
    the sketch futures expose.  ``result()`` waits indefinitely by
    default, matching concurrent.futures and the sync-call contract."""

    def __init__(self, fut):
        self._fut = fut

    def result(self, timeout: Optional[float] = None):
        return self._fut.result(timeout)

    def get(self):
        return self.result()

    def done(self) -> bool:
        return self._fut.done()
