"""RBatch — → org/redisson/RedissonBatch.java (SURVEY.md §3.4): the
user-facing deferred-execution facade the north star says must survive.

``client.create_batch()`` hands out batch-scoped object facades; every
method call queues instead of executing and returns a placeholder future;
``execute()`` runs the queue in submission order — sketch ops ride their
``*_async`` forms so the whole batch coalesces into few device dispatches
(the IN_MEMORY per-node pipeline analog) — and returns a ``BatchResult``
with one response per queued call.
"""

from __future__ import annotations

from typing import Any

_PENDING = object()


class BatchResult:
    """→ org/redisson/api/BatchResult.java."""

    def __init__(self, responses: list):
        self._responses = responses

    def get_responses(self) -> list:
        return self._responses

    @property
    def responses(self) -> list:
        return self._responses

    def __len__(self):
        return len(self._responses)

    def __getitem__(self, i):
        return self._responses[i]


class BatchFuture:
    """Placeholder resolved by Batch.execute() (the RFuture a queued batch
    call returns in the reference)."""

    def __init__(self):
        self._value = _PENDING

    def _set(self, value: Any) -> None:
        self._value = value

    def result(self):
        if self._value is _PENDING:
            raise RuntimeError("batch has not been executed yet")
        return self._value

    get = result

    def done(self) -> bool:
        return self._value is not _PENDING


class _BatchProxy:
    """Object facade whose method calls queue into the batch."""

    def __init__(self, batch: "Batch", obj):
        object.__setattr__(self, "_batch", batch)
        object.__setattr__(self, "_obj", obj)

    def __getattr__(self, item):
        target = getattr(self._obj, item)  # resolves camelCase aliases too
        if not callable(target):
            return target

        def queued(*args, **kwargs):
            fut = BatchFuture()
            self._batch._ops.append((self._obj, item, args, kwargs, fut))
            return fut

        return queued


class Batch:
    """→ RedissonBatch: ``get_*`` mirrors the client surface; objects are
    batch-scoped proxies."""

    def __init__(self, client):
        self._client = client
        self._ops: list[tuple] = []
        self._executed = False

    def __getattr__(self, item):
        if item.startswith("get_") or (item.startswith("get") and item[3:4].isupper()):
            factory = getattr(self._client, item)

            def make(*args, **kwargs):
                return _BatchProxy(self, factory(*args, **kwargs))

            return make
        raise AttributeError(item)

    def execute(self) -> BatchResult:
        """Run every queued call in submission order; returns one response
        per call.  A batch is single-shot (reference semantics).

        Calls queued through a ``*_async`` method resolve their LazyResult
        at the end, so sketch dispatches issued earlier in the batch
        pipeline/coalesce; sync-named calls run with their exact sync
        return contract.
        """
        if self._executed:
            raise RuntimeError("batch was already executed")
        self._executed = True
        from redisson_tpu.grid.base import GridObject
        from redisson_tpu.objects.base import camel_to_snake

        serial = None  # per-execute single worker: grid ops leave the
        # caller thread but keep submission order (the one-connection
        # pipeline ordering of the reference batch)
        staged: list[tuple] = []  # (pending_future_or_None, BatchFuture)
        try:
            for obj, meth, args, kwargs, fut in self._ops:
                # Normalize camelCase alias spellings FIRST: without it,
                # 'incrementAndGetAsync' matches neither the _DEFERRED
                # table nor endswith('_async'), and the batch resolved to
                # a raw future handle instead of the value.
                if not hasattr(type(obj), meth):
                    meth = camel_to_snake(meth)
                # Sync-named sketch calls ride their deferred (async)
                # forms so the whole batch coalesces into few device
                # dispatches — the reference batch pipelines everything
                # by construction (SURVEY.md §3.4); resolved values keep
                # the sync contract.
                deferred = getattr(type(obj), "_DEFERRED", {}).get(meth)
                if deferred is not None:
                    staged.append(
                        (getattr(obj, deferred)(*args, **kwargs), fut)
                    )
                    continue
                if isinstance(obj, GridObject):
                    # ALL grid ops — sync- and async-named — run on ONE
                    # serial worker in submission order (a per-call
                    # thread for async names raced the serial stream:
                    # a get could observe the map before an earlier
                    # fast_put_async).  For *_async names, call the
                    # underlying sync form: the batch pipeline itself is
                    # the asynchrony.  Blocking ops act at execute() like
                    # commands in a Redis MULTI — don't queue them.
                    if meth.endswith("_async"):
                        sync_meth = meth[: -len("_async")]
                        if hasattr(obj, sync_meth):
                            meth = sync_meth
                    if serial is None:
                        from concurrent.futures import ThreadPoolExecutor

                        serial = ThreadPoolExecutor(
                            max_workers=1, thread_name_prefix="rtpu-batch"
                        )
                    staged.append(
                        (serial.submit(getattr(obj, meth), *args, **kwargs), fut)
                    )
                    continue
                result = getattr(obj, meth)(*args, **kwargs)
                if meth.endswith("_async") and hasattr(result, "result"):
                    staged.append((result, fut))
                else:
                    fut._set(result)
                    staged.append((None, fut))
            responses = []
            for pending, fut in staged:
                if pending is not None:
                    fut._set(pending.result())
                responses.append(fut.result())
            return BatchResult(responses)
        finally:
            if serial is not None:
                serial.shutdown(wait=False)

    def discard(self) -> None:
        """→ RBatch#discard."""
        self._ops.clear()
        self._executed = True
