"""Buckets/values — → org/redisson/RedissonBucket.java (RBucket),
RedissonBuckets.java (RBuckets multi-get/set), RedissonBinaryStream.java.

Values are stored codec-encoded (the grid's Redis-string analog), so codec
round-trip semantics match the reference: what you read is
``codec.decode(codec.encode(x))``.
"""

from __future__ import annotations

import io
from typing import Any, Optional

from redisson_tpu.grid.base import GridObject, journaled

_MISSING = object()


@journaled("set", "set_if_absent", "set_if_exists", "get_and_set",
           "get_and_delete", "compare_and_set")
class Bucket(GridObject):
    KIND = "bucket"

    @staticmethod
    def _new_value():
        return None

    def get(self) -> Any:
        e = self._entry(create=False)
        if e is None or e.value is None:
            return None
        return self._dec(e.value)

    def set(self, value: Any, ttl_seconds: Optional[float] = None) -> None:
        self._store.put_entry(self._name, self.KIND, self._enc(value))
        if ttl_seconds is not None:
            self.expire(ttl_seconds)

    def set_if_absent(self, value: Any, ttl_seconds: Optional[float] = None) -> bool:
        """→ RBucket#setIfAbsent (SET NX)."""
        with self._store.lock:
            if self._store.exists(self._name):
                return False
            self.set(value, ttl_seconds)
            return True

    # Deprecated reference alias kept for API parity.
    try_set = set_if_absent

    def set_if_exists(self, value: Any) -> bool:
        """→ RBucket#setIfExists (SET XX).  Replaces the entry wholesale —
        Redis SET XX without KEEPTTL clears any TTL, matching set()."""
        with self._store.lock:
            if self._entry(create=False) is None:
                return False
            self._store.put_entry(self._name, self.KIND, self._enc(value))
            return True

    def get_and_set(self, value: Any) -> Any:
        with self._store.lock:
            old = self.get()
            self.set(value)
            return old

    def get_and_delete(self) -> Any:
        with self._store.lock:
            old = self.get()
            self.delete()
            return old

    def compare_and_set(self, expect: Any, update: Any) -> bool:
        """→ RBucket#compareAndSet: encoded-bytes equality, like the
        reference's value comparison on the serialized form."""
        with self._store.lock:
            e = self._entry(create=False)
            cur = None if e is None or e.value is None else e.value
            exp = None if expect is None else self._enc(expect)
            if cur != exp:
                return False
            self.set(update)
            return True

    def size(self) -> int:
        """→ RBucket#size (STRLEN of the serialized value)."""
        e = self._entry(create=False)
        return 0 if e is None or e.value is None else len(e.value)


class Buckets:
    """→ org/redisson/RedissonBuckets.java: multi-key get/set (MGET/MSET)."""

    def __init__(self, client):
        self._client = client
        self._store = client._grid

    def get(self, *names: str) -> dict:
        out = {}
        for n in names:
            v = self._client.get_bucket(n).get()
            if v is not None:
                out[n] = v
        return out

    def set(self, mapping: dict) -> None:
        with self._store.lock:
            for n, v in mapping.items():
                self._client.get_bucket(n).set(v)

    def try_set(self, mapping: dict) -> bool:
        """MSETNX: all-or-nothing if any key exists."""
        with self._store.lock:
            if any(self._store.exists(n) for n in mapping):
                return False
            self.set(mapping)
            return True


@journaled("set")
class BinaryStream(GridObject):
    """→ org/redisson/RedissonBinaryStream.java: raw byte-string key with
    stream-style IO."""

    KIND = "binarystream"

    @staticmethod
    def _new_value():
        return b""

    def get(self) -> bytes:
        e = self._entry(create=False)
        return b"" if e is None else e.value

    def set(self, data: bytes) -> None:
        self._store.put_entry(self._name, self.KIND, bytes(data))

    def size(self) -> int:
        return len(self.get())

    def get_output_stream(self) -> io.BytesIO:
        """Writer whose close() commits the bytes (append semantics)."""
        stream = self

        class _Out(io.BytesIO):
            def close(self) -> None:
                with stream._store.lock:
                    e = stream._entry()
                    e.value = e.value + self.getvalue()
                super().close()

        return _Out()

    def get_input_stream(self) -> io.BytesIO:
        return io.BytesIO(self.get())


@journaled("set_path", "array_append", "string_append", "increment")
class JsonBucket(Bucket):
    """→ RJsonBucket (RedisJSON-backed bucket): JSON value with dot-path
    reads/writes (`$` or empty = root, `a.b.0.c` walks objects/arrays)."""

    KIND = "bucket"

    def __init__(self, name, client):
        super().__init__(name, client)
        import json as _json

        # JSON values travel as canonical JSON bytes regardless of codec.
        self._enc = lambda v: _json.dumps(v).encode()
        self._dec = lambda b: _json.loads(b.decode())

    @staticmethod
    def _walk(root, path):
        if path in ("", "$", None):
            return root, None, None
        parts = [p for p in str(path).replace("$.", "").split(".") if p]
        cur = root
        for p in parts[:-1]:
            cur = cur[int(p)] if isinstance(cur, list) else cur[p]
        leaf = parts[-1]
        key = int(leaf) if isinstance(cur, list) else leaf
        return cur[key], cur, key

    def get_path(self, path: str = "$"):
        """→ RJsonBucket#get(path) (JSON.GET)."""
        doc = self.get()
        if doc is None:
            return None
        value, _, _ = self._walk(doc, path)
        return value

    def _save(self, doc) -> None:
        """In-place value update PRESERVING the key's TTL — RedisJSON path
        writes (JSON.SET path / NUMINCRBY / ARRAPPEND) never touch key
        expiry, unlike SET."""
        with self._store.lock:
            e = self._entry()
            e.value = self._enc(doc)

    def set_path(self, path: str, value) -> None:
        """→ RJsonBucket#set(path, value) (JSON.SET)."""
        if path in ("", "$", None):
            self.set(value)
            return
        with self._store.lock:
            doc = self.get()
            if doc is None:
                raise ValueError("document does not exist; set the root first")
            _, parent, key = self._walk(doc, path)
            parent[key] = value
            self._save(doc)

    def array_append(self, path: str, *values) -> int:
        """→ JSON.ARRAPPEND: new array length."""
        with self._store.lock:
            doc = self.get()
            arr, parent, key = self._walk(doc, path)
            if not isinstance(arr, list):
                raise TypeError(f"path {path!r} does not hold an array")
            arr.extend(values)
            self._save(doc)
            return len(arr)

    def string_append(self, path: str, suffix: str) -> int:
        """→ JSON.STRAPPEND: new string length."""
        with self._store.lock:
            doc = self.get()
            s, parent, key = self._walk(doc, path)
            if not isinstance(s, str):
                raise TypeError(f"path {path!r} does not hold a string")
            out = s + suffix
            if parent is None:
                self._save(out)
            else:
                parent[key] = out
                self._save(doc)
            return len(out)

    def increment(self, path: str, delta) -> float:
        """→ JSON.NUMINCRBY."""
        with self._store.lock:
            doc = self.get()
            n, parent, key = self._walk(doc, path)
            if not isinstance(n, (int, float)) or isinstance(n, bool):
                raise TypeError(f"path {path!r} does not hold a number")
            out = n + delta
            if parent is None:
                self._save(out)
            else:
                parent[key] = out
                self._save(doc)
            return out
