"""Sets and lists — → org/redisson/RedissonSet.java (Redis sets),
RedissonSetCache (per-element TTL via timeout scores), RedissonList
(Redis lists), RedissonSortedSet (comparator order over a Redis list),
RedissonScoredSortedSet (ZSET), RedissonLexSortedSet (lexicographic ZSET).

Element identity follows the codec-encoded bytes, matching the
reference's serialized-member semantics.
"""

from __future__ import annotations

import bisect
import fnmatch
import time
from typing import Any, Iterable, Optional

from redisson_tpu.grid.base import GridObject, journaled


@journaled("add", "add_all", "remove", "remove_random", "union",
           "intersection", "diff")
class Set_(GridObject):
    KIND = "set"

    @staticmethod
    def _new_value():
        return {}  # key bytes -> None (insertion-ordered like Python dict)

    def add(self, value: Any) -> bool:
        with self._store.lock:
            e = self._entry()
            vb = self._enc(value)
            if vb in e.value:
                return False
            e.value[vb] = None
            return True

    def add_all(self, values: Iterable[Any]) -> bool:
        with self._store.lock:
            return any([self.add(v) for v in values])

    def remove(self, value: Any) -> bool:
        with self._store.lock:
            e = self._entry(create=False)
            if e is None:
                return False
            vb = self._enc(value)
            if vb in e.value:  # explicit membership — the old
                del e.value[vb]  # pop(...)-is-None trick silently
                return True  # inverts if stored markers ever change
            return False

    def contains(self, value: Any) -> bool:
        with self._store.lock:
            e = self._entry(create=False)
            return e is not None and self._enc(value) in e.value

    def size(self) -> int:
        with self._store.lock:
            e = self._entry(create=False)
            return 0 if e is None else len(e.value)

    def read_all(self) -> list:
        with self._store.lock:
            e = self._entry(create=False)
            return [] if e is None else [self._dec(vb) for vb in e.value]

    def random(self, count: int = 1) -> list:
        """→ RSet#random (SRANDMEMBER)."""
        import random as _random

        with self._store.lock:
            vals = self.read_all()
            return _random.sample(vals, min(count, len(vals)))

    def remove_random(self, count: int = 1) -> list:
        """→ RSet#removeRandom (SPOP)."""
        with self._store.lock:
            got = self.random(count)
            for v in got:
                self.remove(v)
            return got

    def move(self, dest_name: str, value: Any) -> bool:
        """→ RSet#move (SMOVE)."""
        with self._store.lock:
            # WRONGTYPE-check the destination BEFORE removing — including
            # the FOREIGN backend (a sketch object under dest_name would
            # make add() raise after remove() succeeded: element lost).
            self._store.get_entry(dest_name, self.KIND)
            self._store._guard_foreign(dest_name)
            if not self.remove(value):
                return False
            self._client.get_set(dest_name).add(value)
            return True

    # -- set algebra (SUNION/SINTER/SDIFF + *STORE analogs) ----------------

    def _other(self, name: str) -> set:
        return {self._enc(v) for v in self._client.get_set(name).read_all()}

    def union(self, *names: str) -> int:
        with self._store.lock:
            e = self._entry()
            for n in names:
                for vb in self._other(n):
                    e.value[vb] = None
            return len(e.value)

    def intersection(self, *names: str) -> int:
        with self._store.lock:
            e = self._entry()
            keep = set(e.value)
            for n in names:
                keep &= self._other(n)
            e.value = {vb: None for vb in e.value if vb in keep}
            return len(e.value)

    def diff(self, *names: str) -> int:
        with self._store.lock:
            e = self._entry()
            drop = set()
            for n in names:
                drop |= self._other(n)
            e.value = {vb: None for vb in e.value if vb not in drop}
            return len(e.value)

    def read_union(self, *names: str) -> list:
        with self._store.lock:
            out = {self._enc(v): None for v in self.read_all()}
            for n in names:
                for vb in self._other(n):
                    out[vb] = None
            return [self._dec(vb) for vb in out]

    def read_intersection(self, *names: str) -> list:
        with self._store.lock:
            keep = {self._enc(v) for v in self.read_all()}
            for n in names:
                keep &= self._other(n)
            return [self._dec(vb) for vb in keep]

    def __contains__(self, value):
        return self.contains(value)

    def __len__(self):
        return self.size()


@journaled("add", "remove")
class SetCache(GridObject):
    """→ RedissonSetCache: set with per-element TTL."""

    KIND = "setcache"

    class _Value:
        __slots__ = ("data",)

        def __init__(self):
            self.data: dict[bytes, Optional[float]] = {}

        def live(self, vb: bytes, now: Optional[float] = None) -> bool:
            exp = self.data.get(vb, -1)
            if exp == -1 and vb not in self.data:
                return False
            now = now or time.time()
            if exp is not None and exp != -1 and now >= exp:
                del self.data[vb]
                return False
            return vb in self.data

        def prune_expired(self, now: float) -> None:
            for vb in list(self.data.keys()):
                self.live(vb, now)

    @classmethod
    def _new_value(cls):
        return cls._Value()

    def add(self, value: Any, ttl_seconds: Optional[float] = None) -> bool:
        with self._store.lock:
            e = self._entry()
            vb = self._enc(value)
            fresh = not e.value.live(vb)
            e.value.data[vb] = (
                None if ttl_seconds is None else time.time() + float(ttl_seconds)
            )
            return fresh

    def contains(self, value: Any) -> bool:
        with self._store.lock:
            e = self._entry(create=False)
            return e is not None and e.value.live(self._enc(value))

    def remove(self, value: Any) -> bool:
        with self._store.lock:
            e = self._entry(create=False)
            if e is None:
                return False
            vb = self._enc(value)
            if not e.value.live(vb):
                return False
            del e.value.data[vb]
            return True

    def size(self) -> int:
        with self._store.lock:
            e = self._entry(create=False)
            if e is None:
                return 0
            e.value.prune_expired(time.time())
            return len(e.value.data)

    def read_all(self) -> list:
        with self._store.lock:
            e = self._entry(create=False)
            if e is None:
                return []
            e.value.prune_expired(time.time())
            return [self._dec(vb) for vb in e.value.data]


@journaled("add", "add_all", "insert", "set", "remove", "remove_at",
           "trim")
class List_(GridObject):
    KIND = "list"

    @staticmethod
    def _new_value():
        return []  # list of value bytes

    def add(self, value: Any) -> bool:
        with self._store.lock:
            self._entry().value.append(self._enc(value))
            self._store.notify()
            return True

    def add_all(self, values: Iterable[Any]) -> bool:
        with self._store.lock:
            vals = [self._enc(v) for v in values]
            self._entry().value.extend(vals)
            self._store.notify()
            return bool(vals)

    def insert(self, index: int, value: Any) -> None:
        with self._store.lock:
            self._entry().value.insert(index, self._enc(value))

    def get(self, index: int) -> Any:
        with self._store.lock:
            e = self._entry(create=False)
            if e is None or not -len(e.value) <= index < len(e.value):
                raise IndexError(index)
            return self._dec(e.value[index])

    def set(self, index: int, value: Any) -> None:
        with self._store.lock:
            e = self._entry()
            e.value[index] = self._enc(value)

    def remove(self, value: Any, count: int = 1) -> bool:
        """→ RList#remove(Object) / LREM semantics for count occurrences."""
        with self._store.lock:
            e = self._entry(create=False)
            if e is None:
                return False
            vb = self._enc(value)
            removed = 0
            while removed < count and vb in e.value:
                e.value.remove(vb)
                removed += 1
            return removed > 0

    def remove_at(self, index: int) -> Any:
        with self._store.lock:
            e = self._entry(create=False)
            if e is None:
                raise IndexError(index)
            return self._dec(e.value.pop(index))

    def index_of(self, value: Any) -> int:
        with self._store.lock:
            e = self._entry(create=False)
            if e is None:
                return -1
            try:
                return e.value.index(self._enc(value))
            except ValueError:
                return -1

    def contains(self, value: Any) -> bool:
        return self.index_of(value) >= 0

    def size(self) -> int:
        with self._store.lock:
            e = self._entry(create=False)
            return 0 if e is None else len(e.value)

    def read_all(self) -> list:
        with self._store.lock:
            e = self._entry(create=False)
            return [] if e is None else [self._dec(vb) for vb in e.value]

    def sub_list(self, from_index: int, to_index: int) -> list:
        with self._store.lock:
            e = self._entry(create=False)
            return [] if e is None else [self._dec(vb) for vb in e.value[from_index:to_index]]

    def trim(self, from_index: int, to_index: int) -> None:
        """LTRIM: keep [from, to] inclusive (Redis convention).  Negative
        indexes count from the tail — to=-1 keeps through the LAST element
        (the naive to+1 slice wiped the whole list on exactly that, the
        most common negative form); from > to empties the list."""
        with self._store.lock:
            e = self._entry(create=False)
            if e is None:
                return
            n = len(e.value)
            if from_index < 0:
                from_index = max(0, n + from_index)
            if to_index < 0:
                to_index = n + to_index
            if from_index > to_index or to_index < 0:
                e.value[:] = []
            else:
                e.value[:] = e.value[from_index : to_index + 1]

    def __getitem__(self, index):
        return self.get(index)

    def __setitem__(self, index, value):
        self.set(index, value)

    def __len__(self):
        return self.size()


class SortedSet(GridObject):
    """→ RedissonSortedSet: natural-order sorted collection of distinct
    values."""

    KIND = "sortedset"

    @staticmethod
    def _new_value():
        return []  # sorted list of (decoded value, value bytes)

    def add(self, value: Any) -> bool:
        with self._store.lock:
            e = self._entry()
            vb = self._enc(value)
            if any(b == vb for _, b in e.value):
                return False
            bisect.insort(e.value, (value, vb), key=lambda t: t[0])
            return True

    def remove(self, value: Any) -> bool:
        with self._store.lock:
            e = self._entry(create=False)
            if e is None:
                return False
            vb = self._enc(value)
            for i, (_, b) in enumerate(e.value):
                if b == vb:
                    e.value.pop(i)
                    return True
            return False

    def contains(self, value: Any) -> bool:
        with self._store.lock:
            e = self._entry(create=False)
            vb = self._enc(value)
            return e is not None and any(b == vb for _, b in e.value)

    def first(self) -> Any:
        with self._store.lock:
            e = self._entry(create=False)
            return None if e is None or not e.value else e.value[0][0]

    def last(self) -> Any:
        with self._store.lock:
            e = self._entry(create=False)
            return None if e is None or not e.value else e.value[-1][0]

    def size(self) -> int:
        with self._store.lock:
            e = self._entry(create=False)
            return 0 if e is None else len(e.value)

    def read_all(self) -> list:
        with self._store.lock:
            e = self._entry(create=False)
            return [] if e is None else [v for v, _ in e.value]


@journaled("add", "add_all", "add_score", "remove",
           "remove_range_by_score", "poll_first", "poll_last")
class ScoredSortedSet(GridObject):
    """→ RedissonScoredSortedSet (Redis ZSET)."""

    KIND = "zset"

    @staticmethod
    def _new_value():
        return {}  # member bytes -> float score

    def add(self, score: float, member: Any) -> bool:
        with self._store.lock:
            e = self._entry()
            mb = self._enc(member)
            fresh = mb not in e.value
            e.value[mb] = float(score)
            return fresh

    def add_all(self, mapping: dict) -> int:
        """mapping: member -> score."""
        with self._store.lock:
            return sum(1 for m, s in mapping.items() if self.add(s, m))

    def add_score(self, member: Any, delta: float) -> float:
        """ZINCRBY."""
        with self._store.lock:
            e = self._entry()
            mb = self._enc(member)
            e.value[mb] = e.value.get(mb, 0.0) + float(delta)
            return e.value[mb]

    def get_score(self, member: Any) -> Optional[float]:
        with self._store.lock:
            e = self._entry(create=False)
            return None if e is None else e.value.get(self._enc(member))

    def remove(self, member: Any) -> bool:
        with self._store.lock:
            e = self._entry(create=False)
            if e is None:
                return False
            return e.value.pop(self._enc(member), None) is not None

    def rank(self, member: Any) -> Optional[int]:
        """ZRANK (ascending, ties by member bytes like Redis lex order)."""
        with self._store.lock:
            order = self._sorted()
            mb = self._enc(member)
            for i, (b, _) in enumerate(order):
                if b == mb:
                    return i
            return None

    def _sorted(self):
        e = self._entry(create=False)
        if e is None:
            return []
        return sorted(e.value.items(), key=lambda kv: (kv[1], kv[0]))

    def value_range(self, start: int, end: int) -> list:
        """ZRANGE start..end inclusive."""
        with self._store.lock:
            order = self._sorted()
            end = len(order) if end == -1 else end + 1
            return [self._dec(b) for b, _ in order[start:end]]

    def entry_range(self, start: int, end: int) -> list:
        with self._store.lock:
            order = self._sorted()
            end = len(order) if end == -1 else end + 1
            return [(self._dec(b), s) for b, s in order[start:end]]

    def value_range_by_score(self, min_score: float, max_score: float) -> list:
        with self._store.lock:
            return [
                self._dec(b)
                for b, s in self._sorted()
                if min_score <= s <= max_score
            ]

    def remove_range_by_score(self, min_score: float, max_score: float) -> int:
        with self._store.lock:
            e = self._entry(create=False)
            if e is None:
                return 0
            drop = [b for b, s in e.value.items() if min_score <= s <= max_score]
            for b in drop:
                del e.value[b]
            return len(drop)

    def poll_first(self) -> Any:
        """ZPOPMIN."""
        with self._store.lock:
            order = self._sorted()
            if not order:
                return None
            b, _ = order[0]
            self._entry().value.pop(b, None)
            return self._dec(b)

    def poll_last(self) -> Any:
        with self._store.lock:
            order = self._sorted()
            if not order:
                return None
            b, _ = order[-1]
            self._entry().value.pop(b, None)
            return self._dec(b)

    def first(self) -> Any:
        with self._store.lock:
            order = self._sorted()
            return None if not order else self._dec(order[0][0])

    def last(self) -> Any:
        with self._store.lock:
            order = self._sorted()
            return None if not order else self._dec(order[-1][0])

    def count(self, min_score: float, max_score: float) -> int:
        with self._store.lock:
            return len(self.value_range_by_score(min_score, max_score))

    def contains(self, member: Any) -> bool:
        return self.get_score(member) is not None

    def size(self) -> int:
        with self._store.lock:
            e = self._entry(create=False)
            return 0 if e is None else len(e.value)

    def read_all(self) -> list:
        with self._store.lock:
            return [self._dec(b) for b, _ in self._sorted()]


@journaled("add", "add_all", "remove")
class LexSortedSet(GridObject):
    """→ RedissonLexSortedSet: string ZSET, all scores 0, lexicographic
    range ops."""

    KIND = "lexset"

    @staticmethod
    def _new_value():
        return set()  # of str

    def add(self, value: str) -> bool:
        with self._store.lock:
            e = self._entry()
            if value in e.value:
                return False
            e.value.add(value)
            return True

    def add_all(self, values: Iterable[str]) -> int:
        with self._store.lock:
            return sum(1 for v in values if self.add(v))

    def remove(self, value: str) -> bool:
        with self._store.lock:
            e = self._entry(create=False)
            if e is None or value not in e.value:
                return False
            e.value.discard(value)
            return True

    def contains(self, value: str) -> bool:
        with self._store.lock:
            e = self._entry(create=False)
            return e is not None and value in e.value

    def range(self, from_value: str, from_inclusive: bool,
              to_value: str, to_inclusive: bool) -> list:
        """ZRANGEBYLEX."""
        with self._store.lock:
            e = self._entry(create=False)
            if e is None:
                return []
            lo = (lambda v: v >= from_value) if from_inclusive else (lambda v: v > from_value)
            hi = (lambda v: v <= to_value) if to_inclusive else (lambda v: v < to_value)
            return sorted(v for v in e.value if lo(v) and hi(v))

    def range_head(self, to_value: str, inclusive: bool = False) -> list:
        with self._store.lock:
            e = self._entry(create=False)
            if e is None:
                return []
            hi = (lambda v: v <= to_value) if inclusive else (lambda v: v < to_value)
            return sorted(v for v in e.value if hi(v))

    def range_tail(self, from_value: str, inclusive: bool = False) -> list:
        with self._store.lock:
            e = self._entry(create=False)
            if e is None:
                return []
            lo = (lambda v: v >= from_value) if inclusive else (lambda v: v > from_value)
            return sorted(v for v in e.value if lo(v))

    def count(self, from_value: str, from_inclusive: bool,
              to_value: str, to_inclusive: bool) -> int:
        return len(self.range(from_value, from_inclusive, to_value, to_inclusive))

    def size(self) -> int:
        with self._store.lock:
            e = self._entry(create=False)
            return 0 if e is None else len(e.value)

    def read_all(self) -> list:
        with self._store.lock:
            e = self._entry(create=False)
            return [] if e is None else sorted(e.value)
