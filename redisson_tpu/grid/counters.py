"""Counters — → org/redisson/RedissonAtomicLong.java, RedissonAtomicDouble,
RedissonLongAdder/RedissonDoubleAdder (topic-coordinated in the reference;
in-process the adder IS its sum), RedissonIdGenerator (allocation-block id
ranges).
"""

from __future__ import annotations

from redisson_tpu.grid.base import GridObject, journaled


def _as_int(v) -> int:
    """Strict integer view of a counter value.  A fractional value (left
    by a RESP INCRBYFLOAT on this key) raises like Java's
    NumberFormatException in RedissonAtomicLong — silently truncating
    would lose the fraction on the next write."""
    if isinstance(v, int):
        return v  # no float round-trip: ints past 2**53 (or 10**400)
    if not float(v).is_integer():
        raise ValueError(f"counter value {v!r} is not an integer")
    return int(v)


@journaled("set", "add_and_get", "get_and_add", "increment_and_get",
           "decrement_and_get", "get_and_increment", "get_and_decrement",
           "get_and_set", "compare_and_set", "get_and_delete")
class AtomicLong(GridObject):
    KIND = "atomiclong"
    # One counter FAMILY on read: RESP INCR/INCRBYFLOAT may legitimately
    # flip an entry between the two kinds (serve/resp.py _numeric_incr),
    # and a live handle of either class must keep working across that.
    _FAMILY = ("atomiclong", "atomicdouble")

    @staticmethod
    def _new_value():
        return 0

    def _entry(self, create: bool = True):
        e = self._store.get_entry(self._name)
        if e is not None and e.kind not in self._FAMILY:
            raise TypeError(
                f"object {self._name!r} holds a {e.kind}, not a {self.KIND}"
            )
        if e is None and create:
            e = self._store.ensure_entry(self._name, self.KIND, self._new_value)
        return e

    def get(self) -> int:
        e = self._entry(create=False)
        return 0 if e is None else _as_int(e.value)

    def set(self, value: int) -> None:
        self._store.put_entry(self._name, self.KIND, int(value))

    def add_and_get(self, delta: int) -> int:
        with self._store.lock:
            e = self._entry()
            e.value = _as_int(e.value) + int(delta)
            e.kind = self.KIND  # an integer write re-claims the int kind
            return e.value

    def get_and_add(self, delta: int) -> int:
        with self._store.lock:
            e = self._entry()
            old = _as_int(e.value)
            e.value = old + int(delta)
            e.kind = self.KIND
            return old

    def increment_and_get(self) -> int:
        return self.add_and_get(1)

    def decrement_and_get(self) -> int:
        return self.add_and_get(-1)

    def get_and_increment(self) -> int:
        return self.get_and_add(1)

    def get_and_decrement(self) -> int:
        return self.get_and_add(-1)

    def get_and_set(self, value: int) -> int:
        with self._store.lock:
            e = self._entry()
            old = _as_int(e.value)
            e.value = int(value)
            e.kind = self.KIND
            return old

    def compare_and_set(self, expect: int, update: int) -> bool:
        with self._store.lock:
            e = self._entry(create=False)
            cur = 0 if e is None else _as_int(e.value)  # absent reads as 0
            if cur != int(expect):
                return False  # failed CAS must NOT materialize the key
            self._store.put_entry(self._name, self.KIND, int(update))
            return True

    def get_and_delete(self) -> int:
        with self._store.lock:
            old = self.get()
            self.delete()
            return old


@journaled("set", "add_and_get", "get_and_add", "get_and_set",
           "compare_and_set")
class AtomicDouble(AtomicLong):
    """→ RedissonAtomicDouble — same surface over float."""

    KIND = "atomicdouble"

    @staticmethod
    def _new_value():
        return 0.0

    def get(self) -> float:
        e = self._entry(create=False)
        return 0.0 if e is None else float(e.value)

    def set(self, value: float) -> None:
        self._store.put_entry(self._name, self.KIND, float(value))

    def add_and_get(self, delta: float) -> float:
        with self._store.lock:
            e = self._entry()
            e.value = float(e.value) + float(delta)
            e.kind = self.KIND  # a float write claims the double kind
            return e.value

    def get_and_add(self, delta: float) -> float:
        with self._store.lock:
            e = self._entry()
            old = float(e.value)
            e.value = old + float(delta)
            e.kind = self.KIND
            return old

    def get_and_set(self, value: float) -> float:
        with self._store.lock:
            e = self._entry()
            old = float(e.value)
            e.value = float(value)
            e.kind = self.KIND
            return old

    def compare_and_set(self, expect: float, update: float) -> bool:
        with self._store.lock:
            e = self._entry(create=False)
            cur = 0.0 if e is None else float(e.value)
            if cur != float(expect):
                return False  # failed CAS must NOT materialize the key
            self._store.put_entry(self._name, self.KIND, float(update))
            return True


@journaled("add", "increment", "decrement", "reset")
class LongAdder(GridObject):
    """→ RedissonLongAdder.  The reference keeps per-client local counters
    synced over a topic; in-process the shared cell is the sum itself."""

    KIND = "longadder"

    @staticmethod
    def _new_value():
        return 0

    def add(self, delta: int) -> None:
        with self._store.lock:
            e = self._entry()
            e.value = int(e.value) + int(delta)

    def increment(self) -> None:
        self.add(1)

    def decrement(self) -> None:
        self.add(-1)

    def sum(self) -> int:
        e = self._entry(create=False)
        return 0 if e is None else int(e.value)

    def reset(self) -> None:
        self._store.put_entry(self._name, self.KIND, 0)


@journaled("add", "reset")
class DoubleAdder(GridObject):
    KIND = "doubleadder"

    @staticmethod
    def _new_value():
        return 0.0

    def add(self, delta: float) -> None:
        with self._store.lock:
            e = self._entry()
            e.value = float(e.value) + float(delta)

    def sum(self) -> float:
        e = self._entry(create=False)
        return 0.0 if e is None else float(e.value)

    def reset(self) -> None:
        self._store.put_entry(self._name, self.KIND, 0.0)


@journaled("try_init", "next_id")
class IdGenerator(GridObject):
    """→ org/redisson/RedissonIdGenerator.java: ids handed out from locally
    cached allocation blocks reserved atomically from the shared counter."""

    KIND = "idgenerator"

    def __init__(self, name, client):
        super().__init__(name, client)
        self._local_next = 0
        self._local_end = 0

    @staticmethod
    def _new_value():
        # (next unallocated id, allocation block size)
        return {"next": 0, "block": 5000}

    def try_init(self, start: int, allocation_size: int) -> bool:
        if allocation_size < 1:
            raise ValueError(  # a zero-width block would hand out the
                "allocation_size must be >= 1"  # same id forever
            )
        with self._store.lock:
            if self._store.exists(self._name):
                return False
            self._store.put_entry(
                self._name, self.KIND,
                {"next": int(start), "block": int(allocation_size)},
            )
            return True

    def next_id(self) -> int:
        with self._store.lock:
            if self._local_next >= self._local_end:
                e = self._entry()
                start = e.value["next"]
                e.value["next"] = start + e.value["block"]
                self._local_next, self._local_end = start, e.value["next"]
            v = self._local_next
            self._local_next += 1
            return v
