"""CronExpression — → org/redisson/executor/CronExpression (the Quartz
cron grammar RScheduledExecutorService#schedule(cron) accepts).

Supports the Quartz 6-field form with seconds (``sec min hour dom month
dow``) and the classic 5-field form (minute resolution); ``?`` is
accepted as ``*`` (Quartz day-field convention), along with ``*``,
``*/n``, ``a-b``, ``a-b/n`` and comma lists.

Day-of-week numbering follows the FORM's own convention — the two
grammars disagree and silently firing on the wrong day is worse than
either choice alone:
- 6-field (Quartz): numeric 1=SUN .. 7=SAT (the Quartz convention);
- 5-field (classic cron): numeric 0=SUN .. 6=SAT, with 7 also Sunday;
- SUN..SAT names work identically in both.
Classic cron's dom/dow OR rule also applies: when BOTH day fields are
restricted, a time matches if EITHER matches (vixie semantics; Quartz
requires '?' on one side, which parses as unrestricted here).
"""

from __future__ import annotations

from datetime import datetime, timedelta

_DOW_NAMES = {
    "SUN": 0, "MON": 1, "TUE": 2, "WED": 3, "THU": 4, "FRI": 5, "SAT": 6,
}
_MON_NAMES = {
    "JAN": 1, "FEB": 2, "MAR": 3, "APR": 4, "MAY": 5, "JUN": 6,
    "JUL": 7, "AUG": 8, "SEP": 9, "OCT": 10, "NOV": 11, "DEC": 12,
}


def _atom(tok: str, lo: int, hi: int, names, quartz_dow: bool = False) -> int:
    t = tok.upper()
    if names and t in names:
        return names[t]
    v = int(tok)
    if lo == 0 and hi == 6:  # the day-of-week field
        if quartz_dow:
            # Quartz numeric convention: 1=SUN .. 7=SAT.
            if not 1 <= v <= 7:
                raise ValueError(f"Quartz day-of-week {tok!r} outside [1, 7]")
            return v - 1
        if v == 7:
            v = 0  # classic cron: 7 == Sunday too
    if not lo <= v <= hi:
        raise ValueError(f"cron field value {tok!r} outside [{lo}, {hi}]")
    return v


def _parse_field(field: str, lo: int, hi: int, names=None,
                 quartz_dow: bool = False) -> frozenset:
    out: set[int] = set()
    for part in field.split(","):
        step, has_step = 1, False
        if "/" in part:
            part, step_s = part.split("/", 1)
            step = int(step_s)
            has_step = True
            if step < 1:
                raise ValueError(f"cron step must be >= 1: {field!r}")
        if part in ("*", "?", ""):
            a, b = lo, hi
        elif "-" in part and not part.lstrip("-").isdigit():
            a_s, b_s = part.split("-", 1)
            a = _atom(a_s, lo, hi, names, quartz_dow)
            b = _atom(b_s, lo, hi, names, quartz_dow)
        else:
            a = _atom(part, lo, hi, names, quartz_dow)
            # Quartz: "n/step" means from n to max (even with step 1 —
            # '0/1' is the standard spelling of 'every'); bare "n" is
            # the single value.
            b = hi if has_step else a
        if b < a:  # wrap range (e.g. FRI-MON)
            out.update(range(a, hi + 1, step))
            out.update(range(lo, b + 1, step))
        else:
            out.update(range(a, b + 1, step))
    return frozenset(out)


class CronExpression:
    def __init__(self, expr: str):
        parts = expr.split()
        if len(parts) == 6:
            self.seconds = _parse_field(parts[0], 0, 59)
            rest = parts[1:]
            quartz = True
        elif len(parts) == 5:
            self.seconds = frozenset({0})
            rest = parts
            quartz = False
        else:
            raise ValueError(
                f"cron expression needs 5 or 6 fields, got {len(parts)}: {expr!r}"
            )
        self.minutes = _parse_field(rest[0], 0, 59)
        self.hours = _parse_field(rest[1], 0, 23)
        self.dom = _parse_field(rest[2], 1, 31)
        self.months = _parse_field(rest[3], 1, 12, _MON_NAMES)
        self.dow = _parse_field(rest[4], 0, 6, _DOW_NAMES, quartz_dow=quartz)
        # Classic cron OR rule: when BOTH day fields are restricted, a
        # time matches if either matches.
        self._dom_star = rest[2].split("/")[0] in ("*", "?")
        self._dow_star = rest[4].split("/")[0] in ("*", "?")
        self.expr = expr

    def _minute_matches(self, dt: datetime) -> bool:
        if not (
            dt.minute in self.minutes
            and dt.hour in self.hours
            and dt.month in self.months
        ):
            return False
        dom_ok = dt.day in self.dom
        dow_ok = (dt.weekday() + 1) % 7 in self.dow  # py Mon=0 → cron Sun=0
        if not self._dom_star and not self._dow_star:
            return dom_ok or dow_ok  # vixie OR semantics
        return dom_ok and dow_ok

    def next_after(self, ts: float) -> float:
        """Epoch seconds of the first fire time strictly after ``ts``."""
        base = datetime.fromtimestamp(ts)
        cur_min = base.replace(second=0, microsecond=0)
        if self._minute_matches(cur_min):
            for s in sorted(self.seconds):
                cand = cur_min + timedelta(seconds=s)
                if cand.timestamp() > ts:
                    return cand.timestamp()
        m = cur_min + timedelta(minutes=1)
        for _ in range(527040):  # bounded scan: 366 days of minutes
            if self._minute_matches(m):
                return (m + timedelta(seconds=min(self.seconds))).timestamp()
            m += timedelta(minutes=1)
        raise ValueError(f"no fire time within a year for {self.expr!r}")

    def __repr__(self) -> str:  # pragma: no cover — debugging aid
        return f"CronExpression({self.expr!r})"
