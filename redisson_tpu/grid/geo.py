"""RGeo — → org/redisson/RedissonGeo.java over GEOADD/GEODIST/GEOPOS/
GEOSEARCH/GEOHASH (SURVEY.md §2.3 geo row).

Members map to (longitude, latitude); distances use the haversine great-
circle formula on the same Earth radius Redis uses (6372797.560856 m), so
GEODIST parity holds to Redis's own precision class.
"""

from __future__ import annotations

import math
from typing import Any, Optional

from redisson_tpu.grid.base import GridObject

_EARTH_M = 6372797.560856  # Redis's earth radius (meters)
_UNITS = {"m": 1.0, "km": 1000.0, "mi": 1609.34, "ft": 0.3048}
_BASE32 = "0123456789bcdefghjkmnpqrstuvwxyz"


def _haversine_m(lon1, lat1, lon2, lat2) -> float:
    p1, p2 = math.radians(lat1), math.radians(lat2)
    dp = p2 - p1
    dl = math.radians(lon2 - lon1)
    a = math.sin(dp / 2) ** 2 + math.cos(p1) * math.cos(p2) * math.sin(dl / 2) ** 2
    return 2 * _EARTH_M * math.asin(math.sqrt(a))


def _geohash(lon: float, lat: float, precision: int = 11) -> str:
    """Standard base32 geohash (the GEOHASH reply shape)."""
    lat_r = [-90.0, 90.0]
    lon_r = [-180.0, 180.0]
    bits = []
    even = True
    while len(bits) < precision * 5:
        if even:
            mid = (lon_r[0] + lon_r[1]) / 2
            if lon >= mid:
                bits.append(1)
                lon_r[0] = mid
            else:
                bits.append(0)
                lon_r[1] = mid
        else:
            mid = (lat_r[0] + lat_r[1]) / 2
            if lat >= mid:
                bits.append(1)
                lat_r[0] = mid
            else:
                bits.append(0)
                lat_r[1] = mid
        even = not even
    out = []
    for i in range(0, len(bits), 5):
        idx = 0
        for b in bits[i : i + 5]:
            idx = (idx << 1) | b
        out.append(_BASE32[idx])
    return "".join(out)


class Geo(GridObject):
    KIND = "geo"

    @staticmethod
    def _new_value():
        return {}  # member bytes -> (lon, lat)

    # -- writes ------------------------------------------------------------

    def add(self, longitude: float, latitude: float, member: Any) -> int:
        """→ RGeo#add: 1 if the member was new."""
        if not (-180.0 <= longitude <= 180.0 and -85.05112878 <= latitude <= 85.05112878):
            raise ValueError("coordinates out of range (GEOADD limits)")
        with self._store.lock:
            e = self._entry()
            mb = self._enc(member)
            new = mb not in e.value
            e.value[mb] = (float(longitude), float(latitude))
            return int(new)

    def add_entries(self, *entries: tuple) -> int:
        """add((lon, lat, member), ...) — returns count of new members.
        All-or-nothing like GEOADD: every coordinate validates BEFORE any
        member is inserted (a mid-list range error used to leave a
        partial mutation)."""
        for lon, lat, _m in entries:
            if not (
                -180.0 <= lon <= 180.0 and -85.05112878 <= lat <= 85.05112878
            ):
                raise ValueError("coordinates out of range (GEOADD limits)")
        with self._store.lock:
            return sum(self.add(lon, lat, m) for lon, lat, m in entries)

    def remove(self, member: Any) -> bool:
        with self._store.lock:
            e = self._entry(create=False)
            return e is not None and e.value.pop(self._enc(member), None) is not None

    # -- reads -------------------------------------------------------------

    def pos(self, *members: Any) -> dict:
        """→ RGeo#pos (GEOPOS): member -> (lon, lat), absent skipped."""
        with self._store.lock:
            e = self._entry(create=False)
            if e is None:
                return {}
            out = {}
            for m in members:
                got = e.value.get(self._enc(m))
                if got is not None:
                    out[m] = got
            return out

    def dist(self, a: Any, b: Any, unit: str = "m") -> Optional[float]:
        """→ RGeo#dist (GEODIST)."""
        scale = _UNITS[unit]
        with self._store.lock:
            e = self._entry(create=False)
            if e is None:
                return None
            pa = e.value.get(self._enc(a))
            pb = e.value.get(self._enc(b))
            if pa is None or pb is None:
                return None
            return _haversine_m(*pa, *pb) / scale

    def hash(self, *members: Any) -> dict:
        """→ RGeo#hash (GEOHASH)."""
        with self._store.lock:
            e = self._entry(create=False)
            if e is None:
                return {}
            out = {}
            for m in members:
                got = e.value.get(self._enc(m))
                if got is not None:
                    out[m] = _geohash(*got)
            return out

    # -- search (GEOSEARCH) -------------------------------------------------

    def search_radius(self, longitude: float, latitude: float, radius: float,
                      unit: str = "m", count: Optional[int] = None,
                      with_dist: bool = False):
        """→ RGeo#search (BYRADIUS FROMLONLAT), nearest-first."""
        limit_m = radius * _UNITS[unit]
        with self._store.lock:
            e = self._entry(create=False)
            if e is None:
                return []
            hits = []
            for mb, (lon, lat) in e.value.items():
                d = _haversine_m(longitude, latitude, lon, lat)
                if d <= limit_m:
                    hits.append((d, mb))
        hits.sort(key=lambda t: t[0])
        if count is not None:
            hits = hits[:count]
        if with_dist:
            return [(self._dec(mb), d / _UNITS[unit]) for d, mb in hits]
        return [self._dec(mb) for _, mb in hits]

    def search_radius_from_member(self, member: Any, radius: float,
                                  unit: str = "m", count: Optional[int] = None,
                                  with_dist: bool = False):
        """→ RGeo#search (BYRADIUS FROMMEMBER)."""
        with self._store.lock:
            e = self._entry(create=False)
            origin = None if e is None else e.value.get(self._enc(member))
        if origin is None:
            raise ValueError(f"member {member!r} has no position")
        return self.search_radius(
            origin[0], origin[1], radius, unit, count, with_dist
        )

    def size(self) -> int:
        with self._store.lock:
            e = self._entry(create=False)
            return 0 if e is None else len(e.value)
