"""RGeo — → org/redisson/RedissonGeo.java over GEOADD/GEODIST/GEOPOS/
GEOSEARCH/GEOHASH (SURVEY.md §2.3 geo row).

Members map to (longitude, latitude); distances use the haversine great-
circle formula on the same Earth radius Redis uses (6372797.560856 m), so
GEODIST parity holds to Redis's own precision class.
"""

from __future__ import annotations

import math
from typing import Any, Optional

from redisson_tpu.grid.base import GridObject, journaled

_EARTH_M = 6372797.560856  # Redis's earth radius (meters)
_UNITS = {"m": 1.0, "km": 1000.0, "mi": 1609.34, "ft": 0.3048}
_BASE32 = "0123456789bcdefghjkmnpqrstuvwxyz"


def _haversine_m(lon1, lat1, lon2, lat2) -> float:
    p1, p2 = math.radians(lat1), math.radians(lat2)
    dp = p2 - p1
    dl = math.radians(lon2 - lon1)
    a = math.sin(dp / 2) ** 2 + math.cos(p1) * math.cos(p2) * math.sin(dl / 2) ** 2
    return 2 * _EARTH_M * math.asin(math.sqrt(a))


_LAT_MAX = 85.05112878  # Redis's geohash latitude clamp (web-mercator)


def _geohash_int52(lon: float, lat: float) -> int:
    """52-bit interleaved geohash cell id — the score Redis stores in
    the zset behind a geo key (26 lon bits + 26 lat bits, lon first),
    and the WITHHASH reply value.  Uses Redis's ±85.05112878° latitude
    range, NOT ±90 — the standard constants a client decodes with."""
    lat_off = (lat + _LAT_MAX) / (2 * _LAT_MAX)
    lon_off = (lon + 180.0) / 360.0
    ilat = min(int(lat_off * (1 << 26)), (1 << 26) - 1)
    ilon = min(int(lon_off * (1 << 26)), (1 << 26) - 1)
    out = 0
    for i in range(26):
        out |= ((ilon >> i) & 1) << (2 * i + 1)
        out |= ((ilat >> i) & 1) << (2 * i)
    return out


def _geohash_int52_decode(cell: int) -> tuple:
    """Center coordinates of a 52-bit cell (inverse of _geohash_int52)
    — the ≤2.7e-6° round-trip error is Redis's own precision class
    (GEOPOS there also returns cell centers, not the added values)."""
    ilon = ilat = 0
    for i in range(26):
        ilon |= ((cell >> (2 * i + 1)) & 1) << i
        ilat |= ((cell >> (2 * i)) & 1) << i
    lon = (ilon + 0.5) / (1 << 26) * 360.0 - 180.0
    lat = (ilat + 0.5) / (1 << 26) * (2 * _LAT_MAX) - _LAT_MAX
    return lon, lat


def _geohash(lon: float, lat: float, precision: int = 11) -> str:
    """Standard base32 geohash (the GEOHASH reply shape)."""
    lat_r = [-90.0, 90.0]
    lon_r = [-180.0, 180.0]
    bits = []
    even = True
    while len(bits) < precision * 5:
        if even:
            mid = (lon_r[0] + lon_r[1]) / 2
            if lon >= mid:
                bits.append(1)
                lon_r[0] = mid
            else:
                bits.append(0)
                lon_r[1] = mid
        else:
            mid = (lat_r[0] + lat_r[1]) / 2
            if lat >= mid:
                bits.append(1)
                lat_r[0] = mid
            else:
                bits.append(0)
                lat_r[1] = mid
        even = not even
    out = []
    for i in range(0, len(bits), 5):
        idx = 0
        for b in bits[i : i + 5]:
            idx = (idx << 1) | b
        out.append(_BASE32[idx])
    return "".join(out)


@journaled("add", "add_entries", "remove")
class Geo(GridObject):
    """A geo key IS a zset whose scores are 52-bit geohash cell ids —
    the Redis representation, verbatim: TYPE reports zset, ZSCORE/ZRANGE
    work on geo keys, GEOSEARCHSTORE destinations are readable by geo
    commands, and positions round-trip through the cell center (the same
    ≤1 m precision class as Redis GEOPOS)."""

    KIND = "zset"

    @staticmethod
    def _new_value():
        return {}  # member bytes -> float(52-bit cell id)

    @staticmethod
    def _coords(score: float) -> tuple:
        return _geohash_int52_decode(int(score))

    # -- writes ------------------------------------------------------------

    def add(self, longitude: float, latitude: float, member: Any) -> int:
        """→ RGeo#add: 1 if the member was new."""
        if not (-180.0 <= longitude <= 180.0 and -85.05112878 <= latitude <= 85.05112878):
            raise ValueError("coordinates out of range (GEOADD limits)")
        with self._store.lock:
            e = self._entry()
            mb = self._enc(member)
            new = mb not in e.value
            e.value[mb] = float(_geohash_int52(longitude, latitude))
            self._nc_bump()  # GEOPOS/GEODIST cached scalars retire
            return int(new)

    def add_entries(self, *entries: tuple) -> int:
        """add((lon, lat, member), ...) — returns count of new members.
        All-or-nothing like GEOADD: every coordinate validates BEFORE any
        member is inserted (a mid-list range error used to leave a
        partial mutation)."""
        for lon, lat, _m in entries:
            if not (
                -180.0 <= lon <= 180.0 and -85.05112878 <= lat <= 85.05112878
            ):
                raise ValueError("coordinates out of range (GEOADD limits)")
        with self._store.lock:
            return sum(self.add(lon, lat, m) for lon, lat, m in entries)

    def remove(self, member: Any) -> bool:
        with self._store.lock:
            e = self._entry(create=False)
            gone = (
                e is not None
                and e.value.pop(self._enc(member), None) is not None
            )
            if gone:
                self._nc_bump()
            return gone

    # -- reads -------------------------------------------------------------

    def pos(self, *members: Any) -> dict:
        """→ RGeo#pos (GEOPOS): member -> (lon, lat), absent skipped.
        Rides the engine near cache keyed by the exact member set
        (ISSUE 14 satellite) — repeated position polls of the same
        members skip the grid lock."""

        def compute():
            with self._store.lock:
                e = self._entry(create=False)
                if e is None:
                    return {}
                out = {}
                for m in members:
                    got = e.value.get(self._enc(m))
                    if got is not None:
                        out[m] = self._coords(got)
                return out

        key = ("pos", *(self._enc(m) for m in members))
        # Copy on the way out: the cached dict must never be mutated
        # by a caller into a poisoned hit.
        return dict(self._nc_scalar("geo", key, compute))

    def dist(self, a: Any, b: Any, unit: str = "m") -> Optional[float]:
        """→ RGeo#dist (GEODIST).  Near-cached like pos()."""
        scale = _UNITS[unit]

        def compute():
            with self._store.lock:
                e = self._entry(create=False)
                if e is None:
                    return None
                pa = e.value.get(self._enc(a))
                pb = e.value.get(self._enc(b))
                if pa is None or pb is None:
                    return None
                return (
                    _haversine_m(*self._coords(pa), *self._coords(pb))
                    / scale
                )

        key = ("dist", self._enc(a), self._enc(b), unit)
        return self._nc_scalar("geo", key, compute)

    def hash(self, *members: Any) -> dict:
        """→ RGeo#hash (GEOHASH)."""
        with self._store.lock:
            e = self._entry(create=False)
            if e is None:
                return {}
            out = {}
            for m in members:
                got = e.value.get(self._enc(m))
                if got is not None:
                    out[m] = _geohash(*self._coords(got))
            return out

    # -- search (GEOSEARCH) -------------------------------------------------

    def search_radius(self, longitude: float, latitude: float, radius: float,
                      unit: str = "m", count: Optional[int] = None,
                      with_dist: bool = False):
        """→ RGeo#search (BYRADIUS FROMLONLAT), nearest-first."""
        limit_m = radius * _UNITS[unit]
        with self._store.lock:
            e = self._entry(create=False)
            if e is None:
                return []
            hits = []
            for mb, score in e.value.items():
                lon, lat = self._coords(score)
                d = _haversine_m(longitude, latitude, lon, lat)
                if d <= limit_m:
                    hits.append((d, mb))
        hits.sort(key=lambda t: t[0])
        if count is not None:
            hits = hits[:count]
        if with_dist:
            return [(self._dec(mb), d / _UNITS[unit]) for d, mb in hits]
        return [self._dec(mb) for _, mb in hits]

    def search_radius_from_member(self, member: Any, radius: float,
                                  unit: str = "m", count: Optional[int] = None,
                                  with_dist: bool = False):
        """→ RGeo#search (BYRADIUS FROMMEMBER)."""
        with self._store.lock:
            e = self._entry(create=False)
            origin = None if e is None else e.value.get(self._enc(member))
        if origin is None:
            raise ValueError(f"member {member!r} has no position")
        lon0, lat0 = self._coords(origin)
        return self.search_radius(lon0, lat0, radius, unit, count, with_dist)

    def search(self, *, member: Any = None, longitude: Optional[float] = None,
               latitude: Optional[float] = None, radius: Optional[float] = None,
               width: Optional[float] = None, height: Optional[float] = None,
               unit: str = "m", count: Optional[int] = None,
               count_any: bool = False, order: Optional[str] = None,
               with_coord: bool = False, with_dist: bool = False,
               with_hash: bool = False):
        """→ RGeo#search(GeoSearchArgs) / GEOSEARCH: origin is FROMMEMBER
        (``member``) or FROMLONLAT (``longitude``/``latitude``); shape is
        BYRADIUS (``radius``) or BYBOX (``width``×``height``, box
        half-extents measured along the lon/lat axes through the center,
        the Redis box test); ``order`` is "asc"/"desc"/None, ``count``
        with ``count_any`` stops at the first COUNT matches unsorted
        (COUNT n ANY).  Plain member list without with-flags; with any
        WITH* flag, a list of dicts {member, dist?, coord?, hash?}.
        ``dist`` is in ``unit`` like GEOSEARCH replies."""
        scale = _UNITS[unit]
        if (radius is None) == (width is None or height is None):
            raise ValueError("search needs exactly one of radius or width+height")
        with self._store.lock:
            e = self._entry(create=False)
            if e is None:
                return []
            if member is not None:
                origin = e.value.get(self._enc(member))
                if origin is None:
                    raise ValueError(f"member {member!r} has no position")
                lon_c, lat_c = self._coords(origin)
            else:
                if longitude is None or latitude is None:
                    raise ValueError("search needs a member or lon/lat origin")
                lon_c, lat_c = float(longitude), float(latitude)
            hits = []
            for mb, score in e.value.items():
                lon, lat = self._coords(score)
                d = _haversine_m(lon_c, lat_c, lon, lat)
                if radius is not None:
                    if d > radius * scale:
                        continue
                else:
                    # BYBOX: per-axis great-circle distances from the
                    # center must fit the half-extents (Redis's box test).
                    dx = _haversine_m(lon_c, lat_c, lon, lat_c)
                    dy = _haversine_m(lon_c, lat_c, lon_c, lat)
                    if dx > width * scale / 2 or dy > height * scale / 2:
                        continue
                hits.append((d, mb, lon, lat))
                if count_any and count is not None and len(hits) >= count:
                    break  # COUNT n ANY: first n matches, no sort
        if order is not None or (count is not None and not count_any):
            # A plain COUNT (no ANY) implies nearest-first, like Redis.
            hits.sort(key=lambda t: t[0], reverse=(order == "desc"))
        if count is not None:
            hits = hits[:count]
        if not (with_coord or with_dist or with_hash):
            return [self._dec(mb) for _, mb, _, _ in hits]
        out = []
        for d, mb, lon, lat in hits:
            row = {"member": self._dec(mb)}
            if with_dist:
                row["dist"] = d / scale
            if with_coord:
                row["coord"] = (lon, lat)
            if with_hash:
                row["hash"] = _geohash_int52(lon, lat)
            out.append(row)
        return out

    def search_and_store(self, dest_name: str, *, store_dist: bool = False,
                         unit: str = "m", **kw) -> int:
        """→ GEOSEARCHSTORE: run :meth:`search` and store the result into
        the ScoredSortedSet ``dest_name`` — score is the 52-bit geohash
        cell id (the Redis zset-backed geo encoding), or the distance in
        ``unit`` with ``store_dist`` (STOREDIST).  Replaces the
        destination like Redis does; returns the stored count."""
        from redisson_tpu.grid.collections import ScoredSortedSet

        dest = ScoredSortedSet(dest_name, self._client)
        # Members must land in the destination under the SAME byte
        # encoding this geo set uses (the RESP front door runs raw-codec
        # handles; re-encoding through the client default would store
        # different bytes than ZRANGE returns).
        dest._enc = self._enc
        dest._dec = self._dec
        with self._store.lock:  # atomic search+replace (RLock re-entry)
            rows = self.search(
                unit=unit, with_dist=True, with_coord=True, with_hash=True,
                **kw
            )
            dest.delete()
            for row in rows:
                score = row["dist"] if store_dist else float(row["hash"])
                dest.add(score, row["member"])
            return len(rows)

    def size(self) -> int:
        with self._store.lock:
            e = self._entry(create=False)
            return 0 if e is None else len(e.value)
