"""JCache — → org.redisson.jcache.* (JSR-107 javax.cache.Cache over a
Redisson map, SURVEY.md §2.3 caching-standards row).

JSR-107 contracts over the MapCache backing: ``put`` returns nothing,
``remove`` returns whether a mapping was removed, ``get_and_put``/
``get_and_remove`` return the previous value, iteration yields entries.
A per-cache default expiry policy (creation TTL) stands in for the JSR
ExpiryPolicy; per-entry TTL rides the MapCache machinery.
"""

from __future__ import annotations

from typing import Any, Iterable, Optional

from redisson_tpu.grid.maps import MapCache


class JCache(MapCache):
    KIND = "mapcache"  # shares MapCache's keyspace semantics

    def __init__(self, name: str, client, *,
                 default_ttl_seconds: Optional[float] = None):
        super().__init__(name, client)
        self._default_ttl = default_ttl_seconds

    # -- javax.cache.Cache surface -----------------------------------------

    def get(self, key: Any) -> Any:
        return super().get(key)

    def put(self, key: Any, value: Any) -> None:
        """JSR-107 put returns void."""
        super().fast_put(key, value, ttl_seconds=self._default_ttl)

    def get_and_put(self, key: Any, value: Any) -> Any:
        return super().put(key, value, ttl_seconds=self._default_ttl)

    def put_if_absent(self, key: Any, value: Any) -> bool:
        """JSR-107 contract: True iff the value was set."""
        return (
            super().put_if_absent(key, value, ttl_seconds=self._default_ttl)
            is None
        )

    def get_all(self, keys: Iterable[Any]) -> dict:
        return super().get_all(keys)

    def contains_key(self, key: Any) -> bool:
        return super().contains_key(key)

    def remove(self, key: Any, old_value: Any = None) -> bool:
        """JSR-107: True iff a mapping was removed (2-arg form compares)."""
        if old_value is None:
            return super().fast_remove(key) > 0
        return bool(super().remove(key, old_value))

    def get_and_remove(self, key: Any) -> Any:
        with self._store.lock:
            prev = super().get(key)
            super().fast_remove(key)
            return prev

    def replace(self, key: Any, value: Any) -> bool:
        """JSR-107: True iff the key existed."""
        with self._store.lock:
            if not super().contains_key(key):
                return False
            super().fast_put(key, value, ttl_seconds=self._default_ttl)
            return True

    def remove_all(self, keys: Optional[Iterable[Any]] = None) -> None:
        if keys is None:
            super().clear()
        else:
            super().fast_remove(*list(keys))

    def clear(self) -> None:
        super().clear()

    def __iter__(self):
        return iter(super().entry_set())

    def close(self) -> None:
        """JSR-107 lifecycle no-op (in-process cache)."""

    def is_closed(self) -> bool:
        return False


class CacheManager:
    """→ javax.cache.CacheManager via Redisson's JCacheManager."""

    def __init__(self, client):
        self._client = client
        self._caches: dict[str, JCache] = {}

    def create_cache(self, name: str, **config) -> JCache:
        cache = JCache(name, self._client, **config)
        self._caches[name] = cache
        return cache

    def get_cache(self, name: str) -> Optional[JCache]:
        """JSR-107 getCache: None when the cache does not exist (silently
        creating one dropped the original configuration — a destroyed
        30s-TTL cache came back immortal)."""
        return self._caches.get(name)

    def get_or_create_cache(self, name: str, **config) -> JCache:
        if name in self._caches:
            return self._caches[name]
        return self.create_cache(name, **config)

    def destroy_cache(self, name: str) -> None:
        cache = self._caches.pop(name, None)
        if cache is not None:
            cache.clear()

    def get_cache_names(self) -> list:
        return list(self._caches)
