"""JCache — → org.redisson.jcache.* (JSR-107 javax.cache.Cache over a
Redisson map, SURVEY.md §2.3 caching-standards row).

JSR-107 contracts over the MapCache backing:

- ``put`` returns nothing, ``remove`` returns whether a mapping was
  removed, ``get_and_put``/``get_and_remove`` return the previous value,
  iteration yields entries;
- **ExpiryPolicy** (→ javax.cache.expiry): creation/access/update TTLs —
  Created/Accessed/Modified/Eternal policies are the three constructor
  knobs (access TTL rides MapCache's max-idle machinery);
- **entry listeners** (→ javax.cache.event.CacheEntryListener):
  created/updated/removed ride the map event channel
  (grid/maps.py Map.add_listener); *expired* events fire from the lazy
  expiry reaper (_MapValue.on_expire) and the grid sweeper;
- **CacheLoader / CacheWriter** (→ javax.cache.integration):
  read-through loads on miss, write-through mirrors every put/remove to
  the writer BEFORE the cache mutates (the JSR ordering — a failing
  writer must leave the cache unchanged).  Locking policy: UNCONDITIONAL
  ops (put/get_and_put/remove/remove_all(keys)) call the writer OUTSIDE
  the store lock, so slow external I/O never stalls unrelated grid ops;
  CONDITIONAL ops (replace, put_if_absent, remove(k, old), clear-form
  remove_all) call it UNDER the lock — exactly-once writer semantics
  for compare-guarded mutations outweigh lock-freedom on these rarer
  paths;
- **statistics** (→ javax.cache.management.CacheStatisticsMXBean):
  hits/misses/gets/puts/removals + hit percentage, per cache.
"""

from __future__ import annotations

import threading
from typing import Any, Callable, Iterable, Optional

from redisson_tpu.analysis import witness as _witness
from redisson_tpu.grid.maps import Map, MapCache

_MISSING = object()


class ExpiryPolicy:
    """→ javax.cache.expiry.ExpiryPolicy: per-event TTLs in seconds.

    - ``CreatedExpiryPolicy``  → ``ExpiryPolicy(creation_ttl=t)``
    - ``AccessedExpiryPolicy`` → ``ExpiryPolicy(access_ttl=t)``
    - ``ModifiedExpiryPolicy`` → ``ExpiryPolicy(update_ttl=t)``
    - ``EternalExpiryPolicy``  → ``ExpiryPolicy()``
    """

    def __init__(self, creation_ttl: Optional[float] = None,
                 access_ttl: Optional[float] = None,
                 update_ttl: Optional[float] = None):
        self.creation_ttl = creation_ttl
        self.access_ttl = access_ttl
        self.update_ttl = update_ttl


class CacheStatistics:
    """→ javax.cache.management.CacheStatisticsMXBean."""

    def __init__(self):
        self._lock = _witness.named(threading.Lock(), "grid.jcache.stats")
        self.reset()

    def reset(self) -> None:
        self.hits = 0
        self.misses = 0
        self.puts = 0
        self.removals = 0

    @property
    def gets(self) -> int:
        return self.hits + self.misses

    @property
    def hit_percentage(self) -> float:
        g = self.gets
        return 0.0 if g == 0 else 100.0 * self.hits / g

    def _hit(self):
        with self._lock:
            self.hits += 1

    def _miss(self):
        with self._lock:
            self.misses += 1

    def _put(self, n=1):
        with self._lock:
            self.puts += n

    def _removal(self, n=1):
        with self._lock:
            self.removals += n


class JCache(MapCache):
    KIND = "mapcache"  # shares MapCache's keyspace semantics

    def __init__(self, name: str, client, *,
                 default_ttl_seconds: Optional[float] = None,
                 expiry_policy: Optional[ExpiryPolicy] = None,
                 cache_loader: Optional[Callable[[Any], Any]] = None,
                 cache_writer: Optional[Any] = None,
                 read_through: bool = False,
                 write_through: bool = False,
                 statistics_enabled: bool = False):
        super().__init__(name, client)
        if expiry_policy is None:
            expiry_policy = ExpiryPolicy(creation_ttl=default_ttl_seconds)
        self._expiry = expiry_policy
        self._loader = cache_loader
        self._writer = cache_writer
        self._read_through = read_through and cache_loader is not None
        self._write_through = write_through and cache_writer is not None
        self.statistics = CacheStatistics() if statistics_enabled else None

    # -- expiry plumbing ----------------------------------------------------

    def _ttl_kwargs(self) -> dict:
        return {
            "ttl_seconds": self._expiry.creation_ttl,
            "max_idle_seconds": self._expiry.access_ttl,
        }

    def _entry(self, create: bool = True):
        e = super()._entry(create)
        if e is not None and e.value.on_expire is None:
            # Surface lazy-expiry reaps as JSR Expired events.  The
            # callback publishes to the map event channel (async
            # delivery pool), so firing under the store lock is safe.
            emit = self._emit
            dec_key = self._dec_key
            dec = self._dec

            def on_expire(kb, vb):
                try:
                    emit("expired", dec_key(kb), dec(vb))
                except Exception:
                    pass  # listener plumbing must never break expiry

            e.value.on_expire = on_expire
        return e

    # -- javax.cache.Cache surface -----------------------------------------

    def get(self, key: Any) -> Any:
        v = super().get(key)
        found = v is not None  # stats: a read-through LOAD is a miss
        if not found and self._read_through:
            v = self._loader(key)
            if v is not None:
                # Loaded entries enter WITHOUT the writer (JSR: loads
                # are not writes) under the creation expiry.
                super().fast_put(key, v, **self._ttl_kwargs())
        if self.statistics is not None:
            (self.statistics._hit if found else self.statistics._miss)()
        return v

    def put(self, key: Any, value: Any) -> None:
        """JSR-107 put returns void; write-through runs FIRST (a failing
        writer leaves the cache unchanged).  An update of an existing
        key re-arms under ``update_ttl`` (ModifiedExpiryPolicy),
        creation under ``creation_ttl``."""
        if self._write_through:
            self._writer.write(key, value)
        with self._store.lock:
            kw = self._ttl_kwargs()
            if (
                self._expiry.update_ttl is not None
                and super().contains_key(key)
            ):
                kw["ttl_seconds"] = self._expiry.update_ttl
            super().fast_put(key, value, **kw)
        if self.statistics is not None:
            self.statistics._put()

    def put_all(self, mapping: dict) -> None:
        for k, v in mapping.items():
            self.put(k, v)

    def get_and_put(self, key: Any, value: Any) -> Any:
        if self._write_through:
            self._writer.write(key, value)
        with self._store.lock:
            # STATIC Map.get: MapCache.put's `self.get` would dispatch
            # to JCache.get — firing the CacheLoader (JSR forbids loads
            # on getAndPut), returning the loaded value instead of None
            # for absent keys, and counting phantom statistics.
            prev = Map.get(self, key)
            kw = self._ttl_kwargs()
            if self._expiry.update_ttl is not None and prev is not None:
                kw["ttl_seconds"] = self._expiry.update_ttl
            super().fast_put(key, value, **kw)
        if self.statistics is not None:
            self.statistics._put()
        return prev

    def put_if_absent(self, key: Any, value: Any) -> bool:
        """JSR-107 contract: True iff the value was set."""
        with self._store.lock:
            if super().contains_key(key):
                return False
            self.put(key, value)
            return True

    def get_all(self, keys: Iterable[Any]) -> dict:
        keys = list(keys)
        # STATIC Map.get per key: Map.get_all's `self.get` would
        # dispatch to JCache.get, double-counting statistics and running
        # the loader under the store lock.
        out = {}
        with self._store.lock:
            for k in keys:
                v = Map.get(self, k)
                if v is not None:
                    out[k] = v
        cached = set(out)  # stats: read-through loads count as misses
        if self._read_through:
            for k in keys:
                if k not in out:
                    v = self._loader(k)  # outside the lock (slow I/O)
                    if v is not None:
                        super().fast_put(k, v, **self._ttl_kwargs())
                        out[k] = v
        if self.statistics is not None:
            for k in keys:
                (self.statistics._hit if k in cached
                 else self.statistics._miss)()
        return out

    def contains_key(self, key: Any) -> bool:
        return super().contains_key(key)

    def access(self, key: Any) -> Any:
        """Value read that refreshes the access-TTL clock (JSR accessed-
        expiry); plain ``get`` already touches via MapCache."""
        return self.get(key)

    def remove(self, key: Any, old_value: Any = None) -> bool:
        if old_value is None:
            if self._write_through:
                self._writer.delete(key)
            # Map.remove (not fast_remove): the removed EVENT must carry
            # the old value, per the JSR CacheEntryRemovedListener shape.
            removed = super().remove(key) is not None
        else:
            # Conditional remove: the writer fires ONLY when the compare
            # succeeds (a failed conditional remove must not touch the
            # external store), atomically under the store lock — see the
            # conditional-op locking policy in the class docstring.
            with self._store.lock:
                if super().get(key) != old_value:
                    return False
                if self._write_through:
                    self._writer.delete(key)
                removed = bool(super().remove(key, old_value))
        if removed and self.statistics is not None:
            self.statistics._removal()
        return removed

    def get_and_remove(self, key: Any) -> Any:
        if self._write_through:
            self._writer.delete(key)
        with self._store.lock:
            # Map.remove (static): the removed EVENT must carry the old
            # value, like JCache.remove — fast_remove would emit None.
            prev = Map.remove(self, key)
        if prev is not None and self.statistics is not None:
            self.statistics._removal()
        return prev

    def replace(self, key: Any, *vals) -> bool:
        """JSR-107 replace: ``replace(k, v)`` = True iff the key
        existed; ``replace(k, old, new)`` = compare-and-replace (the
        three-arg Cache contract — shadowing Map.replace with only the
        two-arg form broke callers written against either surface)."""
        if len(vals) == 1:
            old, value = _MISSING, vals[0]
        elif len(vals) == 2:
            old, value = vals
        else:
            raise TypeError("replace(key, value) or replace(key, old, new)")
        with self._store.lock:
            if not super().contains_key(key):
                return False
            if old is not _MISSING and Map.get(self, key) != old:
                return False
            kw = self._ttl_kwargs()
            if self._expiry.update_ttl is not None:
                kw["ttl_seconds"] = self._expiry.update_ttl
            if self._write_through:
                self._writer.write(key, value)
            super().fast_put(key, value, **kw)
            if self.statistics is not None:
                self.statistics._put()
            return True

    def remove_all(self, keys: Optional[Iterable[Any]] = None) -> None:
        if keys is None:
            # Snapshot + writer deletes + clear under ONE lock hold: a
            # concurrent put between the snapshot and the clear would
            # otherwise vanish from the cache while the external store
            # kept it (see the conditional-op locking policy).
            with self._store.lock:
                entries = self.entry_set()
                if self._write_through:
                    for k, _ in entries:
                        self._writer.delete(k)
                n = len(entries)
                super().clear()
        else:
            keys = list(keys)
            if self._write_through:
                for k in keys:
                    self._writer.delete(k)
            n = super().fast_remove(*keys)
        if self.statistics is not None:
            self.statistics._removal(n)

    def clear(self) -> None:
        """JSR clear: NO writer interaction and no removal stats (the
        spec distinguishes clear from removeAll)."""
        super().clear()

    def load_all(self, keys: Iterable[Any], replace_existing: bool = False) -> int:
        """→ Cache#loadAll (synchronous form): returns loaded count."""
        if self._loader is None:
            return 0
        n = 0
        for k in keys:
            if not replace_existing and super().contains_key(k):
                continue
            v = self._loader(k)
            if v is not None:
                super().fast_put(k, v, **self._ttl_kwargs())
                n += 1
        return n

    # -- listeners (→ javax.cache.event.CacheEntryListener) ----------------

    EVENT_CREATED = "created"
    EVENT_UPDATED = "updated"
    EVENT_REMOVED = "removed"
    EVENT_EXPIRED = "expired"

    def register_cache_entry_listener(self, listener,
                                      event: Optional[str] = None) -> int:
        """``listener(event, key, value)`` with event one of
        created/updated/removed/expired (None = all); returns an id for
        deregistration.  Rides the map event channel, so every handle of
        this cache sees every mutation."""
        return super().add_listener(listener, event)

    def deregister_cache_entry_listener(self, listener_id: int) -> None:
        super().remove_listener(listener_id)

    def __iter__(self):
        return iter(super().entry_set())

    def close(self) -> None:
        """JSR-107 lifecycle no-op (in-process cache)."""

    def is_closed(self) -> bool:
        return False


class CacheManager:
    """→ javax.cache.CacheManager via Redisson's JCacheManager."""

    def __init__(self, client):
        self._client = client
        self._caches: dict[str, JCache] = {}

    def create_cache(self, name: str, **config) -> JCache:
        cache = JCache(name, self._client, **config)
        self._caches[name] = cache
        return cache

    def get_cache(self, name: str) -> Optional[JCache]:
        """JSR-107 getCache: None when the cache does not exist (silently
        creating one dropped the original configuration — a destroyed
        30s-TTL cache came back immortal)."""
        return self._caches.get(name)

    def get_or_create_cache(self, name: str, **config) -> JCache:
        if name in self._caches:
            return self._caches[name]
        return self.create_cache(name, **config)

    def destroy_cache(self, name: str) -> None:
        cache = self._caches.pop(name, None)
        if cache is not None:
            cache.clear()

    def get_cache_names(self) -> list:
        return list(self._caches)
