"""RKeys — → org/redisson/RedissonKeys.java: keyspace administration
spanning BOTH backends (the host data grid and the sketch engine's tenant
registry), since a Redisson user sees one keyspace.
"""

from __future__ import annotations

import fnmatch
import random
from typing import Optional


def _chunked_snapshot_iter(fetch, count: int):
    """Shared SCAN-cursor shape: the snapshot is taken EAGERLY (at
    iterator creation, so the stated every-key-present-now guarantee
    holds even if consumption is deferred); iteration is a plain walk —
    ``count`` is accepted for SCAN-API parity but has no semantic effect
    on an in-process snapshot."""
    return iter(fetch())


class Keys:
    def __init__(self, client):
        self._client = client
        self._grid = client._grid
        self._engine = client._engine

    def get_keys(self, pattern: Optional[str] = None) -> list[str]:
        """→ RKeys#getKeys / getKeysByPattern (SCAN MATCH)."""
        names = self._grid.names(pattern)
        sketch = self._engine.names()
        if pattern is not None:
            sketch = [n for n in sketch if fnmatch.fnmatchcase(n, pattern)]
        return names + sketch

    def scan_iterator(self, pattern: Optional[str] = None, count: int = 10):
        """→ RKeys#getKeysByPattern's SCAN-cursor idiom (one O(N)
        keyspace snapshot).  Guarantees (stronger than Redis SCAN): every
        key present at iterator creation is yielded exactly once; keys
        created after creation do not appear."""
        return _chunked_snapshot_iter(lambda: self.get_keys(pattern), count)

    def count(self) -> int:
        """→ RKeys#count (DBSIZE)."""
        return len(self.get_keys())

    def count_exists(self, *names: str) -> int:
        """→ RKeys#countExists (EXISTS key [key ...])."""
        return sum(
            1
            for n in names
            if self._grid.exists(n) or self._engine.exists(n)
        )

    def delete(self, *names: str) -> int:
        """→ RKeys#delete: number of keys actually removed."""
        n = 0
        for name in names:
            if self._grid.delete(name):
                n += 1
            elif self._engine.exists(name) and self._engine.delete(name):
                n += 1
        return n

    def delete_by_pattern(self, pattern: str) -> int:
        """→ RKeys#deleteByPattern."""
        return self.delete(*self.get_keys(pattern))

    def flushall(self) -> None:
        """→ RKeys#flushall: every key in both backends."""
        self.delete(*self.get_keys())

    flushdb = flushall  # single logical database

    def random_key(self) -> Optional[str]:
        keys = self.get_keys()
        return random.choice(keys) if keys else None

    def rename(self, old: str, new: str) -> None:
        if self._grid.exists(old):
            self._grid.rename(old, new)
        elif self._engine.exists(old):
            self._engine.rename(old, new)
        else:
            raise RuntimeError(f"key {old!r} does not exist")

    def expire(self, name: str, ttl_seconds: float) -> bool:
        if self._grid.exists(name):
            return self._grid.expire(name, ttl_seconds)
        expire = getattr(self._engine, "expire", None)
        return expire(name, ttl_seconds) if expire else False

    def remain_time_to_live(self, name: str) -> int:
        if self._grid.exists(name):
            return self._grid.remain_ttl_ms(name)
        remain = getattr(self._engine, "remain_ttl_ms", None)
        if remain is not None:
            return remain(name)
        return -1 if self._engine.exists(name) else -2

    # camelCase parity
    getKeys = get_keys
    getKeysByPattern = get_keys
    countExists = count_exists
    deleteByPattern = delete_by_pattern
    randomKey = random_key
