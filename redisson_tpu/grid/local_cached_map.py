"""RLocalCachedMap — → org/redisson/RedissonLocalCachedMap.java +
org/redisson/cache/ (LocalCacheView, LRU caches, invalidation-topic sync
strategies).

The reference keeps a near cache in each client and invalidates peers
through a topic; writes publish the touched key hashes.  Here the shared
state is the grid Map entry and invalidation rides the client's TopicBus
on the map's own ``{name}:topic`` channel.

The near cache itself is ONE ``ShardedLRUStore`` per CLIENT, shared by
every LocalCachedMap handle and tenant-keyed by map name (ISSUE 6
satellite, the ROADMAP near-cache-reach item): two handles to one map now
share hits — a key warmed through handle A answers handle B's ``get``
from host memory — instead of each handle refetching into a private
OrderedDict.  Coherence across handles is the sketch near cache's
epoch idiom (cache/nearcache.py): a per-map GENERATION bumps on every
write and every processed invalidation, and a reader installs its
backing-map result only if the generation it sampled before the read is
still current — a racing write retires the in-flight install instead of
letting it cache a stale value.

Riding the shared store keeps what PR 4 bought: per-tenant byte quotas
(``cache_max_bytes``) on top of the entry bound, and
hit/miss/eviction stats (``cache_stats()``) for free.

Sync strategies (→ SyncStrategy): INVALIDATE (default) clears peer cache
entries on write; UPDATE pushes the new value; NONE publishes nothing.
"""

from __future__ import annotations

import threading
from typing import Any, Optional

from redisson_tpu.analysis import witness as _witness
from redisson_tpu.cache import MISS, ShardedLRUStore
from redisson_tpu.grid.maps import Map, _MISSING

INVALIDATE = "invalidate"
UPDATE = "update"
NONE = "none"

_HUB_LOCK = _witness.named(threading.Lock(), "grid.localmap.hub")


def _approx_nbytes(kb: bytes, value: Any) -> int:
    """Caller-estimated entry size for the byte quota: key bytes + a flat
    per-entry overhead + the value's obvious payload (sized types only —
    arbitrary objects count a constant; the bound is a budget, not an
    audit)."""
    if isinstance(value, (bytes, bytearray, str)):
        vb = len(value)
    else:
        vb = 64
    return 96 + len(kb) + vb


class _MapCacheHub:
    """Per-client shared map near cache: the store plus per-map-name
    generation counters (the install guard).  Budget grows to the largest
    any handle asked for; per-map byte/entry quotas are tenant limits."""

    def __init__(self):
        # Few shards: each map's traffic is a handful of user threads
        # plus the TopicBus pool; tenant quotas do the real bounding.
        self.store = ShardedLRUStore(max_bytes=64 << 20, nshards=4)
        self.lock = _witness.named(threading.Lock(), "grid.localmap.gens")
        self.gens: dict = {}
        # Generation FLOOR (the SketchNearCache._prune_locked idiom):
        # ``gens`` is folded back toward the floor once it outgrows the
        # threshold, keeping name-churn workloads (TTL'd per-session
        # maps) from leaking one dict entry per map name forever.  A
        # pruned name's in-flight reads can never install (the floor
        # rises past its last generation, so their sampled gen no longer
        # matches); a pruned name that returns resumes ABOVE it.
        self.floor = 0
        self._prune_at = 4096

    def gen(self, name) -> int:
        g = self.gens.get(name)  # dict probe: atomic under the GIL
        return self.floor if g is None else g

    def bump(self, name) -> None:
        with self.lock:
            self.gens[name] = self.gen(name) + 1
            if len(self.gens) > self._prune_at:
                self._prune_locked()

    def _prune_locked(self) -> None:
        floor, keep = self.floor, {}
        for n, g in self.gens.items():
            if self.store.tenant_entry_count(n):
                keep[n] = g
            else:
                floor = max(floor, g)
        self.floor = floor + 1
        self.gens = keep
        self._prune_at = max(4096, 2 * len(keep))

    def install_if(self, name, key, value, nbytes, gen) -> bool:
        """Install a read-through result only if no write/invalidation
        landed since the reader sampled ``gen`` — the sampled-generation
        idiom shared with SketchNearCache.install."""
        with self.lock:
            if self.gen(name) != gen:
                return False
            return self.store.put(name, key, value, nbytes)

    def ensure_budget(self, max_bytes: int) -> None:
        with self.lock:
            if max_bytes > self.store.max_bytes:
                self.store.resize(max_bytes=max_bytes)


def _hub_for(client) -> _MapCacheHub:
    hub = getattr(client, "_map_cache_hub", None)
    if hub is None:
        with _HUB_LOCK:
            hub = getattr(client, "_map_cache_hub", None)
            if hub is None:
                hub = _MapCacheHub()
                client._map_cache_hub = hub
    return hub


class LocalCachedMap(Map):
    KIND = "map"  # shares the backing Map keyspace entry

    def __init__(self, name, client, *, cache_size: int = 4096,
                 cache_max_bytes: int = 64 << 20,
                 sync_strategy: str = INVALIDATE):
        import uuid

        super().__init__(name, client)
        if sync_strategy not in (INVALIDATE, UPDATE, NONE):
            raise ValueError(f"unknown sync strategy: {sync_strategy}")
        self._hub = _hub_for(client)
        if cache_size > 0:
            self._hub.ensure_budget(int(cache_max_bytes))
            # This map's slice of the shared store: its own byte quota
            # and entry bound (two enabled handles to one map share the
            # limits — last constructor wins, like two clients
            # configuring one cache).  A DISABLED handle (cache_size<=0)
            # must not touch them: passing its 0 through would erase an
            # enabled peer's entry bound (the store reads 0 as
            # UNBOUNDED — the PR 4 inversion, again).
            self._hub.store.set_tenant_limits(
                name, max_bytes=int(cache_max_bytes),
                max_entries=int(cache_size),
            )
        self._cache = self._hub.store
        self._cache_size = cache_size
        self._sync = sync_strategy
        self._bus = client._topic_bus
        self._channel = f"{name}:topic"
        # Invalidation messages carry the writer's cache id so the writer
        # skips its own (it already bumped the generation and maintained
        # the shared entry) — the reference's excludedId on
        # LocalCachedMapInvalidate.  OTHER handles still process it: the
        # redundant discard converges racing writers' installs onto the
        # backing map's order.
        self._cache_id = uuid.uuid4().hex
        self._listener_id = self._bus.subscribe(self._channel, self._on_sync)

    # -- near cache plumbing -----------------------------------------------

    def _on_sync(self, channel, message) -> None:
        origin, op, kb, vb = message
        if origin == self._cache_id:
            return
        # Any processed invalidation bumps the generation: a reader that
        # sampled the backing map BEFORE this message must not install
        # its (possibly stale) value afterwards.
        self._hub.bump(self._name)
        if kb is None:  # full clear
            self._cache.invalidate_tenant(self._name)
            return
        if op == UPDATE and vb is not None:
            self._cache_put(kb, self._dec(vb))
        else:
            self._cache.discard(self._name, kb)

    def _cache_put(self, kb: bytes, value: Any) -> None:
        # cache_size<=0 DISABLES the near cache (the pre-PR-4 OrderedDict
        # evicted down to the bound after every put, leaving it
        # permanently empty) — the store's own 0 means "unbounded entry
        # count", the exact inversion of what the caller asked for.
        if self._cache_size <= 0:
            return
        self._cache.put(self._name, kb, value, _approx_nbytes(kb, value))

    def _publish(self, kb: Optional[bytes], vb: Optional[bytes]) -> None:
        if self._sync == NONE:
            return
        self._bus.publish(self._channel, (self._cache_id, self._sync, kb, vb))

    # -- overridden read/write paths ---------------------------------------

    def get(self, key: Any) -> Any:
        if self._cache_size <= 0:
            # This handle opted out of near-caching entirely: read
            # through — serving an enabled peer's shared entries would
            # un-opt it back in.
            return super().get(key)
        kb = self._enc_key(key)
        cached = self._cache.get(self._name, kb)
        if cached is not MISS:
            return cached
        gen = self._hub.gen(self._name)
        val = super().get(key)
        if val is not None:
            # Install only if no write/invalidation raced the backing
            # read — otherwise a stale value could be cached forever.
            self._hub.install_if(
                self._name, kb, val, _approx_nbytes(kb, val), gen
            )
        return val

    def put(self, key: Any, value: Any) -> Any:
        prev = super().put(key, value)
        kb = self._enc_key(key)
        self._hub.bump(self._name)  # retire in-flight read installs
        self._cache_put(kb, value)
        self._publish(kb, self._enc(value) if self._sync == UPDATE else None)
        return prev

    def fast_put(self, key: Any, value: Any) -> bool:
        created = super().fast_put(key, value)
        kb = self._enc_key(key)
        self._hub.bump(self._name)
        self._cache_put(kb, value)
        self._publish(kb, self._enc(value) if self._sync == UPDATE else None)
        return created

    def remove(self, key: Any, expected: Any = _MISSING) -> Any:
        # _MISSING sentinel, NOT None: remove(key, None) is a CONDITIONAL
        # remove expecting a stored None — collapsing it to unconditional
        # deleted data the caller meant to protect.
        if expected is _MISSING:
            prev = super().remove(key)
        else:
            prev = super().remove(key, expected)
        kb = self._enc_key(key)
        self._hub.bump(self._name)
        self._cache.discard(self._name, kb)
        self._publish(kb, None)
        return prev

    def replace(self, key: Any, value: Any, new_value: Any = _MISSING):
        out = super().replace(key, value, new_value)
        kb = self._enc_key(key)
        self._hub.bump(self._name)
        self._cache.discard(self._name, kb)
        self._publish(kb, None)
        return out

    def put_if_absent(self, key: Any, value: Any):
        out = super().put_if_absent(key, value)
        if out is None:  # stored: peers must drop any stale negative
            kb = self._enc_key(key)
            self._hub.bump(self._name)
            self._cache.discard(self._name, kb)
            self._publish(kb, None)
        return out

    def delete(self) -> bool:
        out = super().delete()
        self._hub.bump(self._name)
        self._cache.invalidate_tenant(self._name)
        # Whole-map invalidation: peers drop EVERYTHING (kb=None marker).
        self._publish(None, None)
        return out

    def fast_remove(self, *keys: Any) -> int:
        n = super().fast_remove(*keys)
        self._hub.bump(self._name)
        for k in keys:
            kb = self._enc_key(k)
            self._cache.discard(self._name, kb)
            self._publish(kb, None)
        return n

    def clear(self) -> bool:
        """→ RLocalCachedMap: clears backing map + every near cache."""
        existed = self.delete()
        self._cache.invalidate_tenant(self._name)
        if self._sync != NONE:
            self._bus.publish(
                self._channel, (self._cache_id, INVALIDATE, None, None)
            )
        return existed

    # -- cache introspection (→ RLocalCachedMap#cachedEntrySet etc.) -------

    def cached_size(self) -> int:
        return self._cache.tenant_entry_count(self._name)

    def cached_key_set(self) -> list:
        return [self._dec_key(kb) for kb in self._cache.tenant_keys(self._name)]

    def cache_stats(self) -> dict:
        """Near-cache occupancy/effectiveness (the shared LRU store's
        hit/miss/eviction/byte accounting).  Store-wide counters: with
        several maps on one client they aggregate — per-map bytes ride
        ``tenant_bytes``."""
        st = self._cache.stats()
        st["tenant_bytes"] = self._cache.tenant_bytes(self._name)
        st["max_entries"] = self._cache_size
        return st

    def clear_local_cache(self) -> None:
        """→ RLocalCachedMap#clearLocalCache.  The store is shared
        per-client now, so this drops the MAP's entries (every local
        handle's view of them — one store, one copy)."""
        self._hub.bump(self._name)
        self._cache.invalidate_tenant(self._name)

    def pre_load_cache(self) -> None:
        """→ RLocalCachedMap#preloadCache: warm the near cache with the
        whole backing map."""
        for k, v in self.read_all_map().items():
            self._cache_put(self._enc_key(k), v)

    def destroy(self) -> None:
        """Unsubscribe this handle's invalidation listener."""
        self._bus.unsubscribe(self._channel, self._listener_id)
        self._hub.bump(self._name)
        self._cache.invalidate_tenant(self._name)
