"""RLocalCachedMap — → org/redisson/RedissonLocalCachedMap.java +
org/redisson/cache/ (LocalCacheView, LRU caches, invalidation-topic sync
strategies).

The reference keeps a near cache in each client and invalidates peers
through a topic; writes publish the touched key hashes.  Here the shared
state is the grid Map entry, the near cache is a per-HANDLE
``ShardedLRUStore`` (the ONE eviction implementation, shared with the
sketch near cache — redisson_tpu/cache/lru.py), and invalidation rides
the client's TopicBus on the map's own ``{name}:topic`` channel — every
handle (including other handles in this process, the reference's
multi-client analog) subscribes and drops invalidated keys.

Riding the shared store buys what the private OrderedDict never had:
byte-quota accounting (``cache_max_bytes``) on top of the entry bound,
and hit/miss/eviction stats (``cache_stats()``) for free.

Sync strategies (→ SyncStrategy): INVALIDATE (default) clears peer cache
entries on write; UPDATE pushes the new value; NONE publishes nothing.
"""

from __future__ import annotations

import threading
from typing import Any, Optional

from redisson_tpu.cache import MISS, ShardedLRUStore
from redisson_tpu.grid.maps import Map, _MISSING

INVALIDATE = "invalidate"
UPDATE = "update"
NONE = "none"


def _approx_nbytes(kb: bytes, value: Any) -> int:
    """Caller-estimated entry size for the byte quota: key bytes + a flat
    per-entry overhead + the value's obvious payload (sized types only —
    arbitrary objects count a constant; the bound is a budget, not an
    audit)."""
    if isinstance(value, (bytes, bytearray, str)):
        vb = len(value)
    else:
        vb = 64
    return 96 + len(kb) + vb


class LocalCachedMap(Map):
    KIND = "map"  # shares the backing Map keyspace entry

    def __init__(self, name, client, *, cache_size: int = 4096,
                 cache_max_bytes: int = 64 << 20,
                 sync_strategy: str = INVALIDATE):
        import uuid

        super().__init__(name, client)
        if sync_strategy not in (INVALIDATE, UPDATE, NONE):
            raise ValueError(f"unknown sync strategy: {sync_strategy}")
        # One shard: a handle's near cache is touched by one user thread
        # plus the TopicBus pool — exact (not approximate) LRU matters
        # more than lock spread at that concurrency.  The single tenant
        # owns the WHOLE byte budget (the store's default per-tenant
        # quota is budget/8, sized for many concurrent sketch tenants —
        # here there is exactly one).
        self._cache = ShardedLRUStore(
            max_bytes=int(cache_max_bytes), nshards=1,
            tenant_quota_bytes=int(cache_max_bytes),
        )
        self._cache.set_tenant_limits(name, max_entries=int(cache_size))
        self._cache_size = cache_size
        self._sync = sync_strategy
        self._bus = client._topic_bus
        self._channel = f"{name}:topic"
        # Invalidation messages carry the writer's cache id so the writer
        # skips its own (its near cache already holds the fresh value) —
        # the reference's excludedId on LocalCachedMapInvalidate.
        self._cache_id = uuid.uuid4().hex
        # The near cache is touched by user threads AND the TopicBus
        # delivery pool (_on_sync) — the store's own locks guard entries;
        # this lock guards the generation counter's read-then-install
        # window.
        self._cache_lock = threading.Lock()
        self._inval_gen = 0
        self._listener_id = self._bus.subscribe(self._channel, self._on_sync)

    # -- near cache plumbing -----------------------------------------------

    def _on_sync(self, channel, message) -> None:
        origin, op, kb, vb = message
        if origin == self._cache_id:
            return
        with self._cache_lock:
            # Any processed invalidation bumps the generation: a reader
            # that sampled the backing map BEFORE this message must not
            # install its (possibly stale) value afterwards.
            self._inval_gen += 1
            if kb is None:  # full clear
                self._cache.invalidate_tenant(self._name)
                return
            if op == UPDATE and vb is not None:
                self._cache_put_locked(kb, self._dec(vb))
            else:
                self._cache.discard(self._name, kb)

    def _cache_put(self, kb: bytes, value: Any) -> None:
        with self._cache_lock:
            self._cache_put_locked(kb, value)

    def _cache_put_locked(self, kb: bytes, value: Any) -> None:
        # cache_size<=0 DISABLES the near cache (the pre-PR-4 OrderedDict
        # evicted down to the bound after every put, leaving it
        # permanently empty) — the store's own 0 means "unbounded entry
        # count", the exact inversion of what the caller asked for.
        if self._cache_size <= 0:
            return
        self._cache.put(self._name, kb, value, _approx_nbytes(kb, value))

    def _publish(self, kb: Optional[bytes], vb: Optional[bytes]) -> None:
        if self._sync == NONE:
            return
        self._bus.publish(self._channel, (self._cache_id, self._sync, kb, vb))

    # -- overridden read/write paths ---------------------------------------

    def get(self, key: Any) -> Any:
        kb = self._enc_key(key)
        cached = self._cache.get(self._name, kb)
        if cached is not MISS:
            return cached
        with self._cache_lock:
            gen = self._inval_gen
        val = super().get(key)
        if val is not None:
            with self._cache_lock:
                # Install only if no invalidation raced the backing read —
                # otherwise a stale value could be cached forever.
                if self._inval_gen == gen:
                    self._cache_put_locked(kb, val)
        return val

    def put(self, key: Any, value: Any) -> Any:
        prev = super().put(key, value)
        kb = self._enc_key(key)
        self._cache_put(kb, value)
        self._publish(kb, self._enc(value) if self._sync == UPDATE else None)
        return prev

    def fast_put(self, key: Any, value: Any) -> bool:
        created = super().fast_put(key, value)
        kb = self._enc_key(key)
        self._cache_put(kb, value)
        self._publish(kb, self._enc(value) if self._sync == UPDATE else None)
        return created

    def remove(self, key: Any, expected: Any = _MISSING) -> Any:
        # _MISSING sentinel, NOT None: remove(key, None) is a CONDITIONAL
        # remove expecting a stored None — collapsing it to unconditional
        # deleted data the caller meant to protect.
        if expected is _MISSING:
            prev = super().remove(key)
        else:
            prev = super().remove(key, expected)
        kb = self._enc_key(key)
        self._cache.discard(self._name, kb)
        self._publish(kb, None)
        return prev

    def replace(self, key: Any, value: Any, new_value: Any = _MISSING):
        out = super().replace(key, value, new_value)
        kb = self._enc_key(key)
        self._cache.discard(self._name, kb)
        self._publish(kb, None)
        return out

    def put_if_absent(self, key: Any, value: Any):
        out = super().put_if_absent(key, value)
        if out is None:  # stored: peers must drop any stale negative
            kb = self._enc_key(key)
            self._cache.discard(self._name, kb)
            self._publish(kb, None)
        return out

    def delete(self) -> bool:
        out = super().delete()
        self._cache.invalidate_tenant(self._name)
        # Whole-map invalidation: peers drop EVERYTHING (kb=None marker).
        self._publish(None, None)
        return out

    def fast_remove(self, *keys: Any) -> int:
        n = super().fast_remove(*keys)
        for k in keys:
            kb = self._enc_key(k)
            self._cache.discard(self._name, kb)
            self._publish(kb, None)
        return n

    def clear(self) -> bool:
        """→ RLocalCachedMap: clears backing map + every near cache."""
        existed = self.delete()
        self._cache.invalidate_tenant(self._name)
        if self._sync != NONE:
            self._bus.publish(
                self._channel, (self._cache_id, INVALIDATE, None, None)
            )
        return existed

    # -- cache introspection (→ RLocalCachedMap#cachedEntrySet etc.) -------

    def cached_size(self) -> int:
        return self._cache.tenant_entry_count(self._name)

    def cached_key_set(self) -> list:
        return [self._dec_key(kb) for kb in self._cache.tenant_keys(self._name)]

    def cache_stats(self) -> dict:
        """Near-cache occupancy/effectiveness (the shared LRU store's
        hit/miss/eviction/byte accounting — the OrderedDict this cache
        rode before PR 4 had none)."""
        st = self._cache.stats()
        st["tenant_bytes"] = self._cache.tenant_bytes(self._name)
        st["max_entries"] = self._cache_size
        return st

    def clear_local_cache(self) -> None:
        """→ RLocalCachedMap#clearLocalCache (this handle only)."""
        self._cache.invalidate_tenant(self._name)

    def pre_load_cache(self) -> None:
        """→ RLocalCachedMap#preloadCache: warm the near cache with the
        whole backing map."""
        for k, v in self.read_all_map().items():
            self._cache_put(self._enc_key(k), v)

    def destroy(self) -> None:
        """Unsubscribe this handle's invalidation listener."""
        self._bus.unsubscribe(self._channel, self._listener_id)
        self._cache.invalidate_tenant(self._name)
