"""Locks & synchronizers — → org/redisson/RedissonLock.java (reentrant
lock + watchdog), RedissonFairLock (FIFO), RedissonReadWriteLock,
RedissonSemaphore, RedissonPermitExpirableSemaphore,
RedissonCountDownLatch, RedissonSpinLock, RedissonFencedLock,
RedissonMultiLock/RedLock (client-side N-lock composition),
RedissonRateLimiter (token bucket).

The reference implements these as Lua scripts + pub/sub wake-ups
(SURVEY.md §3.3); in-process the store's condition variable plays the
unlock-channel role and lease expiry replaces the watchdog's renew loop
(a held lock with no lease simply cannot be lost while the process
lives).  Owner identity is (client id, thread id) — the analog of the
reference's UUID:threadId lock value.
"""

from __future__ import annotations

import threading
import time
import uuid
from typing import Optional

from redisson_tpu.grid.base import GridObject


def _now() -> float:
    return time.monotonic()


class Lock(GridObject):
    KIND = "lock"

    @staticmethod
    def _new_value():
        return {"owner": None, "count": 0, "expire_at": None, "token": 0}

    def _me(self):
        # UUID:threadId — the reference's lock value (→ RedissonLock).
        # id(client) would alias once a dead client's id is recycled.
        return (self._client.id, threading.get_ident())

    def _live_state(self):
        e = self._entry()
        st = e.value
        if st["owner"] is not None and st["expire_at"] is not None and _now() >= st["expire_at"]:
            st["owner"] = None
            st["count"] = 0
            st["expire_at"] = None
        return st

    def _live_state_ro(self):
        """Read-only state peek: does NOT materialize a keyspace entry for
        an absent lock (in Redis an unheld lock key does not exist)."""
        e = self._entry(create=False)
        if e is None:
            return None
        st = e.value
        if st["owner"] is not None and st["expire_at"] is not None and _now() >= st["expire_at"]:
            st["owner"] = None
            st["count"] = 0
            st["expire_at"] = None
        return st

    def _tokens(self) -> dict:
        """Fencing-token counters survive OUTSIDE the keyspace entry (per
        store, keyed by name): the entry itself is deleted on full release
        — in Redis an unheld lock key does not exist — but fencing tokens
        must stay monotonic across acquire/release cycles."""
        return self._store.__dict__.setdefault("_lock_tokens", {})

    def _try_take(self, lease_seconds: Optional[float]) -> bool:
        me = self._me()
        # Contended probe: do NOT materialize an entry for a lock someone
        # else holds (failed try_lock calls must not leak keyspace names).
        ro = self._live_state_ro()
        if ro is not None and ro["owner"] is not None and ro["owner"] != me:
            return False
        st = self._live_state()
        if st["owner"] is None:
            st["owner"] = me
            st["count"] = 1
            st["expire_at"] = None if lease_seconds is None else _now() + lease_seconds
            toks = self._tokens()
            toks[self._name] = st["token"] = toks.get(self._name, 0) + 1
            return True
        if st["owner"] == me:
            st["count"] += 1  # reentrancy (→ RedissonLock hash-incr)
            if lease_seconds is not None:
                st["expire_at"] = _now() + lease_seconds
            return True
        return False

    def lock(self, lease_seconds: Optional[float] = None) -> None:
        with self._store.cond:
            while not self._try_take(lease_seconds):
                self._store.cond.wait(timeout=self._wait_slice())

    def try_lock(self, wait_seconds: float = 0.0, lease_seconds: Optional[float] = None) -> bool:
        deadline = _now() + wait_seconds
        with self._store.cond:
            while True:
                if self._try_take(lease_seconds):
                    return True
                remaining = deadline - _now()
                if remaining <= 0:
                    return False
                self._store.cond.wait(timeout=min(remaining, self._wait_slice()))

    def _wait_slice(self) -> float:
        """Cap waits so lease expiry is noticed without an unlock signal."""
        st = self._live_state_ro()
        if st is None or st["expire_at"] is None:
            return 1.0
        return max(0.01, min(1.0, st["expire_at"] - _now()))

    def unlock(self) -> None:
        with self._store.cond:
            st = self._live_state()
            if st["owner"] != self._me():
                raise RuntimeError(
                    f"lock {self._name!r} is not held by this thread"
                )
            st["count"] -= 1
            if st["count"] <= 0:
                # Full release DELETES the key (Redis unlock semantics:
                # an unheld lock does not exist in the keyspace).
                self._release_entry()
                self._store.cond.notify_all()  # the unlock-channel PUBLISH

    def _release_entry(self) -> None:
        """Remove the keyspace entry on full release.  Subclasses with
        extra durable state (fair queue) override to decide."""
        self._store.delete(self._name)

    def force_unlock(self) -> bool:
        with self._store.cond:
            st = self._live_state_ro()
            held = st is not None and st["owner"] is not None
            if st is not None:
                self._release_entry()
            self._store.cond.notify_all()
            return held

    def is_locked(self) -> bool:
        with self._store.lock:
            st = self._live_state_ro()
            return st is not None and st["owner"] is not None

    def is_held_by_current_thread(self) -> bool:
        with self._store.lock:
            st = self._live_state_ro()
            return st is not None and st["owner"] == self._me()

    def get_hold_count(self) -> int:
        with self._store.lock:
            st = self._live_state_ro()
            if st is None:
                return 0
            return st["count"] if st["owner"] == self._me() else 0

    def remain_lease_time(self) -> int:
        """ms until lease expiry; -1 held without lease, -2 not held."""
        with self._store.lock:
            st = self._live_state_ro()
            if st is None or st["owner"] is None:
                return -2
            if st["expire_at"] is None:
                return -1
            return max(0, int((st["expire_at"] - _now()) * 1000))

    # context manager sugar
    def __enter__(self):
        self.lock()
        return self

    def __exit__(self, *exc):
        self.unlock()


class SpinLock(Lock):
    """→ RedissonSpinLock: same semantics, polling acquisition (the
    reference variant avoids pub/sub; in-process the distinction is moot)."""

    KIND = "spinlock"


class FencedLock(Lock):
    """→ RedissonFencedLock: lock() returns a monotonically increasing
    fencing token."""

    KIND = "fencedlock"

    def lock_and_get_token(self, lease_seconds: Optional[float] = None) -> int:
        self.lock(lease_seconds)
        with self._store.lock:
            return self._entry().value["token"]

    def get_token(self) -> Optional[int]:
        with self._store.lock:
            st = self._live_state_ro()
            if st is None:
                return None
            return st["token"] if st["owner"] == self._me() else None

    # token counters live in Lock._tokens() (store-side), so fencing
    # monotonicity survives the entry's deletion on release.


class FairLock(Lock):
    """→ RedissonFairLock: FIFO handoff — waiters queue and only the head
    may take the lock."""

    KIND = "fairlock"

    @staticmethod
    def _new_value():
        return {"owner": None, "count": 0, "expire_at": None, "token": 0,
                "queue": []}

    def _try_take(self, lease_seconds):
        st = self._live_state()
        me = self._me()
        q = st["queue"]
        if st["owner"] == me:
            return super()._try_take(lease_seconds)
        if st["owner"] is None and (not q or q[0] == me):
            if q and q[0] == me:
                q.pop(0)
            return super()._try_take(lease_seconds)
        if me not in q:
            q.append(me)
        return False

    def try_lock(self, wait_seconds: float = 0.0, lease_seconds: Optional[float] = None) -> bool:
        got = super().try_lock(wait_seconds, lease_seconds)
        if not got:
            with self._store.lock:  # leave the queue on timeout
                st = self._live_state_ro()
                me = self._me()
                if st is not None and me in st["queue"]:
                    st["queue"].remove(me)
                    if st["owner"] is None and not st["queue"]:
                        self._release_entry()  # nothing left to preserve
        return got

    def _release_entry(self) -> None:
        # The FIFO queue must survive a release while waiters are parked
        # (deleting it would lose their positions); the entry goes away
        # only once the queue is empty too.
        st = self._live_state_ro()
        if st is None or not st["queue"]:
            self._store.delete(self._name)
        else:
            st["owner"] = None
            st["count"] = 0
            st["expire_at"] = None


class ReadWriteLock(GridObject):
    """→ RedissonReadWriteLock: many readers or one writer; the writer may
    downgrade by taking the read lock while holding write."""

    KIND = "rwlock"

    @staticmethod
    def _new_value():
        return {"readers": {}, "writer": None, "write_count": 0}

    def read_lock(self) -> "_ReadLock":
        return _ReadLock(self)

    def write_lock(self) -> "_WriteLock":
        return _WriteLock(self)

    def _me(self):
        return (self._client.id, threading.get_ident())


class _ReadLock:
    def __init__(self, rw: ReadWriteLock):
        self._rw = rw
        self._store = rw._store

    def _try_take(self) -> bool:
        st = self._rw._entry().value
        me = self._rw._me()
        if st["writer"] is None or st["writer"] == me:
            st["readers"][me] = st["readers"].get(me, 0) + 1
            return True
        return False

    def lock(self) -> None:
        with self._store.cond:
            while not self._try_take():
                self._store.cond.wait(timeout=1.0)

    def try_lock(self, wait_seconds: float = 0.0) -> bool:
        deadline = _now() + wait_seconds
        with self._store.cond:
            while True:
                if self._try_take():
                    return True
                remaining = deadline - _now()
                if remaining <= 0:
                    return False
                self._store.cond.wait(timeout=remaining)

    def unlock(self) -> None:
        with self._store.cond:
            st = self._rw._entry().value
            me = self._rw._me()
            n = st["readers"].get(me, 0)
            if n <= 0:
                raise RuntimeError("read lock is not held by this thread")
            if n == 1:
                del st["readers"][me]
            else:
                st["readers"][me] = n - 1
            self._store.cond.notify_all()

    def __enter__(self):
        self.lock()
        return self

    def __exit__(self, *exc):
        self.unlock()


class _WriteLock:
    def __init__(self, rw: ReadWriteLock):
        self._rw = rw
        self._store = rw._store

    def _try_take(self) -> bool:
        st = self._rw._entry().value
        me = self._rw._me()
        others_reading = any(k != me for k in st["readers"])
        if st["writer"] in (None, me) and not others_reading:
            st["writer"] = me
            st["write_count"] += 1
            return True
        return False

    def lock(self) -> None:
        with self._store.cond:
            while not self._try_take():
                self._store.cond.wait(timeout=1.0)

    def try_lock(self, wait_seconds: float = 0.0) -> bool:
        deadline = _now() + wait_seconds
        with self._store.cond:
            while True:
                if self._try_take():
                    return True
                remaining = deadline - _now()
                if remaining <= 0:
                    return False
                self._store.cond.wait(timeout=remaining)

    def unlock(self) -> None:
        with self._store.cond:
            st = self._rw._entry().value
            if st["writer"] != self._rw._me():
                raise RuntimeError("write lock is not held by this thread")
            st["write_count"] -= 1
            if st["write_count"] <= 0:
                st["writer"] = None
                st["write_count"] = 0
            self._store.cond.notify_all()

    def __enter__(self):
        self.lock()
        return self

    def __exit__(self, *exc):
        self.unlock()


class Semaphore(GridObject):
    """→ RedissonSemaphore: permits must be set before acquisition
    (trySetPermits), release() may exceed the initial count (Redis
    semantics — permits are just a counter)."""

    KIND = "semaphore"

    @staticmethod
    def _new_value():
        return {"permits": 0, "init": False}

    def try_set_permits(self, permits: int) -> bool:
        with self._store.lock:
            e = self._entry()
            # Guard on initialization, not on the counter: a fully-drained
            # semaphore (permits == 0) must NOT be silently re-armed.
            if e.value["init"]:
                return False
            e.value["permits"] = int(permits)
            e.value["init"] = True
            return True

    def available_permits(self) -> int:
        with self._store.lock:
            e = self._entry(create=False)
            return 0 if e is None else e.value["permits"]

    def try_acquire(self, permits: int = 1, wait_seconds: float = 0.0) -> bool:
        deadline = _now() + wait_seconds
        with self._store.cond:
            while True:
                st = self._entry().value
                if st["permits"] >= permits:
                    st["permits"] -= permits
                    return True
                remaining = deadline - _now()
                if remaining <= 0:
                    return False
                self._store.cond.wait(timeout=remaining)

    def acquire(self, permits: int = 1) -> None:
        with self._store.cond:
            while True:
                st = self._entry().value
                if st["permits"] >= permits:
                    st["permits"] -= permits
                    return
                self._store.cond.wait(timeout=1.0)

    def release(self, permits: int = 1) -> None:
        with self._store.cond:
            self._entry().value["permits"] += permits
            self._store.cond.notify_all()

    def add_permits(self, permits: int) -> None:
        self.release(permits)

    def drain_permits(self) -> int:
        with self._store.lock:
            st = self._entry().value
            n = st["permits"]
            st["permits"] = 0
            return n


class PermitExpirableSemaphore(GridObject):
    """→ RedissonPermitExpirableSemaphore: acquire() returns a permit id;
    leased permits auto-return on expiry; release(id) is idempotent-safe."""

    KIND = "xsemaphore"

    @staticmethod
    def _new_value():
        return {"permits": 0, "leased": {}}  # id -> expire_at|None

    def _reclaim(self, st) -> None:
        now = _now()
        dead = [
            pid
            for pid, exp in st["leased"].items()
            if exp is not None and now >= exp
        ]
        for pid in dead:
            del st["leased"][pid]
            st["permits"] += 1

    def try_set_permits(self, permits: int) -> bool:
        with self._store.lock:
            st = self._entry().value
            if st["permits"] != 0 or st["leased"]:
                return False
            st["permits"] = int(permits)
            return True

    def available_permits(self) -> int:
        with self._store.lock:
            e = self._entry(create=False)
            if e is None:
                return 0
            st = e.value
            self._reclaim(st)
            return st["permits"]

    def try_acquire(self, wait_seconds: float = 0.0,
                    lease_seconds: Optional[float] = None) -> Optional[str]:
        deadline = _now() + wait_seconds
        with self._store.cond:
            while True:
                st = self._entry().value
                self._reclaim(st)
                if st["permits"] > 0:
                    st["permits"] -= 1
                    pid = uuid.uuid4().hex
                    st["leased"][pid] = (
                        None if lease_seconds is None else _now() + lease_seconds
                    )
                    return pid
                remaining = deadline - _now()
                if remaining <= 0:
                    return None
                self._store.cond.wait(timeout=min(0.05, max(0.01, remaining)))

    def acquire(self, lease_seconds: Optional[float] = None) -> str:
        while True:
            pid = self.try_acquire(wait_seconds=1.0, lease_seconds=lease_seconds)
            if pid is not None:
                return pid

    def try_release(self, permit_id: str) -> bool:
        with self._store.cond:
            st = self._entry().value
            if permit_id not in st["leased"]:
                return False
            del st["leased"][permit_id]
            st["permits"] += 1
            self._store.cond.notify_all()
            return True

    def release(self, permit_id: str) -> None:
        if not self.try_release(permit_id):
            raise RuntimeError(f"permit {permit_id!r} is not leased (expired?)")


class CountDownLatch(GridObject):
    """→ RedissonCountDownLatch: trySetCount / countDown / await."""

    KIND = "countdownlatch"

    @staticmethod
    def _new_value():
        return {"count": 0}

    def try_set_count(self, count: int) -> bool:
        with self._store.lock:
            st = self._entry().value
            if st["count"] != 0:
                return False
            st["count"] = int(count)
            return True

    def get_count(self) -> int:
        with self._store.lock:
            e = self._entry(create=False)
            return 0 if e is None else e.value["count"]

    def count_down(self) -> None:
        with self._store.cond:
            st = self._entry().value
            if st["count"] > 0:
                st["count"] -= 1
                if st["count"] == 0:
                    self._store.cond.notify_all()

    def wait_for(self, timeout_seconds: Optional[float] = None) -> bool:
        """→ RCountDownLatch#await (``await`` is reserved in Python)."""
        deadline = None if timeout_seconds is None else _now() + timeout_seconds
        with self._store.cond:
            while self.get_count() > 0:
                remaining = None if deadline is None else deadline - _now()
                if remaining is not None and remaining <= 0:
                    return False
                self._store.cond.wait(
                    timeout=1.0 if remaining is None else min(1.0, remaining)
                )
            return True


class MultiLock:
    """→ RedissonMultiLock / RedissonRedLock: acquire N locks as a unit,
    releasing everything on partial failure."""

    def __init__(self, *locks: Lock):
        if not locks:
            raise ValueError("MultiLock needs at least one lock")
        self._locks = list(locks)

    def try_lock(self, wait_seconds: float = 0.0,
                 lease_seconds: Optional[float] = None) -> bool:
        acquired = []
        deadline = _now() + wait_seconds
        for lk in self._locks:
            remaining = max(0.0, deadline - _now())
            if lk.try_lock(remaining, lease_seconds):
                acquired.append(lk)
            else:
                for got in reversed(acquired):
                    try:
                        got.unlock()
                    except RuntimeError:
                        pass  # lease expired while acquiring the rest
                return False
        return True

    def lock(self, lease_seconds: Optional[float] = None) -> None:
        while not self.try_lock(wait_seconds=1.0, lease_seconds=lease_seconds):
            pass

    def unlock(self) -> None:
        errors = []
        for lk in reversed(self._locks):
            try:
                lk.unlock()
            except RuntimeError as e:
                errors.append(e)
        if errors:
            raise errors[0]

    def __enter__(self):
        self.lock()
        return self

    def __exit__(self, *exc):
        self.unlock()


class RateLimiter(GridObject):
    """→ org/redisson/RedissonRateLimiter.java: fixed-interval token
    bucket — ``rate`` permits become available every ``interval`` seconds
    (the reference's RateType OVERALL; per-client mode keys the bucket by
    client id)."""

    KIND = "ratelimiter"

    OVERALL = "overall"
    PER_CLIENT = "per_client"

    @staticmethod
    def _new_value():
        return {"mode": None, "rate": 0, "interval": 0.0, "buckets": {}}

    @classmethod
    def _check_mode(cls, mode: str) -> None:
        if mode not in (cls.OVERALL, cls.PER_CLIENT):
            raise ValueError(f"unknown rate mode: {mode}")

    def try_set_rate(self, mode: str, rate: int, interval_seconds: float) -> bool:
        self._check_mode(mode)
        with self._store.lock:
            st = self._entry().value
            if st["mode"] is not None:
                return False
            st.update(mode=mode, rate=int(rate), interval=float(interval_seconds))
            return True

    def set_rate(self, mode: str, rate: int, interval_seconds: float) -> None:
        self._check_mode(mode)
        with self._store.lock:
            st = self._entry().value
            st.update(
                mode=mode, rate=int(rate), interval=float(interval_seconds),
                buckets={},
            )

    def _bucket(self, st):
        key = "all" if st["mode"] == self.OVERALL else self._client.id
        b = st["buckets"].get(key)
        now = _now()
        if b is None or now >= b["window_end"]:
            b = {"tokens": st["rate"], "window_end": now + st["interval"]}
            st["buckets"][key] = b
        return b

    def try_acquire(self, permits: int = 1, wait_seconds: float = 0.0) -> bool:
        deadline = _now() + wait_seconds
        while True:
            with self._store.lock:
                st = self._entry().value
                if st["mode"] is None:
                    raise RuntimeError("rate is not set (try_set_rate first)")
                if permits > st["rate"]:
                    raise ValueError(
                        f"requested {permits} permits > rate {st['rate']}"
                    )
                b = self._bucket(st)
                if b["tokens"] >= permits:
                    b["tokens"] -= permits
                    return True
                retry_at = b["window_end"]
            remaining = deadline - _now()
            if remaining <= 0:
                return False
            time.sleep(min(remaining, max(0.005, retry_at - _now())))

    def acquire(self, permits: int = 1) -> None:
        while not self.try_acquire(permits, wait_seconds=1.0):
            pass

    def available_permits(self) -> int:
        with self._store.lock:
            e = self._entry(create=False)
            if e is None or e.value["mode"] is None:
                return 0
            return self._bucket(e.value)["tokens"]
