"""Maps — → org/redisson/RedissonMap.java (RMap over Redis hashes) and
RedissonMapCache.java (per-entry TTL/max-idle via companion timeout
structures + EvictionScheduler; here TTLs live beside the entries and the
grid sweeper prunes them).

Keys and values are stored codec-encoded (hash-field semantics of the
reference: equality is on serialized bytes).
"""

from __future__ import annotations

import time
from typing import Any, Iterable, Optional

from redisson_tpu.grid.base import GridObject, journaled

_MISSING = object()


class _MapValue:
    """dict: key bytes -> (value bytes, expire_at|None, max_idle_s|None,
    last_access).  ``on_expire`` (not persisted — see __getstate__) is
    an optional callback fired when lazy expiry reaps a slot, so cache
    layers can surface JSR-107 Expired events."""

    __slots__ = ("data", "on_expire")

    def __init__(self):
        self.data: dict[bytes, list] = {}
        self.on_expire = None

    def __getstate__(self):
        return self.data  # callbacks are process-local, never persisted

    def __setstate__(self, data):
        self.data = data
        self.on_expire = None

    def live(self, kb: bytes, now: Optional[float] = None, touch: bool = False):
        """Liveness check with lazy expiry.  ``touch`` refreshes the
        max-idle clock — only genuine value reads (RMapCache getAll/get
        semantics) pass it; size()/views/sweeper must NOT keep idle
        entries alive."""
        slot = self.data.get(kb)
        if slot is None:
            return None
        now = now or time.time()
        vb, exp, idle, last = slot
        if exp is not None and now >= exp:
            del self.data[kb]
            if self.on_expire is not None:
                self.on_expire(kb, vb)
            return None
        if idle is not None and now - last >= idle:
            del self.data[kb]
            if self.on_expire is not None:
                self.on_expire(kb, vb)
            return None
        if touch:
            slot[3] = now
        return slot

    def prune_expired(self, now: float) -> None:
        for kb in list(self.data.keys()):
            self.live(kb, now)


@journaled("put", "fast_put", "put_if_absent", "put_all", "remove",
           "fast_remove", "replace", "add_and_get", "clear")
class Map(GridObject):
    KIND = "map"

    @staticmethod
    def _new_value():
        return _MapValue()

    # -- entry listeners (→ RMapCache#addListener: EntryCreated/Updated/
    # Removed listeners ride the client's topic bus on the map's own
    # event channel, so every handle sees every mutation.  TTL expiry is
    # lazy/sweeper-driven with no client context, so no Expired event
    # fires — the reference's expired-listener has no analog here) --------

    _EVENT_CREATED = "created"
    _EVENT_UPDATED = "updated"
    _EVENT_REMOVED = "removed"

    def _event_channel(self) -> str:
        return f"{self._name}:map-events"

    def add_listener(self, listener, event: Optional[str] = None) -> int:
        """``listener(event, key, value)``; ``event`` filters to one of
        'created'/'updated'/'removed' (None = all)."""
        bus = self._client._topic_bus

        def on_event(channel, message):
            ev, key, value = message
            if event is None or ev == event:
                listener(ev, key, value)

        return bus.subscribe(self._event_channel(), on_event)

    def remove_listener(self, listener_id: int) -> None:
        self._client._topic_bus.unsubscribe(self._event_channel(), listener_id)

    def _emit(self, event: str, key, value) -> None:
        bus = getattr(self._client, "_topic_bus", None)
        if bus is not None and bus.count_listeners(self._event_channel()):
            bus.publish(self._event_channel(), (event, key, value))

    # -- core --------------------------------------------------------------

    def put(self, key: Any, value: Any) -> Any:
        """→ RMap#put: returns the previous value (or None)."""
        with self._store.lock:
            e = self._entry()
            kb = self._enc_key(key)
            prev = e.value.live(kb)
            e.value.data[kb] = [self._enc(value), None, None, time.time()]
        self._emit(
            self._EVENT_UPDATED if prev is not None else self._EVENT_CREATED,
            key,
            value,
        )
        return None if prev is None else self._dec(prev[0])

    def fast_put(self, key: Any, value: Any) -> bool:
        """→ RMap#fastPut: True iff the key was new (skips prev fetch)."""
        with self._store.lock:
            e = self._entry()
            kb = self._enc_key(key)
            existed = e.value.live(kb) is not None
            e.value.data[kb] = [self._enc(value), None, None, time.time()]
        self._emit(
            self._EVENT_UPDATED if existed else self._EVENT_CREATED, key, value
        )
        return not existed

    def put_if_absent(self, key: Any, value: Any) -> Any:
        with self._store.lock:
            e = self._entry()
            kb = self._enc_key(key)
            cur = e.value.live(kb)
            if cur is not None:
                return self._dec(cur[0])
            e.value.data[kb] = [self._enc(value), None, None, time.time()]
        self._emit(self._EVENT_CREATED, key, value)
        return None

    def get(self, key: Any) -> Any:
        with self._store.lock:
            e = self._entry(create=False)
            if e is None:
                return None
            slot = e.value.live(self._enc_key(key), touch=True)
            return None if slot is None else self._dec(slot[0])

    def get_all(self, keys: Iterable[Any]) -> dict:
        with self._store.lock:
            out = {}
            for k in keys:
                v = self.get(k)
                if v is not None:
                    out[k] = v
            return out

    def put_all(self, mapping: dict) -> None:
        with self._store.lock:
            for k, v in mapping.items():
                self.fast_put(k, v)

    def remove(self, key: Any, expected: Any = _MISSING) -> Any:
        """→ RMap#remove(key) / remove(key, value)."""
        with self._store.lock:
            e = self._entry(create=False)
            if e is None:
                return None if expected is _MISSING else False
            kb = self._enc_key(key)
            slot = e.value.live(kb)
            if slot is None:
                return None if expected is _MISSING else False
            if expected is not _MISSING:
                if slot[0] != self._enc(expected):
                    return False
                del e.value.data[kb]
                removed = True
            else:
                del e.value.data[kb]
                removed = False
        self._emit(self._EVENT_REMOVED, key, self._dec(slot[0]))
        return True if removed else self._dec(slot[0])

    def fast_remove(self, *keys: Any) -> int:
        removed = []
        with self._store.lock:
            e = self._entry(create=False)
            if e is None:
                return 0
            for k in keys:
                kb = self._enc_key(k)
                if e.value.live(kb) is not None:
                    del e.value.data[kb]
                    removed.append(k)
        for k in removed:
            self._emit(self._EVENT_REMOVED, k, None)
        return len(removed)

    def replace(self, key: Any, value: Any, new_value: Any = _MISSING):
        """→ RMap#replace(key, newValue) returning the previous value, or
        RMap#replace(key, oldValue, newValue) returning success."""
        with self._store.lock:
            e = self._entry(create=False)
            if e is None:
                return None if new_value is _MISSING else False
            kb = self._enc_key(key)
            slot = e.value.live(kb)
            if slot is None:
                return None if new_value is _MISSING else False
            if new_value is not _MISSING:
                if slot[0] != self._enc(value):
                    return False
                slot[0] = self._enc(new_value)
                out = True
                emitted = new_value
            else:
                out = self._dec(slot[0])
                slot[0] = self._enc(value)
                emitted = value
        self._emit(self._EVENT_UPDATED, key, emitted)
        return out

    def contains_key(self, key: Any) -> bool:
        with self._store.lock:
            e = self._entry(create=False)
            return e is not None and e.value.live(self._enc_key(key)) is not None

    def contains_value(self, value: Any) -> bool:
        vb = self._enc(value)
        with self._store.lock:
            e = self._entry(create=False)
            if e is None:
                return False
            now = time.time()
            return any(
                e.value.live(kb, now) is not None and e.value.data.get(kb, [None])[0] == vb
                for kb in list(e.value.data.keys())
            )

    def size(self) -> int:
        with self._store.lock:
            e = self._entry(create=False)
            if e is None:
                return 0
            e.value.prune_expired(time.time())
            return len(e.value.data)

    def is_empty(self) -> bool:
        return self.size() == 0

    def add_and_get(self, key: Any, delta) -> Any:
        """→ RMap#addAndGet (HINCRBY analog on the decoded value)."""
        with self._store.lock:
            cur = self.get(key) or 0
            new = cur + delta
            self.fast_put(key, new)
            return new

    # -- views -------------------------------------------------------------

    def key_set(self, pattern: Optional[str] = None) -> list:
        import fnmatch

        with self._store.lock:
            e = self._entry(create=False)
            if e is None:
                return []
            e.value.prune_expired(time.time())
            keys = [self._dec_key(kb) for kb in e.value.data.keys()]
            if pattern is not None:
                keys = [k for k in keys if fnmatch.fnmatchcase(str(k), pattern)]
            return keys

    def values(self) -> list:
        with self._store.lock:
            e = self._entry(create=False)
            if e is None:
                return []
            e.value.prune_expired(time.time())
            return [self._dec(slot[0]) for slot in e.value.data.values()]

    def entry_set(self) -> list:
        with self._store.lock:
            e = self._entry(create=False)
            if e is None:
                return []
            e.value.prune_expired(time.time())
            return [
                (self._dec_key(kb), self._dec(slot[0]))
                for kb, slot in e.value.data.items()
            ]

    def key_iterator(self, pattern: Optional[str] = None, count: int = 10):
        """HSCAN-cursor idiom: lazy snapshot iteration in chunks (see
        Keys.scan_iterator for the guarantee)."""
        from redisson_tpu.grid.keys import _chunked_snapshot_iter

        return _chunked_snapshot_iter(lambda: self.key_set(pattern), count)

    def entry_iterator(self, count: int = 10):
        for k in self.key_iterator(count=count):
            v = self.get(k)
            if v is not None:
                yield (k, v)

    def read_all_map(self) -> dict:
        return dict(self.entry_set())

    def clear(self) -> bool:
        return self.delete()

    # dict-protocol sugar
    def __getitem__(self, key):
        return self.get(key)

    def __setitem__(self, key, value):
        self.fast_put(key, value)

    def __contains__(self, key):
        return self.contains_key(key)

    def __len__(self):
        return self.size()


@journaled("put", "fast_put", "put_if_absent", "add_and_get")
class MapCache(Map):
    """→ org/redisson/RedissonMapCache.java: RMap + per-entry TTL/max-idle.
    The grid sweeper calls ``prune_expired`` (the MapCacheEvictionTask
    analog); reads prune lazily as in the reference's Lua guards."""

    KIND = "mapcache"

    def put(self, key: Any, value: Any, ttl_seconds: Optional[float] = None,
            max_idle_seconds: Optional[float] = None) -> Any:
        with self._store.lock:
            prev = self.get(key)
            self._put_slot(key, value, ttl_seconds, max_idle_seconds)
        self._emit(
            self._EVENT_UPDATED if prev is not None else self._EVENT_CREATED,
            key,
            value,
        )
        return prev

    def fast_put(self, key: Any, value: Any, ttl_seconds: Optional[float] = None,
                 max_idle_seconds: Optional[float] = None) -> bool:
        with self._store.lock:
            e = self._entry()
            existed = e.value.live(self._enc_key(key)) is not None
            self._put_slot(key, value, ttl_seconds, max_idle_seconds)
        self._emit(
            self._EVENT_UPDATED if existed else self._EVENT_CREATED, key, value
        )
        return not existed

    def put_if_absent(self, key: Any, value: Any, ttl_seconds: Optional[float] = None,
                      max_idle_seconds: Optional[float] = None) -> Any:
        with self._store.lock:
            cur = self.get(key)
            if cur is not None:
                return cur
            self._put_slot(key, value, ttl_seconds, max_idle_seconds)
        self._emit(self._EVENT_CREATED, key, value)
        return None

    def add_and_get(self, key: Any, delta) -> Any:
        """→ RMapCache#addAndGet: the numeric update must PRESERVE the
        entry's TTL/max-idle (the inherited path rewrote the slot with
        fresh None timeouts — a 10s-TTL counter became immortal)."""
        with self._store.lock:
            e = self._entry()
            kb = self._enc_key(key)
            slot = e.value.live(kb)
            cur = 0 if slot is None else self._dec(slot[0])
            new = (cur or 0) + delta
            if slot is None:
                e.value.data[kb] = [self._enc(new), None, None, __import__("time").time()]
            else:
                slot[0] = self._enc(new)  # timeouts untouched
            return new

    def _put_slot(self, key, value, ttl_s, idle_s) -> None:
        e = self._entry()
        now = time.time()
        exp = None if ttl_s is None else now + float(ttl_s)
        e.value.data[self._enc_key(key)] = [
            self._enc(value), exp, None if idle_s is None else float(idle_s), now
        ]

    def remain_time_to_live_entry(self, key: Any) -> int:
        """Entry-level TTL in ms (-2 absent, -1 no TTL)."""
        with self._store.lock:
            e = self._entry(create=False)
            slot = None if e is None else e.value.live(self._enc_key(key))
            if slot is None:
                return -2
            if slot[1] is None:
                return -1
            return max(0, int((slot[1] - time.time()) * 1000))
