"""Multimaps — → org/redisson/RedissonListMultimap.java,
RedissonSetMultimap.java (+ the *Cache variants with per-KEY TTL,
→ RedissonListMultimapCache.java / RedissonSetMultimapCache.java).

Reference layout: one Redis hash mapping key→bucket-id plus one
list/set per bucket; here one entry holds key-bytes → container of value
bytes.  Cache variants carry a per-key expiry (RMultimapCache#expireKey),
pruned lazily and by the grid sweeper.
"""

from __future__ import annotations

import time
from typing import Any, Iterable, Optional

from redisson_tpu.grid.base import GridObject


class _MultimapValue:
    """key bytes -> {"vals": list[bytes] | list-as-set, "expire_at": float|None}."""

    __slots__ = ("data",)

    def __init__(self):
        self.data: dict[bytes, dict] = {}

    def live(self, kb: bytes, now: Optional[float] = None):
        slot = self.data.get(kb)
        if slot is None:
            return None
        if slot["expire_at"] is not None and (now or time.time()) >= slot["expire_at"]:
            del self.data[kb]
            return None
        return slot

    def prune_expired(self, now: float) -> None:
        for kb in list(self.data.keys()):
            self.live(kb, now)


class _BaseMultimap(GridObject):
    SET_SEMANTICS = False

    @staticmethod
    def _new_value():
        return _MultimapValue()

    def _slot(self, kb: bytes, create: bool):
        e = self._entry(create=create)
        if e is None:
            return None
        slot = e.value.live(kb)
        if slot is None and create:
            # Set semantics: value-bytes -> count-of-1 dict (insertion-
            # ordered, O(1) membership).  List semantics: plain list with
            # duplicates.
            slot = {
                "vals": {} if self.SET_SEMANTICS else [],
                "expire_at": None,
            }
            e.value.data[kb] = slot
        return slot

    # -- core --------------------------------------------------------------

    def _add_locked(self, slot, vb: bytes) -> bool:
        vals = slot["vals"]
        if self.SET_SEMANTICS:
            if vb in vals:
                return False
            vals[vb] = None
            return True
        vals.append(vb)
        return True

    def put(self, key: Any, value: Any) -> bool:
        """→ RMultimap#put: True if the multimap changed."""
        with self._store.lock:
            slot = self._slot(self._enc_key(key), create=True)
            return self._add_locked(slot, self._enc(value))

    def put_all(self, key: Any, values: Iterable[Any]) -> bool:
        with self._store.lock:
            slot = self._slot(self._enc_key(key), create=True)
            changed = False
            for v in values:
                changed |= self._add_locked(slot, self._enc(v))
            return changed

    def get_all(self, key: Any) -> list:
        """→ RMultimap#getAll (a snapshot copy, like the reference's
        readAll on the bucket)."""
        with self._store.lock:
            slot = self._slot(self._enc_key(key), create=False)
            return [] if slot is None else [self._dec(v) for v in slot["vals"]]

    get = get_all  # reference's live-view get(); snapshot here

    def remove(self, key: Any, value: Any) -> bool:
        """→ RMultimap#remove: removes ONE occurrence."""
        with self._store.lock:
            slot = self._slot(self._enc_key(key), create=False)
            if slot is None:
                return False
            vb = self._enc(value)
            if self.SET_SEMANTICS:
                if vb not in slot["vals"]:
                    return False
                del slot["vals"][vb]
            else:
                try:
                    slot["vals"].remove(vb)
                except ValueError:
                    return False
            if not slot["vals"]:
                self._drop_key(self._enc_key(key))
            return True

    def remove_all(self, key: Any) -> list:
        """→ RMultimap#removeAll: drops the key, returns its old values."""
        with self._store.lock:
            kb = self._enc_key(key)
            slot = self._slot(kb, create=False)
            if slot is None:
                return []
            vals = [self._dec(v) for v in slot["vals"]]
            self._drop_key(kb)
            return vals

    def _drop_key(self, kb: bytes) -> None:
        e = self._entry(create=False)
        if e is not None:
            e.value.data.pop(kb, None)

    def contains_key(self, key: Any) -> bool:
        with self._store.lock:
            return self._slot(self._enc_key(key), create=False) is not None

    def contains_value(self, value: Any) -> bool:
        with self._store.lock:
            e = self._entry(create=False)
            if e is None:
                return False
            vb = self._enc(value)
            now = time.time()
            return any(
                vb in slot["vals"]
                for kb, slot in list(e.value.data.items())
                if e.value.live(kb, now) is not None
            )

    def contains_entry(self, key: Any, value: Any) -> bool:
        with self._store.lock:
            slot = self._slot(self._enc_key(key), create=False)
            return slot is not None and self._enc(value) in slot["vals"]

    def key_set(self) -> list:
        with self._store.lock:
            e = self._entry(create=False)
            if e is None:
                return []
            now = time.time()
            return [
                self._dec_key(kb)
                for kb in list(e.value.data.keys())
                if e.value.live(kb, now) is not None
            ]

    def key_size(self) -> int:
        # Count live keys WITHOUT decoding them (a decode per key just to
        # take a len() pays full codec cost under the store lock).
        with self._store.lock:
            e = self._entry(create=False)
            if e is None:
                return 0
            now = time.time()
            return sum(
                1
                for kb in list(e.value.data.keys())
                if e.value.live(kb, now) is not None
            )

    def values(self) -> list:
        with self._store.lock:
            e = self._entry(create=False)
            if e is None:
                return []
            now = time.time()
            out = []
            for kb in list(e.value.data.keys()):
                slot = e.value.live(kb, now)
                if slot is not None:
                    out.extend(self._dec(v) for v in slot["vals"])
            return out

    def entries(self) -> list:
        with self._store.lock:
            e = self._entry(create=False)
            if e is None:
                return []
            now = time.time()
            out = []
            for kb in list(e.value.data.keys()):
                slot = e.value.live(kb, now)
                if slot is not None:
                    k = self._dec_key(kb)
                    out.extend((k, self._dec(v)) for v in slot["vals"])
            return out

    def size(self) -> int:
        """→ RMultimap#size: total number of (key, value) pairs —
        counted from the slots directly (decoding every value only to
        discard it paid full codec cost under the store lock)."""
        with self._store.lock:
            e = self._entry(create=False)
            if e is None:
                return 0
            now = time.time()
            return sum(
                len(e.value.live(kb, now)["vals"])
                for kb in list(e.value.data.keys())
                if e.value.live(kb, now) is not None
            )

    def fast_remove(self, *keys: Any) -> int:
        """→ RMultimap#fastRemove(K...): number of keys dropped."""
        with self._store.lock:
            n = 0
            for k in keys:
                kb = self._enc_key(k)
                if self._slot(kb, create=False) is not None:
                    self._drop_key(kb)
                    n += 1
            return n


class ListMultimap(_BaseMultimap):
    """→ RListMultimap: duplicate values per key, insertion order."""

    KIND = "listmultimap"
    SET_SEMANTICS = False


class SetMultimap(_BaseMultimap):
    """→ RSetMultimap: distinct values per key (serialized-bytes equality)."""

    KIND = "setmultimap"
    SET_SEMANTICS = True


class _MultimapCacheMixin:
    """→ RMultimapCache#expireKey: per-KEY TTL."""

    def expire_key(self, key: Any, ttl_seconds: float) -> bool:
        with self._store.lock:
            slot = self._slot(self._enc_key(key), create=False)
            if slot is None:
                return False
            slot["expire_at"] = time.time() + float(ttl_seconds)
            return True

    def remain_key_ttl_ms(self, key: Any) -> int:
        with self._store.lock:
            slot = self._slot(self._enc_key(key), create=False)
            if slot is None:
                return -2
            if slot["expire_at"] is None:
                return -1
            return max(0, int((slot["expire_at"] - time.time()) * 1000))


class ListMultimapCache(_MultimapCacheMixin, ListMultimap):
    KIND = "listmultimapcache"


class SetMultimapCache(_MultimapCacheMixin, SetMultimap):
    KIND = "setmultimapcache"
