"""Queues — → org/redisson/RedissonQueue.java (RQueue over Redis lists),
RedissonDeque, RedissonBlockingQueue/Deque (BLPOP parked on the store
condition — the pub/sub-wakeup analog, SURVEY.md §3.3), RedissonDelayedQueue
(timeout ZSET + transfer task → here a timer thread moving due items into
the destination queue), RedissonPriorityQueue (comparator order),
RedissonRingBuffer (capacity-trimmed queue).
"""

from __future__ import annotations

import bisect
import threading
import time
from typing import Any, Iterable, Optional

from redisson_tpu.grid.base import GridObject, journaled


# "No element" marker distinct from a stored None: codecs encode None
# (b'null' / pickle) as a perfectly valid element, so blocking consumers
# must not use None to mean "queue empty" — that would silently destroy
# a popped None and park forever.
_EMPTY = object()


@journaled("offer", "offer_all", "poll", "poll_last_and_offer_first_to",
           "remove", "clear")
class Queue(GridObject):
    KIND = "list"  # queues are lists in Redis; share the kind (RQueue over RList)

    @staticmethod
    def _new_value():
        return []

    def _poll_raw(self, last: bool = False):
        """Pop one ENCODED element, or _EMPTY when none — the primitive
        every blocking consumer builds on (None-element safe)."""
        with self._store.lock:
            e = self._entry(create=False)
            if e is None or not e.value:
                return _EMPTY
            return e.value.pop(-1 if last else 0)

    def offer(self, value: Any) -> bool:
        with self._store.lock:
            self._entry().value.append(self._enc(value))
            self._store.notify()
            return True

    add = offer

    def offer_all(self, values: Iterable[Any]) -> bool:
        with self._store.lock:
            for v in values:
                self._entry().value.append(self._enc(v))
            self._store.notify()
            return True

    def poll(self) -> Any:
        with self._store.lock:
            e = self._entry(create=False)
            if e is None or not e.value:
                return None
            return self._dec(e.value.pop(0))

    def peek(self) -> Any:
        with self._store.lock:
            e = self._entry(create=False)
            if e is None or not e.value:
                return None
            return self._dec(e.value[0])

    def poll_last_and_offer_first_to(self, dest_name: str) -> Any:
        """→ RQueue#pollLastAndOfferFirstTo (RPOPLPUSH)."""
        with self._store.lock:
            # WRONGTYPE-check the destination BEFORE popping, so a kind
            # mismatch cannot lose the element.
            self._store.get_entry(dest_name, self.KIND)
            e = self._entry(create=False)
            if e is None or not e.value:
                return None
            vb = e.value.pop()
            dest = self._client.get_queue(dest_name)
            dest._entry().value.insert(0, vb)
            # The destination is mutated RAW (not through a decorated
            # method), so it journals here; the wrapper's own capture of
            # self follows with a higher seq, and its durability ack
            # covers this record too (fsync is seq-ordered).
            self._store._journal_capture(dest_name)
            self._store.notify()
            return self._dec(vb)

    def size(self) -> int:
        with self._store.lock:
            e = self._entry(create=False)
            return 0 if e is None else len(e.value)

    def is_empty(self) -> bool:
        return self.size() == 0

    def contains(self, value: Any) -> bool:
        with self._store.lock:
            e = self._entry(create=False)
            return e is not None and self._enc(value) in e.value

    def remove(self, value: Any) -> bool:
        with self._store.lock:
            e = self._entry(create=False)
            if e is None:
                return False
            vb = self._enc(value)
            if vb not in e.value:
                return False
            e.value.remove(vb)
            return True

    def clear(self) -> bool:
        return self.delete()

    def read_all(self) -> list:
        with self._store.lock:
            e = self._entry(create=False)
            return [] if e is None else [self._dec(vb) for vb in e.value]

    def __len__(self):
        return self.size()


@journaled("add_first", "add_last", "poll_first", "poll_last")
class Deque(Queue):
    """→ RedissonDeque: double-ended ops."""

    def add_first(self, value: Any) -> None:
        with self._store.lock:
            self._entry().value.insert(0, self._enc(value))
            self._store.notify()

    def add_last(self, value: Any) -> None:
        self.offer(value)

    offer_first = add_first
    offer_last = add_last

    def poll_first(self) -> Any:
        return self.poll()

    def poll_last(self) -> Any:
        with self._store.lock:
            e = self._entry(create=False)
            if e is None or not e.value:
                return None
            return self._dec(e.value.pop())

    def peek_first(self) -> Any:
        return self.peek()

    def peek_last(self) -> Any:
        with self._store.lock:
            e = self._entry(create=False)
            if e is None or not e.value:
                return None
            return self._dec(e.value[-1])


@journaled("poll", "take", "put", "drain_to", "poll_from_any")
class BlockingQueue(Queue):
    """→ RedissonBlockingQueue: poll with timeout parks on the store
    condition until an offer lands (the BLPOP pub/sub-wakeup analog)."""

    def poll(self, timeout_seconds: Optional[float] = None) -> Any:
        if timeout_seconds is None:
            return super().poll()
        deadline = time.monotonic() + timeout_seconds
        with self._store.cond:
            while True:
                vb = self._poll_raw()
                if vb is not _EMPTY:
                    return self._dec(vb)
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return None
                self._store.cond.wait(timeout=remaining)

    def take(self) -> Any:
        with self._store.cond:
            while True:
                vb = self._poll_raw()
                if vb is not _EMPTY:
                    return self._dec(vb)
                self._store.cond.wait(timeout=1.0)

    def put(self, value: Any) -> None:
        self.offer(value)

    def drain_to(self, collection: list, max_elements: Optional[int] = None) -> int:
        with self._store.lock:
            n = 0
            while max_elements is None or n < max_elements:
                vb = self._poll_raw()
                if vb is _EMPTY:
                    break
                collection.append(self._dec(vb))
                n += 1
            return n

    def poll_from_any(self, timeout_seconds: float, *queue_names: str) -> Any:
        """→ RBlockingQueue#pollFromAny (BLPOP over several keys)."""
        queues = [self] + [self._client.get_blocking_queue(n) for n in queue_names]
        deadline = time.monotonic() + timeout_seconds
        with self._store.cond:
            while True:
                for q in queues:
                    vb = q._poll_raw()
                    if vb is not _EMPTY:
                        return q._dec(vb)
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return None
                self._store.cond.wait(timeout=remaining)


@journaled("poll_first", "poll_last")
class BlockingDeque(BlockingQueue, Deque):
    """→ RedissonBlockingDeque."""

    def poll_first(self, timeout_seconds: Optional[float] = None) -> Any:
        return BlockingQueue.poll(self, timeout_seconds)

    def poll_last(self, timeout_seconds: Optional[float] = None) -> Any:
        if timeout_seconds is None:
            return Deque.poll_last(self)
        deadline = time.monotonic() + timeout_seconds
        with self._store.cond:
            while True:
                vb = self._poll_raw(last=True)
                if vb is not _EMPTY:
                    return self._dec(vb)
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return None
                self._store.cond.wait(timeout=remaining)


class DelayedQueue(GridObject):
    """→ org/redisson/RedissonDelayedQueue.java: offer(value, delay) holds
    the value in a timeout structure; a transfer thread moves due items to
    the destination queue (the reference's scheduled transfer task)."""

    KIND = "delayedqueue"

    def __init__(self, name: str, client, destination: Queue):
        super().__init__(name, client)
        # The transfer task appends raw encoded bytes into the
        # destination's backing LIST — only plain list-backed queues
        # qualify (a RingBuffer's dict value or a PriorityQueue's tuple
        # list would crash the timer thread or corrupt the structure).
        if not isinstance(destination, Queue) or not isinstance(
            destination._new_value(), list
        ):
            raise TypeError(
                "DelayedQueue destination must be a plain list-backed "
                f"queue, not {type(destination).__name__}"
            )
        self._dest = destination
        self._timer: Optional[threading.Timer] = None

    @staticmethod
    def _new_value():
        return []  # sorted list of (due_epoch, seq, value bytes)

    _seq = 0

    def offer(self, value: Any, delay_seconds: float) -> None:
        due = time.time() + float(delay_seconds)
        with self._store.lock:
            e = self._entry()
            DelayedQueue._seq += 1
            bisect.insort(e.value, (due, DelayedQueue._seq, self._enc(value)))
            self._schedule_transfer()

    def _schedule_transfer(self) -> None:
        e = self._entry(create=False)
        if e is None or not e.value:
            return
        delay = max(0.0, e.value[0][0] - time.time())
        if self._timer is not None:
            self._timer.cancel()
        self._timer = threading.Timer(delay, self._transfer_due)
        self._timer.daemon = True
        self._timer.start()

    def _transfer_due(self) -> None:
        with self._store.lock:
            e = self._entry(create=False)
            if e is None:
                return
            now = time.time()
            while e.value and e.value[0][0] <= now:
                _, _, vb = e.value.pop(0)
                self._dest._entry().value.append(vb)
            self._store.notify()
            if e.value:
                self._schedule_transfer()

    def size(self) -> int:
        with self._store.lock:
            e = self._entry(create=False)
            return 0 if e is None else len(e.value)

    def read_all(self) -> list:
        with self._store.lock:
            e = self._entry(create=False)
            return [] if e is None else [self._dec(vb) for _, _, vb in e.value]

    def remove(self, value: Any) -> bool:
        with self._store.lock:
            e = self._entry(create=False)
            if e is None:
                return False
            vb = self._enc(value)
            for i, (_, _, b) in enumerate(e.value):
                if b == vb:
                    e.value.pop(i)
                    return True
            return False


class PriorityQueue(GridObject):
    """→ RedissonPriorityQueue: natural-order poll."""

    KIND = "priorityqueue"

    @staticmethod
    def _new_value():
        return []  # sorted list of (value, value bytes)

    def offer(self, value: Any) -> bool:
        with self._store.lock:
            e = self._entry()
            bisect.insort(e.value, (value, self._enc(value)), key=lambda t: t[0])
            self._store.notify()
            return True

    add = offer

    def _poll_raw(self, last: bool = False):
        with self._store.lock:
            e = self._entry(create=False)
            if e is None or not e.value:
                return _EMPTY
            return e.value.pop(-1 if last else 0)[1]

    def poll(self) -> Any:
        vb = self._poll_raw()
        return None if vb is _EMPTY else self._dec(vb)

    def peek(self) -> Any:
        with self._store.lock:
            e = self._entry(create=False)
            if e is None or not e.value:
                return None
            return self._dec(e.value[0][1])

    def size(self) -> int:
        with self._store.lock:
            e = self._entry(create=False)
            return 0 if e is None else len(e.value)

    def read_all(self) -> list:
        with self._store.lock:
            e = self._entry(create=False)
            return [] if e is None else [v for v, _ in e.value]


@journaled("offer", "offer_all", "poll", "remove",
           "poll_last_and_offer_first_to", "try_set_capacity",
           "set_capacity")
class RingBuffer(Queue):
    """→ RedissonRingBuffer: bounded queue; offers past capacity evict the
    oldest elements.

    The backing value is {"cap", "items"} rather than Queue's plain list,
    so every inherited method that walks the value is overridden below.
    """

    KIND = "ringbuffer"

    @staticmethod
    def _new_value():
        return {"cap": 0, "items": []}

    def offer_all(self, values: Iterable[Any]) -> bool:
        with self._store.lock:
            for v in values:
                self.offer(v)
            return True

    def contains(self, value: Any) -> bool:
        with self._store.lock:
            e = self._entry(create=False)
            return e is not None and self._enc(value) in e.value["items"]

    def remove(self, value: Any) -> bool:
        with self._store.lock:
            e = self._entry(create=False)
            if e is None:
                return False
            vb = self._enc(value)
            if vb not in e.value["items"]:
                return False
            e.value["items"].remove(vb)
            return True

    def poll_last_and_offer_first_to(self, dest_name: str) -> Any:
        with self._store.lock:
            self._store.get_entry(dest_name, Queue.KIND)
            e = self._entry(create=False)
            if e is None or not e.value["items"]:
                return None
            vb = e.value["items"].pop()
            self._client.get_queue(dest_name)._entry().value.insert(0, vb)
            self._store.notify()
            return self._dec(vb)

    def try_set_capacity(self, capacity: int) -> bool:
        with self._store.lock:
            e = self._entry()
            if e.value["cap"]:
                return False
            e.value["cap"] = int(capacity)
            return True

    def set_capacity(self, capacity: int) -> None:
        with self._store.lock:
            e = self._entry()
            e.value["cap"] = int(capacity)
            self._trim(e)

    def capacity(self) -> int:
        with self._store.lock:
            e = self._entry(create=False)
            return 0 if e is None else e.value["cap"]

    def remaining_capacity(self) -> int:
        with self._store.lock:
            e = self._entry(create=False)
            if e is None:
                return 0
            return max(0, e.value["cap"] - len(e.value["items"]))

    def _trim(self, e) -> None:
        cap = e.value["cap"]
        if cap:
            del e.value["items"][: max(0, len(e.value["items"]) - cap)]

    def offer(self, value: Any) -> bool:
        with self._store.lock:
            e = self._entry()
            if not e.value["cap"]:
                raise RuntimeError("RingBuffer capacity is not set")
            e.value["items"].append(self._enc(value))
            self._trim(e)
            self._store.notify()
            return True

    add = offer

    def poll(self) -> Any:
        with self._store.lock:
            e = self._entry(create=False)
            if e is None or not e.value["items"]:
                return None
            return self._dec(e.value["items"].pop(0))

    def peek(self) -> Any:
        with self._store.lock:
            e = self._entry(create=False)
            if e is None or not e.value["items"]:
                return None
            return self._dec(e.value["items"][0])

    def size(self) -> int:
        with self._store.lock:
            e = self._entry(create=False)
            return 0 if e is None else len(e.value["items"])

    def read_all(self) -> list:
        with self._store.lock:
            e = self._entry(create=False)
            return [] if e is None else [self._dec(vb) for vb in e.value["items"]]


class PriorityBlockingQueue(PriorityQueue):
    """→ RedissonPriorityBlockingQueue: natural-order poll with blocking
    take/poll(timeout) parked on the store condition."""

    KIND = "priorityqueue"

    def poll(self, timeout_seconds: Optional[float] = None) -> Any:
        if timeout_seconds is None:
            return super().poll()
        deadline = time.monotonic() + timeout_seconds
        with self._store.cond:
            while True:
                vb = self._poll_raw()
                if vb is not _EMPTY:
                    return self._dec(vb)
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return None
                self._store.cond.wait(timeout=remaining)

    def take(self) -> Any:
        with self._store.cond:
            while True:
                vb = self._poll_raw()
                if vb is not _EMPTY:
                    return self._dec(vb)
                self._store.cond.wait(timeout=1.0)

    def put(self, value: Any) -> None:
        self.offer(value)


class PriorityDeque(PriorityQueue):
    """→ RedissonPriorityDeque: priority order with access to BOTH ends
    (pollFirst = smallest, pollLast = largest)."""

    KIND = "priorityqueue"

    def poll_first(self) -> Any:
        return self.poll()

    def poll_last(self) -> Any:
        with self._store.lock:
            e = self._entry(create=False)
            if e is None or not e.value:
                return None
            return self._dec(e.value.pop()[1])

    def peek_first(self) -> Any:
        return self.peek()

    def peek_last(self) -> Any:
        with self._store.lock:
            e = self._entry(create=False)
            if e is None or not e.value:
                return None
            return self._dec(e.value[-1][1])

class _TransferHandle(bytes):
    """bytes subclass used only for its guaranteed-fresh identity (the
    constructor can never return an interned builtin-bytes singleton)."""

    __slots__ = ()


@journaled("transfer", "try_transfer", "poll", "take", "drain_to")
class TransferQueue(BlockingQueue):
    """→ RTransferQueue (java.util.concurrent.TransferQueue semantics):
    ``transfer`` blocks until a consumer takes the element; plain
    offer/poll still behave like a queue.

    Elements are PLAIN encoded bytes in the same list shape as every
    other queue (KIND "list" — one namespace with RQueue/RList, so
    RPOPLPUSH/poll_from_any/RESP LPOP all interoperate).  A pending
    transfer is tracked by the IDENTITY of its bytes object: the
    transferer waits until that exact object leaves the queue's CURRENT
    backing list — any consumer path that removes it (poll, take, LPOP,
    a move to another queue, remove(), even DEL of the whole key)
    completes the handoff."""

    def _transfer_locked(self, value: Any, deadline: Optional[float]) -> bool:
        """Caller holds the store cond.  Appends the offer, waits for a
        consumer to take it; withdraws on timeout."""
        vb = self._enc(value)
        if isinstance(vb, str):
            vb = vb.encode()
        # Identity tracking needs a DISTINCT object per transfer call:
        # ByteArrayCodec.encode returns its input unchanged, and CPython
        # interns empty/1-byte bytes (bytes(bytearray(b'a')) is b'a'), so
        # any plain-bytes copy can still alias two concurrent transfers
        # of the same value under one identity.  A bytes-subclass
        # instance is never the cached singleton, behaves as bytes
        # everywhere else, and decodes identically for consumers.
        vb = _TransferHandle(vb)
        self._entry().value.append(vb)
        self._store.cond.notify_all()
        while True:
            # Re-resolve the entry EVERY iteration: clear()/DEL swaps the
            # backing list, and a stale reference would strand this wait
            # forever on an orphaned list no consumer can reach.
            e = self._entry(create=False)
            if e is None or not any(s is vb for s in e.value):
                return True  # consumed (or the key itself was consumed)
            remaining = (
                None if deadline is None else deadline - time.monotonic()
            )
            if remaining is not None and remaining <= 0:
                for i, s in enumerate(e.value):
                    if s is vb:  # identity, not equality: duplicates of
                        del e.value[i]  # the same VALUE must survive
                        return False
                return True  # taken between checks
            self._store.cond.wait(
                timeout=1.0 if remaining is None else min(1.0, remaining)
            )

    def transfer(self, value: Any, timeout_seconds: Optional[float] = None) -> bool:
        """Blocks until a consumer removes the element; False on timeout
        (the element is withdrawn, tryTransfer-with-timeout semantics)."""
        deadline = (
            None
            if timeout_seconds is None
            else time.monotonic() + timeout_seconds
        )
        with self._store.cond:
            return self._transfer_locked(value, deadline)

    def _waiting_count(self, delta: int = 0) -> int:
        """Waiting-consumer count shared across every handle of this queue
        (kept on the store, keyed by name — handle-local state would make
        hasWaitingConsumer lie between handles)."""
        reg = self._store.__dict__.setdefault("_tq_waiting", {})
        reg[self._name] = reg.get(self._name, 0) + delta
        return reg[self._name]

    def try_transfer(self, value: Any) -> bool:
        """Immediate handoff: succeeds only if a consumer is waiting AT
        the moment of the call.  The waiting-check and the offer happen
        under ONE cond hold (no check-then-act gap); the short grace wait
        only covers the woken consumer's re-acquisition of the lock."""
        with self._store.cond:
            if self._waiting_count() <= 0:
                return False
            return self._transfer_locked(
                value, time.monotonic() + 1.0
            )

    def poll(self, timeout_seconds: Optional[float] = None) -> Any:
        deadline = (
            None
            if timeout_seconds is None
            else time.monotonic() + timeout_seconds
        )
        with self._store.cond:
            self._waiting_count(+1)
            try:
                while True:
                    vb = self._poll_raw()
                    if vb is not _EMPTY:
                        self._store.cond.notify_all()  # wake transferers
                        return self._dec(vb)
                    if deadline is None:
                        return None
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        return None
                    self._store.cond.wait(timeout=min(1.0, remaining))
            finally:
                self._waiting_count(-1)

    def take(self) -> Any:
        with self._store.cond:
            self._waiting_count(+1)
            try:
                while True:
                    vb = self._poll_raw()
                    if vb is not _EMPTY:
                        self._store.cond.notify_all()
                        return self._dec(vb)
                    self._store.cond.wait(timeout=1.0)
            finally:
                self._waiting_count(-1)

    def drain_to(self, collection: list, max_elements: Optional[int] = None) -> int:
        n = super().drain_to(collection, max_elements)
        if n:
            with self._store.cond:
                self._store.cond.notify_all()  # wake transferers
        return n

    def has_waiting_consumer(self) -> bool:
        with self._store.lock:
            return self._waiting_count() > 0

    def remove(self, value: Any) -> bool:
        """Removing a pending-transfer element counts as consuming it —
        the blocked transferer resolves True."""
        with self._store.cond:
            ok = super().remove(value)
            if ok:
                self._store.cond.notify_all()
            return ok
