"""Coordination services — the reference's service layer (SURVEY.md §2.3
services row):

- ``ExecutorService`` → org/redisson/executor/ (RExecutorService +
  RScheduledExecutorService): tasks serialize into a grid queue; worker
  threads (the RedissonNode analog) poll and execute; futures resolve
  through a per-task response slot.
- ``RemoteService`` → org/redisson/remote/ (RRemoteService): method
  invocations ride a request queue to a registered implementation;
  responses return on per-invocation channels with ack semantics.
- ``Transaction`` → org/redisson/transaction/ (RTransaction): optimistic
  — reads collect a validation set, writes buffer in an operation log,
  commit validates + applies atomically under the store lock.
- ``ScriptService`` → RScript/RFunction: named procedures executed
  ATOMICALLY against the grid (the Lua-atomicity analog; procedures are
  Python callables — there is no Lua VM here, by design).
- ``LiveObjectService`` → org/redisson/liveobject/: attribute-mapped
  proxies whose fields live in an RMap.
- ``MapReduce`` → org/redisson/mapreduce/: mapper/reducer over map
  entries fanned out on the executor service's workers.
"""

from __future__ import annotations

import threading
import time
import uuid
from typing import Any, Callable, Optional

from redisson_tpu.analysis import witness as _witness
from redisson_tpu.objects.base import CamelCompatMixin

_MISSING = object()


class TaskFuture:
    """RExecutorFuture analog."""

    def __init__(self):
        self._event = threading.Event()
        self._value = None
        self._error: Optional[BaseException] = None
        self._cancelled = False

    def _resolve(self, value=None, error=None):
        self._value = value
        self._error = error
        self._event.set()

    def cancel(self) -> bool:
        if self._event.is_set():
            return False
        self._cancelled = True
        self._event.set()
        return True

    def cancelled(self) -> bool:
        return self._cancelled

    def done(self) -> bool:
        return self._event.is_set()

    def result(self, timeout: Optional[float] = None):
        if not self._event.wait(timeout):
            raise TimeoutError("task result not ready")
        if self._cancelled:
            raise RuntimeError("task was cancelled")
        if self._error is not None:
            raise self._error
        return self._value

    get = result


class ExecutorService(CamelCompatMixin):
    """→ RExecutorService / RScheduledExecutorService.

    Tasks are (callable, args, kwargs) tuples on a named in-process queue;
    ``register_workers(n)`` is the RedissonNode analog — without workers,
    tasks queue but never run (exactly the reference's model where a
    separate worker JVM polls the task queue)."""

    def __init__(self, name: str, client):
        self._name = name
        self._client = client
        self._tasks: "list[tuple]" = []
        self._lock = _witness.named(
            threading.Lock(), "grid.services.executor"
        )
        self._cond = threading.Condition(self._lock)
        self._workers: list[threading.Thread] = []
        self._futures: dict[str, TaskFuture] = {}
        self._shutdown = False
        self._timer: Optional[threading.Thread] = None
        self._scheduled: list[tuple] = []  # (fire_at, period|None, task)
        self._periodic: set[str] = set()  # futures stay open for cancel()

    def get_name(self) -> str:
        return self._name

    # -- submission (→ RExecutorService#submit) -----------------------------

    def submit(self, fn: Callable, *args, **kwargs) -> TaskFuture:
        fut = TaskFuture()
        task_id = uuid.uuid4().hex
        with self._cond:
            if self._shutdown:
                raise RuntimeError("executor service is shut down")
            self._futures[task_id] = fut
            self._tasks.append((task_id, fn, args, kwargs))
            self._cond.notify()
        return fut

    def execute(self, fn: Callable, *args, **kwargs) -> None:
        """→ RExecutorService#execute (fire-and-forget)."""
        self.submit(fn, *args, **kwargs)

    # -- scheduling (→ RScheduledExecutorService) ---------------------------

    def schedule(self, fn: Callable, delay_seconds: float, *args, **kwargs) -> TaskFuture:
        fut = TaskFuture()
        task_id = uuid.uuid4().hex
        with self._cond:
            if self._shutdown:
                raise RuntimeError("executor service is shut down")
            self._futures[task_id] = fut
            self._scheduled.append(
                (time.monotonic() + delay_seconds, None, (task_id, fn, args, kwargs))
            )
            self._ensure_timer()
        return fut

    def schedule_at_fixed_rate(self, fn: Callable, initial_delay_seconds: float,
                               period_seconds: float, *args, **kwargs) -> TaskFuture:
        """Returns a future usable only for cancel() (like the reference's
        scheduled future for periodic tasks)."""
        fut = TaskFuture()
        task_id = uuid.uuid4().hex
        with self._cond:
            if self._shutdown:
                raise RuntimeError("executor service is shut down")
            self._futures[task_id] = fut
            self._periodic.add(task_id)
            self._scheduled.append(
                (
                    time.monotonic() + initial_delay_seconds,
                    period_seconds,
                    (task_id, fn, args, kwargs),
                )
            )
            self._ensure_timer()
        return fut

    def schedule_cron(self, fn: Callable, cron: str, *args, **kwargs) -> TaskFuture:
        """→ RScheduledExecutorService#schedule(cron) with the upstream
        CronExpression grammar (Quartz 6-field with seconds, or classic
        5-field).  Periodic: the returned future exists for cancel()."""
        from redisson_tpu.grid.cron import CronExpression

        expr = CronExpression(cron)
        fut = TaskFuture()
        task_id = uuid.uuid4().hex
        with self._cond:
            if self._shutdown:
                raise RuntimeError("executor service is shut down")
            self._futures[task_id] = fut
            self._periodic.add(task_id)
            delay = expr.next_after(time.time()) - time.time()
            self._scheduled.append(
                (time.monotonic() + delay, expr, (task_id, fn, args, kwargs))
            )
            self._ensure_timer()
        return fut

    def _ensure_timer(self) -> None:
        if self._timer is None or not self._timer.is_alive():
            self._timer = threading.Thread(
                target=self._timer_loop, name="rtpu-exec-timer", daemon=True
            )
            self._timer.start()

    def _timer_loop(self) -> None:
        while True:
            with self._cond:
                if self._shutdown:
                    return
                now = time.monotonic()
                due = [s for s in self._scheduled if s[0] <= now]
                self._scheduled = [s for s in self._scheduled if s[0] > now]
                for fire_at, period, task in due:
                    fut = self._futures.get(task[0])
                    if fut is not None and fut.cancelled():
                        # Cancelled periodic/cron tasks leave the tables
                        # for good — no re-arm, no future leak.
                        self._futures.pop(task[0], None)
                        self._periodic.discard(task[0])
                        continue
                    self._tasks.append(task)
                    if period is not None:
                        from redisson_tpu.grid.cron import CronExpression

                        if isinstance(period, CronExpression):
                            # Cron re-arm: wall-clock next fire mapped
                            # onto the monotonic timer.
                            delay = period.next_after(time.time()) - time.time()
                            self._scheduled.append((now + delay, period, task))
                        else:
                            self._scheduled.append(
                                (fire_at + period, period, task)
                            )
                if due:
                    self._cond.notify_all()
            time.sleep(0.02)

    # -- workers (→ RedissonNode / TasksRunnerService) ----------------------

    def register_workers(self, n: int = 1) -> None:
        for _ in range(n):
            t = threading.Thread(
                target=self._worker_loop, name=f"rtpu-exec-{self._name}",
                daemon=True,
            )
            self._workers.append(t)
            t.start()

    def _worker_loop(self) -> None:
        while True:
            with self._cond:
                while not self._tasks and not self._shutdown:
                    self._cond.wait(timeout=0.5)
                if self._shutdown and not self._tasks:
                    return
                task_id, fn, args, kwargs = self._tasks.pop(0)
            fut = self._futures.get(task_id)
            if fut is not None and fut.cancelled():
                with self._cond:
                    # Purge EVERY trace of the task: dropping only the
                    # future would let the timer loop re-arm the periodic
                    # entry (its cancelled-check reads _futures) — an
                    # uncancellable task running forever.
                    self._futures.pop(task_id, None)
                    self._periodic.discard(task_id)
                    self._scheduled = [
                        ent for ent in self._scheduled if ent[2][0] != task_id
                    ]
                continue
            # Periodic tasks keep their future OPEN (it exists for
            # cancel(), like the reference's scheduled future).
            resolve = fut is not None and task_id not in self._periodic
            try:
                value = fn(*args, **kwargs)
                if resolve and not fut.done():
                    fut._resolve(value=value)
            except BaseException as e:  # task errors resolve the future
                if resolve and not fut.done():
                    fut._resolve(error=e)
            finally:
                if resolve:  # one-shot futures leave the table once run
                    self._futures.pop(task_id, None)

    # -- lifecycle -----------------------------------------------------------

    def get_task_count(self) -> int:
        with self._lock:
            return len(self._tasks)

    def shutdown(self) -> None:
        with self._cond:
            self._shutdown = True
            # Scheduled-but-not-yet-due tasks will never fire: resolve
            # their futures with a rejection instead of leaving callers
            # blocked forever.
            for _fire_at, _period, task in self._scheduled:
                fut = self._futures.pop(task[0], None)
                if fut is not None and not fut.done():
                    fut._resolve(
                        error=RuntimeError("executor service shut down")
                    )
            self._scheduled.clear()
            self._cond.notify_all()

    def is_shutdown(self) -> bool:
        return self._shutdown


class RemoteService(CamelCompatMixin):
    """→ RRemoteService: request-queue RPC between a registered
    implementation and proxies (get())."""

    def __init__(self, name: str, client):
        self._name = name
        self._client = client
        self._impls: dict[str, tuple] = {}  # iface -> (impl, executor)
        self._lock = _witness.named(threading.Lock(), "grid.services.remote")

    def register(self, iface: str, impl: Any, workers: int = 1) -> None:
        """→ RRemoteService#register(Class, T, workers).  Re-registering
        an iface replaces the implementation and shuts down the previous
        registration's worker pool (it would otherwise leak its threads
        for the process lifetime)."""
        ex = ExecutorService(f"{self._name}:{iface}:workers", self._client)
        ex.register_workers(workers)
        with self._lock:
            prev = self._impls.get(iface)
            self._impls[iface] = (impl, ex)
        if prev is not None:
            prev[1].shutdown()

    def deregister(self, iface: str) -> None:
        with self._lock:
            got = self._impls.pop(iface, None)
        if got is not None:
            got[1].shutdown()

    def get(self, iface: str, timeout_seconds: float = 30.0,
            ack_timeout_seconds: float = 1.0):
        """→ RRemoteService#get(Class, executionTimeout, ackTimeout): sync
        proxy.  A worker must ACK pickup of the invocation within
        ``ack_timeout_seconds`` (the reference's ack message on the
        per-invocation response queue) or the call fails with
        RemoteServiceAckTimeoutException WITHOUT waiting the full
        execution timeout — the no-live-worker fast-fail."""
        service = self

        class _Proxy(CamelCompatMixin):
            def __getattr__(self, method):
                def call(*args, **kwargs):
                    with service._lock:
                        got = service._impls.get(iface)
                    if got is None:
                        raise RuntimeError(
                            f"no workers registered for {iface!r}"
                        )
                    impl, ex = got
                    ack = threading.Event()
                    target = getattr(impl, method)
                    # ack vs timeout is decided EXACTLY once under this
                    # lock: either the worker acks first (we await the
                    # result) or the caller times out first (the worker
                    # then refuses to start, so the invocation NEVER runs
                    # after an ack-timeout was reported — no invisible
                    # side effects).
                    gate_lock = threading.Lock()
                    state = {"v": "pending"}

                    def acked_call():
                        with gate_lock:
                            if state["v"] == "timedout":
                                return None  # late pickup: refuse to run
                            state["v"] = "acked"
                            ack.set()
                        return target(*args, **kwargs)

                    fut = ex.submit(acked_call)
                    if not ack.wait(ack_timeout_seconds):
                        with gate_lock:
                            if state["v"] == "pending":
                                state["v"] = "timedout"
                                fut.cancel()
                                raise RemoteServiceAckTimeoutException(
                                    f"no worker acked {iface}.{method} "
                                    f"within {ack_timeout_seconds}s"
                                )
                        # The worker won the race and is executing.
                    return fut.result(timeout_seconds)

                return call

        return _Proxy()

    def get_async(self, iface: str):
        """Async proxy: calls return TaskFutures."""
        service = self

        class _AsyncProxy(CamelCompatMixin):
            def __getattr__(self, method):
                def call(*args, **kwargs):
                    with service._lock:
                        got = service._impls.get(iface)
                    if got is None:
                        raise RuntimeError(
                            f"no workers registered for {iface!r}"
                        )
                    impl, ex = got
                    return ex.submit(getattr(impl, method), *args, **kwargs)

                return call

        return _AsyncProxy()


class RemoteServiceAckTimeoutException(RuntimeError):
    """→ org.redisson.remote.RemoteServiceAckTimeoutException: no worker
    acknowledged the invocation within the ack timeout."""


class TransactionException(RuntimeError):
    """→ org.redisson.transaction.TransactionException."""


class Transaction(CamelCompatMixin):
    """→ RTransaction (optimistic): reads collect a validation snapshot,
    writes buffer in an operation log; commit() validates every read
    under the store lock and applies the log atomically, raising
    TransactionException when a concurrent writer invalidated a read."""

    def __init__(self, client):
        self._client = client
        self._store = client._grid
        self._reads: dict[tuple, Any] = {}  # (name, key_bytes|None) -> snapshot
        # Set-membership reads validate as BOOLEANS: 'entry absent' and
        # 'entry exists, member absent' are the same observation (False),
        # unlike bucket/map reads where None is a distinct value.
        self._set_reads: dict[tuple, bool] = {}
        # Zset score reads validate by VALUE (score-or-None), distinct
        # from set membership booleans.
        self._score_reads: dict[tuple, Any] = {}
        self._writes: list[tuple] = []  # (apply_fn,)
        self._done = False

    # -- transactional facades ---------------------------------------------

    def get_bucket(self, name: str):
        return _TxBucket(self, name)

    def get_map(self, name: str):
        return _TxMap(self, name)

    def get_set(self, name: str):
        """→ RTransaction#getSet (upstream transactions cover sets too)."""
        return _TxSet(self, name)

    def get_list(self, name: str):
        """→ RTransaction-scoped list (upstream transactional breadth)."""
        return _TxList(self, name)

    def get_scored_sorted_set(self, name: str):
        """→ RedissonTransactionalSet's scored sibling."""
        return _TxScoredSortedSet(self, name)

    # -- commit/rollback -----------------------------------------------------

    def _check_open(self):
        if self._done:
            raise TransactionException("transaction already completed")

    def commit(self) -> None:
        self._check_open()
        self._done = True
        with self._store.lock:
            for (name, kb), snapshot in self._reads.items():
                cur = self._current(name, kb)
                if cur != snapshot:
                    raise TransactionException(
                        f"read of {name!r} invalidated by a concurrent write"
                    )
            for (name, kb), member in self._set_reads.items():
                if bool(self._current(name, kb)) != member:
                    raise TransactionException(
                        f"read of {name!r} invalidated by a concurrent write"
                    )
            for (name, kb), score in self._score_reads.items():
                if self._current_score(name, kb) != score:
                    raise TransactionException(
                        f"read of {name!r} invalidated by a concurrent write"
                    )
            # Pre-validate EVERY write target's kind BEFORE applying any
            # (write-only keys are not in the read-validation set): a
            # WRONGTYPE surfacing mid-apply would leave the log half-
            # committed — the atomicity contract this method documents.
            for name, kind, _fn in self._writes:
                if kind is None:
                    continue
                e = self._store.get_entry(name)
                if e is not None and e.kind != kind:
                    raise TransactionException(
                        f"WRONGTYPE: {name!r} holds a {e.kind}, "
                        f"transaction writes a {kind}"
                    )
            try:
                for _name, _kind, apply_fn in self._writes:
                    apply_fn()
            except BaseException as e:  # pragma: no cover — applies are
                raise TransactionException(  # pre-validated; belt+braces
                    f"transaction partially applied: {e!r}"
                ) from e
            self._store.cond.notify_all()

    def rollback(self) -> None:
        self._check_open()
        self._done = True
        self._reads.clear()
        self._writes.clear()

    def _register_read(self, table: dict, key: tuple, current_fn):
        """First-read-wins snapshot registration: repeated reads of the
        same key return the FIRST observation (repeatable reads within
        the tx) and commit validates against it — re-registering on
        every read would validate only the LAST observation, silently
        accepting a concurrent write between two in-tx reads."""
        if key in table:
            return table[key]
        cur = current_fn()
        table[key] = cur
        return cur

    def _current(self, name: str, kb: Optional[bytes]):
        e = self._store.get_entry(name)
        if e is None:
            return None
        if kb is None:
            if isinstance(e.value, list):
                # Whole-list reads snapshot CONTENTS (a tuple copy) —
                # the live list object always equals itself, which would
                # make validation vacuous.
                return tuple(e.value)
            return e.value
        if hasattr(e.value, "live"):  # map: per-key live slot
            slot = e.value.live(kb)
            return None if slot is None else slot[0]
        if isinstance(e.value, dict):  # set: membership snapshot
            return kb in e.value
        return None

    def _current_score(self, name: str, kb: bytes):
        """Zset score-or-None (distinct from set membership: a set's
        dict values are all None, so .get() cannot express membership)."""
        e = self._store.get_entry(name)
        if e is None or not isinstance(e.value, dict):
            return None
        return e.value.get(kb)


class _TxBucket:
    def __init__(self, tx: Transaction, name: str):
        self._tx = tx
        self._name = name
        self._codec = tx._client.config.codec
        self._local: Any = _MISSING

    def get(self):
        self._tx._check_open()
        if self._local is not _MISSING:
            return None if self._local is None else self._codec.decode(self._local)
        with self._tx._store.lock:
            def current():
                e = self._tx._store.get_entry(self._name, "bucket")
                return None if e is None else e.value
            snapshot = self._tx._register_read(
                self._tx._reads, (self._name, None), current
            )
            return None if snapshot is None else self._codec.decode(snapshot)

    def set(self, value) -> None:
        self._tx._check_open()
        vb = self._codec.encode(value)
        self._local = vb
        store, name = self._tx._store, self._name

        def apply():
            store.put_entry(name, "bucket", vb)

        self._tx._writes.append((name, "bucket", apply))

    def delete(self) -> None:
        self._tx._check_open()
        self._local = None
        store, name = self._tx._store, self._name
        self._tx._writes.append((name, None, lambda: store.delete(name)))


class _TxMap:
    def __init__(self, tx: Transaction, name: str):
        self._tx = tx
        self._name = name
        self._codec = tx._client.config.codec
        self._local: dict[bytes, Any] = {}

    def get(self, key):
        self._tx._check_open()
        kb = self._codec.encode_key(key)
        if kb in self._local:
            vb = self._local[kb]
            return None if vb is None else self._codec.decode(vb)
        with self._tx._store.lock:
            cur = self._tx._register_read(
                self._tx._reads, (self._name, kb),
                lambda: self._tx._current(self._name, kb),
            )
            return None if cur is None else self._codec.decode(cur)

    def put(self, key, value) -> None:
        self._tx._check_open()
        kb = self._codec.encode_key(key)
        vb = self._codec.encode(value)
        self._local[kb] = vb
        tx, name = self._tx, self._name

        def apply():
            from redisson_tpu.grid.maps import _MapValue

            e = tx._store.ensure_entry(name, "map", _MapValue)
            e.value.data[kb] = [vb, None, None, time.time()]

        self._tx._writes.append((name, "map", apply))

    def remove(self, key) -> None:
        self._tx._check_open()
        kb = self._codec.encode_key(key)
        self._local[kb] = None
        tx, name = self._tx, self._name

        def apply():
            e = tx._store.get_entry(name, "map")
            if e is not None:
                e.value.data.pop(kb, None)

        self._tx._writes.append((name, "map", apply))


class _TxSet:
    """Transactional set facade (→ org/redisson/transaction/
    RedissonTransactionalSet): contains() snapshots membership for
    commit-time validation; add/remove buffer in the operation log."""

    def __init__(self, tx: Transaction, name: str):
        self._tx = tx
        self._name = name
        self._codec = tx._client.config.codec
        self._local: dict[bytes, bool] = {}  # staged membership

    def contains(self, value) -> bool:
        self._tx._check_open()
        kb = self._codec.encode(value)
        if kb in self._local:
            return self._local[kb]
        with self._tx._store.lock:
            return self._tx._register_read(
                self._tx._set_reads, (self._name, kb),
                lambda: bool(self._tx._current(self._name, kb)),
            )

    def add(self, value) -> bool:
        added = not self.contains(value)
        kb = self._codec.encode(value)
        self._local[kb] = True
        tx, name = self._tx, self._name

        def apply():
            e = tx._store.ensure_entry(name, "set", dict)
            e.value[kb] = None

        tx._writes.append((name, "set", apply))
        return added

    def remove(self, value) -> bool:
        removed = self.contains(value)
        kb = self._codec.encode(value)
        self._local[kb] = False
        tx, name = self._tx, self._name

        def apply():
            e = tx._store.get_entry(name, "set")
            if e is not None:
                e.value.pop(kb, None)

        tx._writes.append((name, "set", apply))
        return removed


class _TxList:
    """Transactional list facade (→ org/redisson/transaction breadth):
    reads snapshot the WHOLE list contents for commit-time validation
    (list positions shift under concurrent writes, so per-index
    validation would be unsound).  Staged ops replay over the snapshot
    for reads (read-your-writes AND read-your-removes) and over the live
    list at commit — ONE apply closure registered on first mutation."""

    def __init__(self, tx: Transaction, name: str):
        self._tx = tx
        self._name = name
        self._codec = tx._client.config.codec
        self._ops: list[tuple] = []  # ("add"|"remove", value_bytes)
        self._registered = False

    def _snapshot(self) -> tuple:
        with self._tx._store.lock:
            # Snapshot None for an ABSENT key (commit-time _current also
            # yields None there — storing () made every read of a
            # not-yet-existing list fail validation spuriously).
            cur = self._tx._register_read(
                self._tx._reads, (self._name, None),
                lambda: self._tx._current(self._name, None),
            )
            return cur if isinstance(cur, tuple) else ()

    def _view(self) -> list:
        """Snapshot with this tx's staged ops replayed — what reads see."""
        out = list(self._snapshot())
        for op, vb in self._ops:
            if op == "add":
                out.append(vb)
            elif vb in out:
                out.remove(vb)
        return out

    def _ensure_apply(self) -> None:
        if self._registered:
            return
        self._registered = True
        tx, name, ops = self._tx, self._name, self._ops

        def apply():
            e = tx._store.ensure_entry(name, "list", list)
            for op, vb in ops:
                if op == "add":
                    e.value.append(vb)
                elif vb in e.value:
                    e.value.remove(vb)

        tx._writes.append((name, "list", apply))

    def read_all(self) -> list:
        self._tx._check_open()
        return [self._codec.decode(vb) for vb in self._view()]

    def size(self) -> int:
        self._tx._check_open()
        return len(self._view())

    def get(self, index: int):
        self._tx._check_open()
        return self.read_all()[index]

    def contains(self, value) -> bool:
        self._tx._check_open()
        return self._codec.encode(value) in self._view()

    def add(self, value) -> bool:
        self._tx._check_open()
        self._ops.append(("add", self._codec.encode(value)))
        self._ensure_apply()
        return True

    def remove(self, value) -> bool:
        self._tx._check_open()
        vb = self._codec.encode(value)
        present = vb in self._view()
        if present:
            self._ops.append(("remove", vb))
            self._ensure_apply()
        return present


class _TxScoredSortedSet:
    """Transactional scored-sorted-set facade: score reads validate by
    value at commit (see Transaction._score_reads); add/remove buffer."""

    def __init__(self, tx: Transaction, name: str):
        self._tx = tx
        self._name = name
        self._codec = tx._client.config.codec
        self._local: dict[bytes, Any] = {}  # staged member -> score|None

    def get_score(self, member):
        self._tx._check_open()
        kb = self._codec.encode(member)
        if kb in self._local:
            return self._local[kb]
        with self._tx._store.lock:
            return self._tx._register_read(
                self._tx._score_reads, (self._name, kb),
                lambda: self._tx._current_score(self._name, kb),
            )

    def contains(self, member) -> bool:
        return self.get_score(member) is not None

    def add(self, score: float, member) -> bool:
        fresh = not self.contains(member)
        kb = self._codec.encode(member)
        self._local[kb] = float(score)
        tx, name = self._tx, self._name
        sc = float(score)

        def apply():
            e = tx._store.ensure_entry(name, "zset", dict)
            e.value[kb] = sc

        tx._writes.append((name, "zset", apply))
        return fresh

    def remove(self, member) -> bool:
        present = self.contains(member)
        kb = self._codec.encode(member)
        self._local[kb] = None
        tx, name = self._tx, self._name

        def apply():
            e = tx._store.get_entry(name, "zset")
            if e is not None:
                e.value.pop(kb, None)

        tx._writes.append((name, "zset", apply))
        return present


class ScriptService(CamelCompatMixin):
    """→ RScript/RFunction analog: named procedures run ATOMICALLY against
    the grid (under the store lock — the Lua-script atomicity contract).
    Procedures are Python callables ``fn(client, keys, args)`` registered
    in-process; there is deliberately no Lua VM."""

    def __init__(self, client):
        self._client = client
        self._fns: dict[str, Callable] = {}
        self._lock = _witness.named(threading.Lock(), "grid.services.script")

    def register(self, name: str, fn: Callable) -> None:
        """→ SCRIPT LOAD (returns nothing; the name is the sha analog)."""
        with self._lock:
            self._fns[name] = fn

    def eval(self, name: str, keys: list = (), args: list = ()):
        """→ RScript#eval(EVALSHA): atomic w.r.t. every other grid op."""
        with self._lock:
            fn = self._fns.get(name)
        if fn is None:
            raise KeyError(f"NOSCRIPT: {name!r} is not registered")
        with self._client._grid.lock:
            out = fn(self._client, list(keys), list(args))
            self._client._grid.cond.notify_all()
            return out


class FunctionService(CamelCompatMixin):
    """→ RFunction (org/redisson/api/RFunction.java, upstream ≥3.17):
    Redis Functions group named procedures into LIBRARIES (FUNCTION LOAD
    ships a library of functions; FCALL invokes one by name).  Same
    atomicity contract as ScriptService — a call runs under the grid
    lock, indivisible w.r.t. every other grid op.  ``call_ro`` mirrors
    FCALL_RO's read-only contract: the function must not mutate (the
    contract is declarative here, as upstream's is — Redis enforces it
    via script flags, we via the no_writes registration flag).

    Libraries hold Python callables ``fn(client, keys, args)``; there is
    deliberately no Lua VM (ScriptService's design note applies)."""

    def __init__(self, client):
        self._client = client
        # library -> {function name -> (fn, no_writes)}
        self._libs: dict[str, dict] = {}
        self._by_name: dict[str, tuple] = {}  # flat FCALL lookup
        self._lock = _witness.named(
            threading.Lock(), "grid.services.function"
        )

    def load(self, library: str, functions: dict, *, replace: bool = False,
             no_writes: tuple = ()) -> None:
        """→ FUNCTION LOAD [REPLACE]: register a library.  ``functions``
        maps function name -> callable; names are GLOBAL across libraries
        (the Redis rule) — loading a clashing name raises unless
        ``replace`` and the name belongs to this same library."""
        with self._lock:
            if library in self._libs and not replace:
                raise ValueError(f"library {library!r} already exists")
            for fname in functions:
                owner = self._by_name.get(fname)
                if owner is not None and owner[0] != library:
                    raise ValueError(
                        f"function {fname!r} already registered by "
                        f"library {owner[0]!r}"
                    )
            old = self._libs.pop(library, {})
            for fname in old:
                self._by_name.pop(fname, None)
            lib = {
                fname: (fn, fname in no_writes)
                for fname, fn in functions.items()
            }
            self._libs[library] = lib
            for fname, entry in lib.items():
                self._by_name[fname] = (library, *entry)

    def call(self, name: str, keys: list = (), args: list = ()):
        """→ FCALL: atomic named-function invocation."""
        with self._lock:
            entry = self._by_name.get(name)
        if entry is None:
            raise KeyError(f"Function not found: {name!r}")
        _, fn, _ = entry
        with self._client._grid.lock:
            out = fn(self._client, list(keys), list(args))
            self._client._grid.cond.notify_all()
            return out

    def call_ro(self, name: str, keys: list = (), args: list = ()):
        """→ FCALL_RO: only functions registered ``no_writes`` qualify."""
        with self._lock:
            entry = self._by_name.get(name)
        if entry is None:
            raise KeyError(f"Function not found: {name!r}")
        _, fn, ro = entry
        if not ro:
            raise ValueError(
                f"Can not execute a function with write flag using fcall_ro: "
                f"{name!r}"
            )
        with self._client._grid.lock:
            return fn(self._client, list(keys), list(args))

    def list(self, library_pattern: Optional[str] = None) -> list:
        """→ FUNCTION LIST [LIBRARYNAME pat]: library metadata."""
        import fnmatch

        with self._lock:
            out = []
            for lib, fns in self._libs.items():
                if library_pattern and not fnmatch.fnmatch(lib, library_pattern):
                    continue
                out.append(
                    {
                        "library_name": lib,
                        "functions": [
                            {"name": f, "flags": ["no-writes"] if ro else []}
                            for f, (_, ro) in fns.items()
                        ],
                    }
                )
            return out

    def delete(self, library: str) -> None:
        """→ FUNCTION DELETE."""
        with self._lock:
            fns = self._libs.pop(library, None)
            if fns is None:
                raise KeyError(f"Library not found: {library!r}")
            for fname in fns:
                self._by_name.pop(fname, None)

    def flush(self) -> None:
        """→ FUNCTION FLUSH."""
        with self._lock:
            self._libs.clear()
            self._by_name.clear()


class LiveObjectService(CamelCompatMixin):
    """→ RLiveObjectService: instances whose attributes live in an RMap
    named ``{class}:{id}`` — every attribute read/write is a map op, so
    state is shared across handles (the @REntity/@RId proxy pattern).

    Index/search (→ org/redisson/liveobject/ @RIndex machinery): fields
    named in ``persist(..., index=(...))`` maintain per-(class, field,
    value) index sets, so ``find_by_field`` resolves indexed queries as
    one set read; non-indexed fields fall back to scanning the class's
    id registry (upstream requires the annotation; the scan fallback is
    a convenience)."""

    def __init__(self, client):
        self._client = client

    def _map_for(self, cls_name: str, rid) -> Any:
        return self._client.get_map(f"live:{cls_name}:{rid}")

    def _registry(self, cls_name: str):
        return self._client.get_set(f"live:{cls_name}:__ids__")

    def _indexed_fields(self, cls_name: str):
        return self._client.get_set(f"live:{cls_name}:__indexed__")

    def _value_key(self, value) -> str:
        """Deterministic index-set key component: the CODEC bytes of the
        value (repr() embedded memory addresses for objects with the
        default repr, so removal/lookup could never find the add-time
        set)."""
        return self._client.config.codec.encode(value).hex()

    def _index_set(self, cls_name: str, field: str, value):
        return self._client.get_set(
            f"live-idx:{cls_name}:{field}:{self._value_key(value)}"
        )

    def persist(self, obj: Any, rid=None, index: tuple = ()) -> "LiveProxy":
        """Store a plain object's __dict__ and return its live proxy.
        ``index`` names fields to index (the @RIndex analog); indexed
        fields stay maintained through proxy writes.  Marking a field
        indexed BACKFILLS its index sets from every already-registered
        instance, so the fast path never hides pre-index objects."""
        cls_name = type(obj).__name__
        rid = rid if rid is not None else getattr(obj, "id", None)
        if rid is None:
            raise ValueError("live object needs an 'id' attribute or rid=")
        m = self._map_for(cls_name, rid)
        indexed = self._indexed_fields(cls_name)
        with self._client._grid.lock:  # index + map mutate atomically
            newly_indexed = [
                f for f in index if not indexed.contains(f)
            ]
            for f in index:
                indexed.add(f)
            for f in newly_indexed:
                # Backfill from the registry: objects persisted BEFORE
                # the field became indexed must be findable too.
                for other in self._registry(cls_name).read_all():
                    if other == rid:
                        continue
                    v = self._map_for(cls_name, other).get(f)
                    if v is not None:
                        self._index_set(cls_name, f, v).add(other)
            idx_fields = set(indexed.read_all())
            for k, v in vars(obj).items():
                if k in idx_fields:
                    # Re-persist: drop the rid from the OLD value's set
                    # first, or a changed field leaves a stale entry.
                    old = m.get(k)
                    if old is not None and old != v:
                        self._index_set(cls_name, k, old).remove(rid)
                    self._index_set(cls_name, k, v).add(rid)
                m.fast_put(k, v)
            self._registry(cls_name).add(rid)
        return LiveProxy(self._client, cls_name, rid, self)

    def get(self, cls_or_name, rid) -> "LiveProxy":
        name = cls_or_name if isinstance(cls_or_name, str) else cls_or_name.__name__
        return LiveProxy(self._client, name, rid, self)

    def delete(self, cls_or_name, rid) -> bool:
        name = cls_or_name if isinstance(cls_or_name, str) else cls_or_name.__name__
        m = self._map_for(name, rid)
        # Drop this instance from every index it occupies.
        idx_fields = set(self._indexed_fields(name).read_all())
        for f in idx_fields:
            v = m.get(f)
            if v is not None:
                self._index_set(name, f, v).remove(rid)
        self._registry(name).remove(rid)
        return m.delete()

    def exists(self, cls_or_name, rid) -> bool:
        name = cls_or_name if isinstance(cls_or_name, str) else cls_or_name.__name__
        return self._map_for(name, rid).is_exists()

    # -- find/search (→ RLiveObjectService#find + Conditions.eq) -----------

    def find_by_field(self, cls_or_name, field: str, value) -> list:
        """All live proxies of the class whose ``field`` equals
        ``value`` — one index-set read when the field is indexed, a
        registry scan otherwise."""
        name = cls_or_name if isinstance(cls_or_name, str) else cls_or_name.__name__
        if field in set(self._indexed_fields(name).read_all()):
            rids = self._index_set(name, field, value).read_all()
        else:
            rids = [
                rid for rid in self._registry(name).read_all()
                if self._map_for(name, rid).get(field) == value
            ]
        return [LiveProxy(self._client, name, rid, self) for rid in rids]

    find = find_by_field  # upstream-shaped alias (Conditions.eq analog)

    def count(self, cls_or_name) -> int:
        name = cls_or_name if isinstance(cls_or_name, str) else cls_or_name.__name__
        return self._registry(name).size()

    def list_ids(self, cls_or_name) -> list:
        name = cls_or_name if isinstance(cls_or_name, str) else cls_or_name.__name__
        return self._registry(name).read_all()


class LiveProxy:
    """Attribute-mapped live object (the ByteBuddy proxy analog).
    Writes to indexed fields keep the class's index sets current."""

    def __init__(self, client, cls_name: str, rid, service=None):
        object.__setattr__(self, "_map", client.get_map(f"live:{cls_name}:{rid}"))
        object.__setattr__(self, "_cls_name", cls_name)
        object.__setattr__(self, "_rid", rid)
        object.__setattr__(
            self, "_svc", service or LiveObjectService(client)
        )

    def __getattr__(self, item):
        if item.startswith("_"):
            raise AttributeError(item)
        return self._map.get(item)

    def __setattr__(self, item, value):
        svc, cls_name, rid = self._svc, self._cls_name, self._rid
        # One lock hold across read-old/move-index/write: two racing
        # writers would otherwise both read the same old value and leave
        # the rid ghost-indexed under both new values.
        with self._map._store.lock:
            if item in set(svc._indexed_fields(cls_name).read_all()):
                old = self._map.get(item)
                if old is not None and old != value:
                    svc._index_set(cls_name, item, old).remove(rid)
                svc._index_set(cls_name, item, value).add(rid)
            self._map.fast_put(item, value)

    def __delattr__(self, item):
        svc, cls_name, rid = self._svc, self._cls_name, self._rid
        with self._map._store.lock:
            if item in set(svc._indexed_fields(cls_name).read_all()):
                old = self._map.get(item)
                if old is not None:
                    svc._index_set(cls_name, item, old).remove(rid)
            self._map.fast_remove(item)


class MapReduce(CamelCompatMixin):
    """→ RMapReduce: mapper over a Map's entries, grouped shuffle, reducer
    per key — fanned out over an ExecutorService's workers in chunks."""

    def __init__(self, client, source_map, *, workers: int = 4,
                 chunk_size: int = 256):
        self._client = client
        self._source = source_map
        self._mapper: Optional[Callable] = None
        self._reducer: Optional[Callable] = None
        self._workers = workers
        self._chunk = chunk_size

    def mapper(self, fn: Callable) -> "MapReduce":
        """``fn(key, value) -> iterable[(k2, v2)]``."""
        self._mapper = fn
        return self

    def reducer(self, fn: Callable) -> "MapReduce":
        """``fn(k2, values) -> result``."""
        self._reducer = fn
        return self

    def execute(self) -> dict:
        if self._mapper is None or self._reducer is None:
            raise RuntimeError("mapper and reducer must both be set")
        entries = self._source.entry_set()
        ex = ExecutorService("mapreduce", self._client)
        ex.register_workers(self._workers)
        try:
            chunks = [
                entries[i : i + self._chunk]
                for i in range(0, len(entries), self._chunk)
            ]

            def run_chunk(chunk):
                out = []
                for k, v in chunk:
                    out.extend(self._mapper(k, v))
                return out

            futs = [ex.submit(run_chunk, c) for c in chunks]
            shuffled: dict[Any, list] = {}
            for f in futs:
                for k2, v2 in f.result(60.0):
                    shuffled.setdefault(k2, []).append(v2)
            rfuts = {
                k2: ex.submit(self._reducer, k2, vals)
                for k2, vals in shuffled.items()
            }
            return {k2: f.result(60.0) for k2, f in rfuts.items()}
        finally:
            ex.shutdown()
