"""GridStore — the host keyspace behind the data-grid catalog.

Role parity: the Redis server's keyspace as seen through Redisson
(→ org/redisson/RedissonObject.java name addressing + RedissonExpirable
TTL): name → (kind, value, expire_at), with WRONGTYPE guards, lazy expiry
on access, and a background sweeper standing in for the reference's
``EvictionScheduler`` (→ org/redisson/eviction/, SURVEY.md §2.1).

All mutation happens under one re-entrant lock; blocking collection ops
wait on a condition tied to that lock (the pub/sub-wakeup analog of
BLPOP, → SURVEY.md §3.3).
"""

from __future__ import annotations

import fnmatch
import threading
import time
from typing import Any, Callable, Optional

from redisson_tpu.analysis import witness as _witness


class GridEntry:
    __slots__ = ("kind", "value", "expire_at")

    def __init__(self, kind: str, value: Any):
        self.kind = kind
        self.value = value
        self.expire_at: Optional[float] = None  # epoch seconds

    def expired(self, now: float) -> bool:
        return self.expire_at is not None and now >= self.expire_at


class GridStore:
    SWEEP_INTERVAL_S = 0.25

    def __init__(self):
        self.lock = _witness.named(threading.RLock(), "grid.store")
        self.cond = threading.Condition(self.lock)
        self._data: dict[str, GridEntry] = {}
        self._sweeper: Optional[threading.Thread] = None
        self._closed = False
        # Wired by the client to the sketch engine's ``probe``: the user
        # sees ONE keyspace, so creating a grid object under a name held by
        # the other backend is the WRONGTYPE error, not a shadow copy.
        # The probe MUST be lock-free and side-effect-free on the foreign
        # backend — each side calls it while holding its own lock, so a
        # locking probe would be an AB-BA deadlock (found in r3 review).
        self.foreign_exists = None
        # Near-cache reach (ISSUE 14 satellite): the client wires these
        # to the engine near cache's grid-tenant invalidation, so
        # store-level identity changes (delete / rename / expiry /
        # snapshot restore) retire cached grid scalars (XLEN, GEOPOS)
        # the per-object mutators can't see.  Both must be leaf-safe:
        # they are called under ``self.lock``.
        self.on_invalidate = None
        self.on_invalidate_all = None
        # Load-attribution reach (ISSUE 16): the serve layer wires this
        # to the loadmap's exact per-slot key counters.  Called as
        # ``on_keyspace(name, +1/-1)`` at every point the set of live
        # names changes, UNDER ``self.lock`` — the hook must be
        # leaf-safe, like the invalidation hooks above.
        self.on_keyspace = None
        # Op-journal reach (ISSUE 18 satellite): the client wires these
        # to the sketch engine's journal seam so grid mutations enter
        # the SAME total order the replication stream ships.  Grid
        # records are full-entry-state (idempotent, last-write-wins on
        # replay), captured+appended atomically under ``self.lock`` —
        # so seq order equals capture order and the highest seq for a
        # name always carries its newest state.  ``on_journal(op, name,
        # **fields) -> seq|None`` is called under the lock (it only
        # takes the journal's queue lock — ordering grid.store →
        # journal.queue, never reversed); ``on_journal_ack(seq)`` is
        # called OUTSIDE it (under appendfsync=always it blocks on the
        # fsync fence).
        self.on_journal = None
        self.on_journal_ack = None
        # True while applying replicated/replayed records: the apply
        # path must never re-journal what it applies.
        self.journal_suspended = False

    def _note_invalidate(self, name: str) -> None:
        hook = self.on_invalidate
        if hook is not None:
            hook(name)

    def _note_keyspace(self, name: str, delta: int) -> None:
        hook = self.on_keyspace
        if hook is not None:
            hook(name, delta)

    # -- op-journal capture/apply (ISSUE 18 satellite) ---------------------

    @staticmethod
    def _pack_blobs(blobs) -> bytes:
        """Length-prefixed blob list as one bytes field (the record
        codec ships bytes as a uint8 array)."""
        import struct

        parts = [struct.pack("<I", len(blobs))]
        for b in blobs:
            parts.append(struct.pack("<I", len(b)))
            parts.append(bytes(b))
        return b"".join(parts)

    @staticmethod
    def _unpack_blobs(data) -> list:
        import struct

        if hasattr(data, "tobytes"):  # decoded records carry uint8 arrays
            data = data.tobytes()
        (n,) = struct.unpack_from("<I", data, 0)
        off = 4
        out = []
        for _ in range(n):
            (ln,) = struct.unpack_from("<I", data, off)
            off += 4
            out.append(bytes(data[off : off + ln]))
            off += ln
        if len(out) != n:
            raise ValueError("truncated grid blob pack")
        return out

    def _journal_capture(self, name: str):
        """Append one full-state record for ``name`` as it stands RIGHT
        NOW (a ``grid.del`` when absent/expired) and return the seq.
        MUST run under ``self.lock`` — capture+append atomicity is what
        makes seq order equal state order.  Returns None when journaling
        is off, suspended, or the kind has no codec (such kinds don't
        snapshot either, so replicas/recovery can't see them anyway)."""
        hook = self.on_journal
        if hook is None or self.journal_suspended:
            return None
        e = self._data.get(name)
        if e is None or e.expired(time.time()):
            return hook("grid.del", name)
        blobs: list = []

        def add_blob(b) -> int:
            blobs.append(bytes(b))
            return len(blobs) - 1

        desc = self._enc_entry(e.kind, e.value, add_blob)
        if desc is None:
            return None
        import json

        return hook(
            "grid.state", name,
            kind=e.kind,
            desc=json.dumps(desc, separators=(",", ":")),
            expire_at=e.expire_at,
            blobs=self._pack_blobs(blobs),
        )

    def _journal_ack(self, seq) -> None:
        """Durability fence for a captured record — call OUTSIDE the
        store lock (blocks on fsync under appendfsync=always)."""
        ack = self.on_journal_ack
        if ack is not None and seq is not None:
            ack(seq)

    def journal_entry(self, name: str) -> None:
        """Capture + ack one name's state: the per-mutator hook the
        ``journaled`` decorator (grid/base.py) calls after a mutation
        returns."""
        with self.lock:
            seq = self._journal_capture(name)
        self._journal_ack(seq)

    def apply_journal_record(self, rec: dict) -> None:
        """Install one ``grid.state``/``grid.del`` record — the replica
        stream-apply and journal-recovery entry point.  Full-state
        semantics: idempotent, latest-seq-wins."""
        op = rec["op"]
        name = rec["name"]
        prev = self.journal_suspended
        self.journal_suspended = True
        try:
            if op == "grid.del":
                self.delete(name)
                return
            if op != "grid.state":
                raise ValueError(f"not a grid journal record: {op!r}")
            import json

            blobs = self._unpack_blobs(rec["blobs"])
            value = self._dec_entry(json.loads(rec["desc"]), blobs)
            exp = rec.get("expire_at")
            with self.lock:
                e = GridEntry(str(rec["kind"]), value)
                e.expire_at = exp
                if name not in self._data:
                    self._note_keyspace(name, +1)
                self._data[name] = e
                self._note_invalidate(name)
                if exp is not None:
                    self._ensure_sweeper()
                self.cond.notify_all()
        finally:
            self.journal_suspended = prev

    def _guard_foreign(self, name: str) -> None:
        if self.foreign_exists is not None and self.foreign_exists(name):
            raise TypeError(
                f"object {name!r} is held by the sketch backend (WRONGTYPE)"
            )

    def probe(self, name: str) -> bool:
        """Lock-free existence probe for the sketch backend's guard (dict
        reads are atomic in CPython; expiry checked without reaping)."""
        e = self._data.get(name)
        return e is not None and not e.expired(time.time())

    # -- entry access ------------------------------------------------------

    def get_entry(self, name: str, kind: Optional[str] = None) -> Optional[GridEntry]:
        """Live entry or None; raises TypeError on kind mismatch (the Redis
        WRONGTYPE analog).  Caller must hold ``self.lock`` for compound
        read-modify-write sequences."""
        with self.lock:
            e = self._data.get(name)
            if e is not None and e.expired(time.time()):
                del self._data[name]
                self._note_invalidate(name)
                self._note_keyspace(name, -1)
                e = None
            if e is not None and kind is not None and e.kind != kind:
                raise TypeError(f"object {name!r} holds a {e.kind}, not a {kind}")
            return e

    def ensure_entry(self, name: str, kind: str, factory: Callable[[], Any]) -> GridEntry:
        with self.lock:
            e = self.get_entry(name, kind)
            if e is None:
                self._guard_foreign(name)
                e = GridEntry(kind, factory())
                self._data[name] = e
                self._note_keyspace(name, +1)
            return e

    def put_entry(self, name: str, kind: str, value: Any) -> GridEntry:
        with self.lock:
            prior = self._data.get(name)
            if prior is None or prior.expired(time.time()):
                # An expired-unswept entry confers NO ownership: probe()
                # already reports the name absent to the sketch side,
                # which may have legitimately created it meanwhile.
                self._guard_foreign(name)
            e = GridEntry(kind, value)
            # An expired-unreaped prior still holds its +1 (only the
            # reap paths decrement), so the overwrite transfers it: the
            # count moves only when the name was genuinely absent.
            if prior is None:
                self._note_keyspace(name, +1)
            self._data[name] = e
            self.cond.notify_all()
            return e

    def notify(self) -> None:
        """Wake blocked takers after a mutation (BLPOP-wakeup analog)."""
        with self.lock:
            self.cond.notify_all()

    # -- keyspace admin (RKeys backing) ------------------------------------

    def exists(self, name: str) -> bool:
        return self.get_entry(name) is not None

    def delete(self, name: str) -> bool:
        with self.lock:
            e = self.get_entry(name)
            if e is None:
                return False
            del self._data[name]
            self._note_invalidate(name)
            self._note_keyspace(name, -1)
            self.cond.notify_all()
            seq = self._journal_capture(name)
        self._journal_ack(seq)
        return True

    def rename(self, old: str, new: str) -> bool:
        with self.lock:
            e = self.get_entry(old)
            if e is None:
                return False
            if old == new:
                return True  # RENAME key key succeeds when the key exists
            # One logical keyspace: renaming ONTO a sketch-held name would
            # leave it live on both backends.
            self._guard_foreign(new)
            displaced = new in self._data
            del self._data[old]
            self._data[new] = e
            self._note_invalidate(old)
            self._note_invalidate(new)
            self._note_keyspace(old, -1)
            if not displaced:  # overwrite transfers the displaced +1
                self._note_keyspace(new, +1)
            # Two full-state records (old absent, new present) — rename
            # needs no dedicated record type under last-write-wins.
            self._journal_capture(old)
            seq = self._journal_capture(new)
        self._journal_ack(seq)
        return True

    def names(self, pattern: Optional[str] = None) -> list[str]:
        with self.lock:
            now = time.time()
            out = []
            for n, e in list(self._data.items()):
                if e.expired(now):
                    del self._data[n]
                    self._note_invalidate(n)
                    self._note_keyspace(n, -1)
                    continue
                if pattern is None or fnmatch.fnmatchcase(n, pattern):
                    out.append(n)
            return out

    # -- TTL (RedissonExpirable parity) ------------------------------------

    def expire(self, name: str, ttl_s: float) -> bool:
        with self.lock:
            e = self.get_entry(name)
            if e is None:
                return False
            e.expire_at = time.time() + ttl_s
            self._note_invalidate(name)
            self._ensure_sweeper()
            seq = self._journal_capture(name)
        self._journal_ack(seq)
        return True

    def expire_at(self, name: str, epoch_s: float) -> bool:
        with self.lock:
            e = self.get_entry(name)
            if e is None:
                return False
            e.expire_at = float(epoch_s)
            self._note_invalidate(name)
            self._ensure_sweeper()
            seq = self._journal_capture(name)
        self._journal_ack(seq)
        return True

    def clear_expire(self, name: str) -> bool:
        with self.lock:
            e = self.get_entry(name)
            if e is None or e.expire_at is None:
                return False
            e.expire_at = None
            self._note_invalidate(name)
            seq = self._journal_capture(name)
        self._journal_ack(seq)
        return True

    def peek_expire_at(self, name: str):
        """The entry's TTL deadline (or None) WITHOUT reaping — the
        near-cache reach tags cached scalars with it so a hit can
        observe the deadline exactly, not at the next sweep."""
        with self.lock:
            e = self._data.get(name)
            return None if e is None else e.expire_at

    def remain_ttl_ms(self, name: str) -> int:
        """→ RExpirable#remainTimeToLive: -2 absent, -1 no TTL, else ms."""
        with self.lock:
            e = self.get_entry(name)
            if e is None:
                return -2
            if e.expire_at is None:
                return -1
            return max(0, int((e.expire_at - time.time()) * 1000))

    # -- sweeper (EvictionScheduler analog) --------------------------------

    def _ensure_sweeper(self) -> None:
        if self._sweeper is None or not self._sweeper.is_alive():
            self._sweeper = threading.Thread(
                target=self._sweep_loop, name="rtpu-grid-sweeper", daemon=True
            )
            self._sweeper.start()

    def _sweep_loop(self) -> None:
        while not self._closed:
            time.sleep(self.SWEEP_INTERVAL_S)
            now = time.time()
            with self.lock:
                dead = [n for n, e in self._data.items() if e.expired(now)]
                for n in dead:
                    del self._data[n]
                    self._note_invalidate(n)
                    self._note_keyspace(n, -1)
                if dead:
                    self.cond.notify_all()
                # Let map-entry TTL structures prune themselves too.
                for e in self._data.values():
                    pruner = getattr(e.value, "prune_expired", None)
                    if pruner is not None:
                        pruner(now)

    def shutdown(self) -> None:
        self._closed = True

    # -- persistence (the RDB-analog for the HOST keyspace; sketch pools
    # snapshot separately in objects/durability.py).  DATA-ONLY wire
    # format — no pickle (snapshots may be moved between machines):
    # RTPG | u32 meta_len | json meta | u32-length-prefixed blobs.
    # Values reference blobs by index.  Persisted kinds: bucket,
    # binarystream, set, setcache, zset, lexset, map, mapcache, list
    # (queues/deques share it), ringbuffer, atomic counters/adders,
    # idgenerator, stream (entries + consumer groups/PELs — the
    # replication stream needs full stream state, ISSUE 18).  NOT
    # persisted (skipped with a summary warning): coordination state
    # (locks, latches, semaphores), delayed/priority queues, geo,
    # timeseries, multimaps, and sortedset (its in-memory order is
    # codec-decoded, which the store cannot rebuild).  The same codec
    # backs per-mutation ``grid.state`` journal records — an
    # unsupported kind is skipped in BOTH tiers, so replicas and
    # recovery stay consistent with snapshots.
    # ----------------------------------------------------------------------

    _SNAP_MAGIC = b"RTPG"
    _SNAP_VERSION = 1

    @staticmethod
    def _enc_entry(kind: str, value, add_blob):
        """-> JSON-safe value descriptor, or None if kind unsupported."""
        if kind in ("bucket", "binarystream"):
            if value is None:
                return {"t": "none"}
            if isinstance(value, str):  # legacy str bucket payloads
                value = value.encode()
            if not isinstance(value, bytes):
                return None
            return {"t": "b", "v": add_blob(value)}
        if kind == "set":
            return {"t": "set", "m": [add_blob(vb) for vb in value]}
        if kind == "setcache":
            return {
                "t": "setc",
                "m": [[add_blob(vb), exp] for vb, exp in value.data.items()],
            }
        if kind == "zset":
            return {
                "t": "zset",
                "m": [[add_blob(vb), s] for vb, s in value.items()],
            }
        if kind == "lexset":
            return {"t": "lex", "m": sorted(value)}
        if kind in ("map", "mapcache"):
            now = time.time()
            rows = []
            for kb, slot in value.data.items():
                vb, exp, idle, last = slot
                elapsed = now - last
                if idle is not None and elapsed >= idle:
                    continue  # idle-dead at snapshot time: do not resurrect
                rows.append(
                    [add_blob(kb), add_blob(vb), exp, idle, elapsed]
                )
            return {"t": "map", "m": rows}
        if kind == "list":
            return {"t": "list", "m": [add_blob(vb) for vb in value]}
        if kind in ("atomiclong", "atomicdouble", "longadder", "doubleadder"):
            return {"t": "num", "v": value}
        if kind == "idgenerator":
            return {"t": "idgen", "next": value["next"], "block": value["block"]}
        if kind == "ringbuffer":
            return {
                "t": "ring",
                "cap": value["cap"],
                "m": [add_blob(vb) for vb in value["items"]],
            }
        if kind == "stream":
            # Full _StreamValue state incl. consumer groups and PELs —
            # required by the replication stream (ISSUE 18): XADD on a
            # primary must materialize on its replicas.
            rows = [
                [ms, sq,
                 [[add_blob(fk), add_blob(fv)] for fk, fv in fields.items()]]
                for (ms, sq), fields in value.entries.items()
            ]
            groups = [
                {
                    "n": gname,
                    "ld": list(g["last_delivered"]),
                    "p": [
                        [ms, sq, p["consumer"], p["time_ms"], p["count"]]
                        for (ms, sq), p in g["pending"].items()
                    ],
                    "c": sorted(g["consumers"]),
                }
                for gname, g in value.groups.items()
            ]
            return {
                "t": "stream",
                "m": rows,
                "last": list(value.last_id),
                "maxdel": list(value.max_deleted_id),
                "added": value.added,
                "g": groups,
            }
        return None

    @staticmethod
    def _dec_entry(desc: dict, blobs):
        t = desc["t"]
        if t == "none":
            return None
        if t == "b":
            return blobs[desc["v"]]
        if t == "set":
            return {blobs[i]: None for i in desc["m"]}
        if t == "setc":
            from redisson_tpu.grid.collections import SetCache

            v = SetCache._Value()
            v.data = {blobs[i]: exp for i, exp in desc["m"]}
            return v
        if t == "zset":
            return {blobs[i]: float(s) for i, s in desc["m"]}
        if t == "lex":
            return set(desc["m"])
        if t == "map":
            from redisson_tpu.grid.maps import _MapValue

            v = _MapValue()
            now = time.time()
            # last_access carries over as ELAPSED idle: an entry that had
            # burned 40s of a 60s max-idle window resumes with 20s left,
            # not a fresh window (RMapCache max-idle contract).
            v.data = {
                blobs[ki]: [blobs[vi], exp, idle, now - elapsed]
                for ki, vi, exp, idle, elapsed in desc["m"]
            }
            return v
        if t == "list":
            return [blobs[i] for i in desc["m"]]
        if t == "num":
            return desc["v"]
        if t == "idgen":
            return {"next": int(desc["next"]), "block": int(desc["block"])}
        if t == "ring":
            return {"cap": int(desc["cap"]), "items": [blobs[i] for i in desc["m"]]}
        if t == "stream":
            from redisson_tpu.grid.streams import _StreamValue

            v = _StreamValue()
            v.entries = {
                (int(ms), int(sq)): {blobs[ki]: blobs[vi] for ki, vi in fm}
                for ms, sq, fm in desc["m"]
            }
            v.last_id = tuple(int(x) for x in desc["last"])
            v.max_deleted_id = tuple(int(x) for x in desc["maxdel"])
            v.added = int(desc["added"])
            v.groups = {
                g["n"]: {
                    "last_delivered": tuple(int(x) for x in g["ld"]),
                    "pending": {
                        (int(ms), int(sq)): {
                            "consumer": cons,
                            "time_ms": int(tms),
                            "count": int(cnt),
                        }
                        for ms, sq, cons, tms, cnt in g["p"]
                    },
                    "consumers": set(g["c"]),
                }
                for g in desc["g"]
            }
            return v
        raise ValueError(f"unknown grid snapshot value type {t!r}")

    def snapshot_to(self, path: str) -> int:
        """Write every persistable live entry; returns the count written.
        Atomic (tmp + rename)."""
        import io
        import json
        import os
        import struct

        blobs: list[bytes] = []

        def add_blob(b: bytes) -> int:
            blobs.append(bytes(b))
            return len(blobs) - 1

        meta = []
        skipped: dict[str, int] = {}
        now = time.time()
        with self.lock:
            for name, e in self._data.items():
                if e.expired(now):
                    continue
                desc = self._enc_entry(e.kind, e.value, add_blob)
                if desc is None:
                    skipped[e.kind] = skipped.get(e.kind, 0) + 1
                    continue
                meta.append(
                    {
                        "name": name,
                        "kind": e.kind,
                        "expire_at": e.expire_at,
                        "value": desc,
                    }
                )
        if skipped:
            import logging

            logging.getLogger(__name__).warning(
                "grid snapshot skipped non-persisted kinds: %s", skipped
            )
        header = json.dumps({"v": self._SNAP_VERSION, "entries": meta}).encode()
        buf = io.BytesIO()
        buf.write(self._SNAP_MAGIC)
        buf.write(struct.pack("<I", len(header)))
        buf.write(header)
        for b in blobs:
            buf.write(struct.pack("<I", len(b)))
            buf.write(b)
        import uuid

        parent = os.path.dirname(os.path.abspath(path))
        os.makedirs(parent, exist_ok=True)
        # Unique per WRITER (not just per process): the shutdown writer
        # and the periodic snapshotter thread may race on the same path;
        # identical tmp names would truncate each other mid-write.
        tmp = f"{path}.tmp.{os.getpid()}.{uuid.uuid4().hex[:8]}"
        with open(tmp, "wb") as f:
            f.write(buf.getvalue())
            f.flush()
            # fsync-then-rename (RT014): without the barrier a host
            # crash can publish the snapshot NAME over void bytes —
            # restore_from would then load a torn grid snapshot where
            # the pre-rename file was still intact.
            os.fsync(f.fileno())
        os.replace(tmp, path)
        from redisson_tpu.durability.journal import _fsync_dir

        _fsync_dir(parent)
        return len(meta)

    def restore_from(self, path: str) -> bool:
        """Load a snapshot written by ``snapshot_to``; True if one was
        found.  Intended at init (empty keyspace); existing names are
        overwritten (same-name restore-on-boot semantics as the sketch
        side's empty-keyspace contract, enforced by call order)."""
        import json
        import os
        import struct

        if not os.path.exists(path):
            return False
        with open(path, "rb") as f:
            data = f.read()
        if data[:4] != self._SNAP_MAGIC:
            raise ValueError("not a grid snapshot (bad magic)")
        (hlen,) = struct.unpack("<I", data[4:8])
        head = json.loads(data[8 : 8 + hlen].decode())
        if head.get("v") != self._SNAP_VERSION:
            raise ValueError(f"unsupported grid snapshot v{head.get('v')}")
        # Whole-keyspace replacement: every cached grid scalar predates
        # the restored state (near-cache reach, ISSUE 14 satellite).
        hook = self.on_invalidate_all
        if hook is not None:
            hook()
        blobs: list[bytes] = []
        off = 8 + hlen
        while off < len(data):
            (n,) = struct.unpack("<I", data[off : off + 4])
            off += 4
            if off + n > len(data):
                raise ValueError("truncated grid snapshot blob")
            blobs.append(data[off : off + n])
            off += n
        now = time.time()
        clashes = []
        with self.lock:
            for ent in head["entries"]:
                exp = ent.get("expire_at")
                if exp is not None and now >= exp:
                    continue  # expired while on disk
                if self.foreign_exists is not None and self.foreign_exists(
                    ent["name"]
                ):
                    # The sketch and grid halves snapshot at different
                    # instants; a name that moved between backends in that
                    # window must not end up live on BOTH (the one-
                    # logical-keyspace invariant).  Sketch wins: it was
                    # captured under the engine locks.
                    clashes.append(ent["name"])
                    continue
                ge = GridEntry(ent["kind"], self._dec_entry(ent["value"], blobs))
                ge.expire_at = exp
                if ent["name"] not in self._data:
                    self._note_keyspace(ent["name"], +1)
                self._data[ent["name"]] = ge
                if exp is not None:
                    self._ensure_sweeper()
            self.cond.notify_all()
        if clashes:
            import logging

            logging.getLogger(__name__).warning(
                "grid restore skipped %d name(s) held by the sketch "
                "backend (snapshot halves raced): %s",
                len(clashes), clashes[:5],
            )
        return True
