"""GridStore — the host keyspace behind the data-grid catalog.

Role parity: the Redis server's keyspace as seen through Redisson
(→ org/redisson/RedissonObject.java name addressing + RedissonExpirable
TTL): name → (kind, value, expire_at), with WRONGTYPE guards, lazy expiry
on access, and a background sweeper standing in for the reference's
``EvictionScheduler`` (→ org/redisson/eviction/, SURVEY.md §2.1).

All mutation happens under one re-entrant lock; blocking collection ops
wait on a condition tied to that lock (the pub/sub-wakeup analog of
BLPOP, → SURVEY.md §3.3).
"""

from __future__ import annotations

import fnmatch
import threading
import time
from typing import Any, Callable, Optional


class GridEntry:
    __slots__ = ("kind", "value", "expire_at")

    def __init__(self, kind: str, value: Any):
        self.kind = kind
        self.value = value
        self.expire_at: Optional[float] = None  # epoch seconds

    def expired(self, now: float) -> bool:
        return self.expire_at is not None and now >= self.expire_at


class GridStore:
    SWEEP_INTERVAL_S = 0.25

    def __init__(self):
        self.lock = threading.RLock()
        self.cond = threading.Condition(self.lock)
        self._data: dict[str, GridEntry] = {}
        self._sweeper: Optional[threading.Thread] = None
        self._closed = False
        # Wired by the client to the sketch engine's ``probe``: the user
        # sees ONE keyspace, so creating a grid object under a name held by
        # the other backend is the WRONGTYPE error, not a shadow copy.
        # The probe MUST be lock-free and side-effect-free on the foreign
        # backend — each side calls it while holding its own lock, so a
        # locking probe would be an AB-BA deadlock (found in r3 review).
        self.foreign_exists = None

    def _guard_foreign(self, name: str) -> None:
        if self.foreign_exists is not None and self.foreign_exists(name):
            raise TypeError(
                f"object {name!r} is held by the sketch backend (WRONGTYPE)"
            )

    def probe(self, name: str) -> bool:
        """Lock-free existence probe for the sketch backend's guard (dict
        reads are atomic in CPython; expiry checked without reaping)."""
        e = self._data.get(name)
        return e is not None and not e.expired(time.time())

    # -- entry access ------------------------------------------------------

    def get_entry(self, name: str, kind: Optional[str] = None) -> Optional[GridEntry]:
        """Live entry or None; raises TypeError on kind mismatch (the Redis
        WRONGTYPE analog).  Caller must hold ``self.lock`` for compound
        read-modify-write sequences."""
        with self.lock:
            e = self._data.get(name)
            if e is not None and e.expired(time.time()):
                del self._data[name]
                e = None
            if e is not None and kind is not None and e.kind != kind:
                raise TypeError(f"object {name!r} holds a {e.kind}, not a {kind}")
            return e

    def ensure_entry(self, name: str, kind: str, factory: Callable[[], Any]) -> GridEntry:
        with self.lock:
            e = self.get_entry(name, kind)
            if e is None:
                self._guard_foreign(name)
                e = GridEntry(kind, factory())
                self._data[name] = e
            return e

    def put_entry(self, name: str, kind: str, value: Any) -> GridEntry:
        with self.lock:
            if name not in self._data:
                self._guard_foreign(name)
            e = GridEntry(kind, value)
            self._data[name] = e
            self.cond.notify_all()
            return e

    def notify(self) -> None:
        """Wake blocked takers after a mutation (BLPOP-wakeup analog)."""
        with self.lock:
            self.cond.notify_all()

    # -- keyspace admin (RKeys backing) ------------------------------------

    def exists(self, name: str) -> bool:
        return self.get_entry(name) is not None

    def delete(self, name: str) -> bool:
        with self.lock:
            e = self.get_entry(name)
            if e is None:
                return False
            del self._data[name]
            self.cond.notify_all()
            return True

    def rename(self, old: str, new: str) -> bool:
        with self.lock:
            e = self.get_entry(old)
            if e is None:
                return False
            if old == new:
                return True  # RENAME key key succeeds when the key exists
            del self._data[old]
            self._data[new] = e
            return True

    def names(self, pattern: Optional[str] = None) -> list[str]:
        with self.lock:
            now = time.time()
            out = []
            for n, e in list(self._data.items()):
                if e.expired(now):
                    del self._data[n]
                    continue
                if pattern is None or fnmatch.fnmatchcase(n, pattern):
                    out.append(n)
            return out

    # -- TTL (RedissonExpirable parity) ------------------------------------

    def expire(self, name: str, ttl_s: float) -> bool:
        with self.lock:
            e = self.get_entry(name)
            if e is None:
                return False
            e.expire_at = time.time() + ttl_s
            self._ensure_sweeper()
            return True

    def expire_at(self, name: str, epoch_s: float) -> bool:
        with self.lock:
            e = self.get_entry(name)
            if e is None:
                return False
            e.expire_at = float(epoch_s)
            self._ensure_sweeper()
            return True

    def clear_expire(self, name: str) -> bool:
        with self.lock:
            e = self.get_entry(name)
            if e is None or e.expire_at is None:
                return False
            e.expire_at = None
            return True

    def remain_ttl_ms(self, name: str) -> int:
        """→ RExpirable#remainTimeToLive: -2 absent, -1 no TTL, else ms."""
        with self.lock:
            e = self.get_entry(name)
            if e is None:
                return -2
            if e.expire_at is None:
                return -1
            return max(0, int((e.expire_at - time.time()) * 1000))

    # -- sweeper (EvictionScheduler analog) --------------------------------

    def _ensure_sweeper(self) -> None:
        if self._sweeper is None or not self._sweeper.is_alive():
            self._sweeper = threading.Thread(
                target=self._sweep_loop, name="rtpu-grid-sweeper", daemon=True
            )
            self._sweeper.start()

    def _sweep_loop(self) -> None:
        while not self._closed:
            time.sleep(self.SWEEP_INTERVAL_S)
            now = time.time()
            with self.lock:
                dead = [n for n, e in self._data.items() if e.expired(now)]
                for n in dead:
                    del self._data[n]
                if dead:
                    self.cond.notify_all()
                # Let map-entry TTL structures prune themselves too.
                for e in self._data.values():
                    pruner = getattr(e.value, "prune_expired", None)
                    if pruner is not None:
                        pruner(now)

    def shutdown(self) -> None:
        self._closed = True
