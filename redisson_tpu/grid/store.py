"""GridStore — the host keyspace behind the data-grid catalog.

Role parity: the Redis server's keyspace as seen through Redisson
(→ org/redisson/RedissonObject.java name addressing + RedissonExpirable
TTL): name → (kind, value, expire_at), with WRONGTYPE guards, lazy expiry
on access, and a background sweeper standing in for the reference's
``EvictionScheduler`` (→ org/redisson/eviction/, SURVEY.md §2.1).

All mutation happens under one re-entrant lock; blocking collection ops
wait on a condition tied to that lock (the pub/sub-wakeup analog of
BLPOP, → SURVEY.md §3.3).
"""

from __future__ import annotations

import fnmatch
import threading
import time
from typing import Any, Callable, Optional

from redisson_tpu.analysis import witness as _witness


class GridEntry:
    __slots__ = ("kind", "value", "expire_at")

    def __init__(self, kind: str, value: Any):
        self.kind = kind
        self.value = value
        self.expire_at: Optional[float] = None  # epoch seconds

    def expired(self, now: float) -> bool:
        return self.expire_at is not None and now >= self.expire_at


class GridStore:
    SWEEP_INTERVAL_S = 0.25

    def __init__(self):
        self.lock = _witness.named(threading.RLock(), "grid.store")
        self.cond = threading.Condition(self.lock)
        self._data: dict[str, GridEntry] = {}
        self._sweeper: Optional[threading.Thread] = None
        self._closed = False
        # Wired by the client to the sketch engine's ``probe``: the user
        # sees ONE keyspace, so creating a grid object under a name held by
        # the other backend is the WRONGTYPE error, not a shadow copy.
        # The probe MUST be lock-free and side-effect-free on the foreign
        # backend — each side calls it while holding its own lock, so a
        # locking probe would be an AB-BA deadlock (found in r3 review).
        self.foreign_exists = None
        # Near-cache reach (ISSUE 14 satellite): the client wires these
        # to the engine near cache's grid-tenant invalidation, so
        # store-level identity changes (delete / rename / expiry /
        # snapshot restore) retire cached grid scalars (XLEN, GEOPOS)
        # the per-object mutators can't see.  Both must be leaf-safe:
        # they are called under ``self.lock``.
        self.on_invalidate = None
        self.on_invalidate_all = None
        # Load-attribution reach (ISSUE 16): the serve layer wires this
        # to the loadmap's exact per-slot key counters.  Called as
        # ``on_keyspace(name, +1/-1)`` at every point the set of live
        # names changes, UNDER ``self.lock`` — the hook must be
        # leaf-safe, like the invalidation hooks above.
        self.on_keyspace = None

    def _note_invalidate(self, name: str) -> None:
        hook = self.on_invalidate
        if hook is not None:
            hook(name)

    def _note_keyspace(self, name: str, delta: int) -> None:
        hook = self.on_keyspace
        if hook is not None:
            hook(name, delta)

    def _guard_foreign(self, name: str) -> None:
        if self.foreign_exists is not None and self.foreign_exists(name):
            raise TypeError(
                f"object {name!r} is held by the sketch backend (WRONGTYPE)"
            )

    def probe(self, name: str) -> bool:
        """Lock-free existence probe for the sketch backend's guard (dict
        reads are atomic in CPython; expiry checked without reaping)."""
        e = self._data.get(name)
        return e is not None and not e.expired(time.time())

    # -- entry access ------------------------------------------------------

    def get_entry(self, name: str, kind: Optional[str] = None) -> Optional[GridEntry]:
        """Live entry or None; raises TypeError on kind mismatch (the Redis
        WRONGTYPE analog).  Caller must hold ``self.lock`` for compound
        read-modify-write sequences."""
        with self.lock:
            e = self._data.get(name)
            if e is not None and e.expired(time.time()):
                del self._data[name]
                self._note_invalidate(name)
                self._note_keyspace(name, -1)
                e = None
            if e is not None and kind is not None and e.kind != kind:
                raise TypeError(f"object {name!r} holds a {e.kind}, not a {kind}")
            return e

    def ensure_entry(self, name: str, kind: str, factory: Callable[[], Any]) -> GridEntry:
        with self.lock:
            e = self.get_entry(name, kind)
            if e is None:
                self._guard_foreign(name)
                e = GridEntry(kind, factory())
                self._data[name] = e
                self._note_keyspace(name, +1)
            return e

    def put_entry(self, name: str, kind: str, value: Any) -> GridEntry:
        with self.lock:
            prior = self._data.get(name)
            if prior is None or prior.expired(time.time()):
                # An expired-unswept entry confers NO ownership: probe()
                # already reports the name absent to the sketch side,
                # which may have legitimately created it meanwhile.
                self._guard_foreign(name)
            e = GridEntry(kind, value)
            # An expired-unreaped prior still holds its +1 (only the
            # reap paths decrement), so the overwrite transfers it: the
            # count moves only when the name was genuinely absent.
            if prior is None:
                self._note_keyspace(name, +1)
            self._data[name] = e
            self.cond.notify_all()
            return e

    def notify(self) -> None:
        """Wake blocked takers after a mutation (BLPOP-wakeup analog)."""
        with self.lock:
            self.cond.notify_all()

    # -- keyspace admin (RKeys backing) ------------------------------------

    def exists(self, name: str) -> bool:
        return self.get_entry(name) is not None

    def delete(self, name: str) -> bool:
        with self.lock:
            e = self.get_entry(name)
            if e is None:
                return False
            del self._data[name]
            self._note_invalidate(name)
            self._note_keyspace(name, -1)
            self.cond.notify_all()
            return True

    def rename(self, old: str, new: str) -> bool:
        with self.lock:
            e = self.get_entry(old)
            if e is None:
                return False
            if old == new:
                return True  # RENAME key key succeeds when the key exists
            # One logical keyspace: renaming ONTO a sketch-held name would
            # leave it live on both backends.
            self._guard_foreign(new)
            displaced = new in self._data
            del self._data[old]
            self._data[new] = e
            self._note_invalidate(old)
            self._note_invalidate(new)
            self._note_keyspace(old, -1)
            if not displaced:  # overwrite transfers the displaced +1
                self._note_keyspace(new, +1)
            return True

    def names(self, pattern: Optional[str] = None) -> list[str]:
        with self.lock:
            now = time.time()
            out = []
            for n, e in list(self._data.items()):
                if e.expired(now):
                    del self._data[n]
                    self._note_invalidate(n)
                    self._note_keyspace(n, -1)
                    continue
                if pattern is None or fnmatch.fnmatchcase(n, pattern):
                    out.append(n)
            return out

    # -- TTL (RedissonExpirable parity) ------------------------------------

    def expire(self, name: str, ttl_s: float) -> bool:
        with self.lock:
            e = self.get_entry(name)
            if e is None:
                return False
            e.expire_at = time.time() + ttl_s
            self._note_invalidate(name)
            self._ensure_sweeper()
            return True

    def expire_at(self, name: str, epoch_s: float) -> bool:
        with self.lock:
            e = self.get_entry(name)
            if e is None:
                return False
            e.expire_at = float(epoch_s)
            self._note_invalidate(name)
            self._ensure_sweeper()
            return True

    def clear_expire(self, name: str) -> bool:
        with self.lock:
            e = self.get_entry(name)
            if e is None or e.expire_at is None:
                return False
            e.expire_at = None
            self._note_invalidate(name)
            return True

    def peek_expire_at(self, name: str):
        """The entry's TTL deadline (or None) WITHOUT reaping — the
        near-cache reach tags cached scalars with it so a hit can
        observe the deadline exactly, not at the next sweep."""
        with self.lock:
            e = self._data.get(name)
            return None if e is None else e.expire_at

    def remain_ttl_ms(self, name: str) -> int:
        """→ RExpirable#remainTimeToLive: -2 absent, -1 no TTL, else ms."""
        with self.lock:
            e = self.get_entry(name)
            if e is None:
                return -2
            if e.expire_at is None:
                return -1
            return max(0, int((e.expire_at - time.time()) * 1000))

    # -- sweeper (EvictionScheduler analog) --------------------------------

    def _ensure_sweeper(self) -> None:
        if self._sweeper is None or not self._sweeper.is_alive():
            self._sweeper = threading.Thread(
                target=self._sweep_loop, name="rtpu-grid-sweeper", daemon=True
            )
            self._sweeper.start()

    def _sweep_loop(self) -> None:
        while not self._closed:
            time.sleep(self.SWEEP_INTERVAL_S)
            now = time.time()
            with self.lock:
                dead = [n for n, e in self._data.items() if e.expired(now)]
                for n in dead:
                    del self._data[n]
                    self._note_invalidate(n)
                    self._note_keyspace(n, -1)
                if dead:
                    self.cond.notify_all()
                # Let map-entry TTL structures prune themselves too.
                for e in self._data.values():
                    pruner = getattr(e.value, "prune_expired", None)
                    if pruner is not None:
                        pruner(now)

    def shutdown(self) -> None:
        self._closed = True

    # -- persistence (the RDB-analog for the HOST keyspace; sketch pools
    # snapshot separately in objects/durability.py).  DATA-ONLY wire
    # format — no pickle (snapshots may be moved between machines):
    # RTPG | u32 meta_len | json meta | u32-length-prefixed blobs.
    # Values reference blobs by index.  Persisted kinds: bucket,
    # binarystream, set, setcache, zset, lexset, map, mapcache, list
    # (queues/deques share it), ringbuffer, atomic counters/adders,
    # idgenerator.  NOT persisted (skipped with a summary warning):
    # coordination state (locks, latches, semaphores), streams, delayed/
    # priority queues, geo, timeseries, multimaps, and sortedset (its
    # in-memory order is codec-decoded, which the store cannot rebuild).
    # ----------------------------------------------------------------------

    _SNAP_MAGIC = b"RTPG"
    _SNAP_VERSION = 1

    @staticmethod
    def _enc_entry(kind: str, value, add_blob):
        """-> JSON-safe value descriptor, or None if kind unsupported."""
        if kind in ("bucket", "binarystream"):
            if value is None:
                return {"t": "none"}
            if isinstance(value, str):  # legacy str bucket payloads
                value = value.encode()
            if not isinstance(value, bytes):
                return None
            return {"t": "b", "v": add_blob(value)}
        if kind == "set":
            return {"t": "set", "m": [add_blob(vb) for vb in value]}
        if kind == "setcache":
            return {
                "t": "setc",
                "m": [[add_blob(vb), exp] for vb, exp in value.data.items()],
            }
        if kind == "zset":
            return {
                "t": "zset",
                "m": [[add_blob(vb), s] for vb, s in value.items()],
            }
        if kind == "lexset":
            return {"t": "lex", "m": sorted(value)}
        if kind in ("map", "mapcache"):
            now = time.time()
            rows = []
            for kb, slot in value.data.items():
                vb, exp, idle, last = slot
                elapsed = now - last
                if idle is not None and elapsed >= idle:
                    continue  # idle-dead at snapshot time: do not resurrect
                rows.append(
                    [add_blob(kb), add_blob(vb), exp, idle, elapsed]
                )
            return {"t": "map", "m": rows}
        if kind == "list":
            return {"t": "list", "m": [add_blob(vb) for vb in value]}
        if kind in ("atomiclong", "atomicdouble", "longadder", "doubleadder"):
            return {"t": "num", "v": value}
        if kind == "idgenerator":
            return {"t": "idgen", "next": value["next"], "block": value["block"]}
        if kind == "ringbuffer":
            return {
                "t": "ring",
                "cap": value["cap"],
                "m": [add_blob(vb) for vb in value["items"]],
            }
        return None

    @staticmethod
    def _dec_entry(desc: dict, blobs):
        t = desc["t"]
        if t == "none":
            return None
        if t == "b":
            return blobs[desc["v"]]
        if t == "set":
            return {blobs[i]: None for i in desc["m"]}
        if t == "setc":
            from redisson_tpu.grid.collections import SetCache

            v = SetCache._Value()
            v.data = {blobs[i]: exp for i, exp in desc["m"]}
            return v
        if t == "zset":
            return {blobs[i]: float(s) for i, s in desc["m"]}
        if t == "lex":
            return set(desc["m"])
        if t == "map":
            from redisson_tpu.grid.maps import _MapValue

            v = _MapValue()
            now = time.time()
            # last_access carries over as ELAPSED idle: an entry that had
            # burned 40s of a 60s max-idle window resumes with 20s left,
            # not a fresh window (RMapCache max-idle contract).
            v.data = {
                blobs[ki]: [blobs[vi], exp, idle, now - elapsed]
                for ki, vi, exp, idle, elapsed in desc["m"]
            }
            return v
        if t == "list":
            return [blobs[i] for i in desc["m"]]
        if t == "num":
            return desc["v"]
        if t == "idgen":
            return {"next": int(desc["next"]), "block": int(desc["block"])}
        if t == "ring":
            return {"cap": int(desc["cap"]), "items": [blobs[i] for i in desc["m"]]}
        raise ValueError(f"unknown grid snapshot value type {t!r}")

    def snapshot_to(self, path: str) -> int:
        """Write every persistable live entry; returns the count written.
        Atomic (tmp + rename)."""
        import io
        import json
        import os
        import struct

        blobs: list[bytes] = []

        def add_blob(b: bytes) -> int:
            blobs.append(bytes(b))
            return len(blobs) - 1

        meta = []
        skipped: dict[str, int] = {}
        now = time.time()
        with self.lock:
            for name, e in self._data.items():
                if e.expired(now):
                    continue
                desc = self._enc_entry(e.kind, e.value, add_blob)
                if desc is None:
                    skipped[e.kind] = skipped.get(e.kind, 0) + 1
                    continue
                meta.append(
                    {
                        "name": name,
                        "kind": e.kind,
                        "expire_at": e.expire_at,
                        "value": desc,
                    }
                )
        if skipped:
            import logging

            logging.getLogger(__name__).warning(
                "grid snapshot skipped non-persisted kinds: %s", skipped
            )
        header = json.dumps({"v": self._SNAP_VERSION, "entries": meta}).encode()
        buf = io.BytesIO()
        buf.write(self._SNAP_MAGIC)
        buf.write(struct.pack("<I", len(header)))
        buf.write(header)
        for b in blobs:
            buf.write(struct.pack("<I", len(b)))
            buf.write(b)
        import uuid

        parent = os.path.dirname(os.path.abspath(path))
        os.makedirs(parent, exist_ok=True)
        # Unique per WRITER (not just per process): the shutdown writer
        # and the periodic snapshotter thread may race on the same path;
        # identical tmp names would truncate each other mid-write.
        tmp = f"{path}.tmp.{os.getpid()}.{uuid.uuid4().hex[:8]}"
        with open(tmp, "wb") as f:
            f.write(buf.getvalue())
            f.flush()
            # fsync-then-rename (RT014): without the barrier a host
            # crash can publish the snapshot NAME over void bytes —
            # restore_from would then load a torn grid snapshot where
            # the pre-rename file was still intact.
            os.fsync(f.fileno())
        os.replace(tmp, path)
        from redisson_tpu.durability.journal import _fsync_dir

        _fsync_dir(parent)
        return len(meta)

    def restore_from(self, path: str) -> bool:
        """Load a snapshot written by ``snapshot_to``; True if one was
        found.  Intended at init (empty keyspace); existing names are
        overwritten (same-name restore-on-boot semantics as the sketch
        side's empty-keyspace contract, enforced by call order)."""
        import json
        import os
        import struct

        if not os.path.exists(path):
            return False
        with open(path, "rb") as f:
            data = f.read()
        if data[:4] != self._SNAP_MAGIC:
            raise ValueError("not a grid snapshot (bad magic)")
        (hlen,) = struct.unpack("<I", data[4:8])
        head = json.loads(data[8 : 8 + hlen].decode())
        if head.get("v") != self._SNAP_VERSION:
            raise ValueError(f"unsupported grid snapshot v{head.get('v')}")
        # Whole-keyspace replacement: every cached grid scalar predates
        # the restored state (near-cache reach, ISSUE 14 satellite).
        hook = self.on_invalidate_all
        if hook is not None:
            hook()
        blobs: list[bytes] = []
        off = 8 + hlen
        while off < len(data):
            (n,) = struct.unpack("<I", data[off : off + 4])
            off += 4
            if off + n > len(data):
                raise ValueError("truncated grid snapshot blob")
            blobs.append(data[off : off + n])
            off += n
        now = time.time()
        clashes = []
        with self.lock:
            for ent in head["entries"]:
                exp = ent.get("expire_at")
                if exp is not None and now >= exp:
                    continue  # expired while on disk
                if self.foreign_exists is not None and self.foreign_exists(
                    ent["name"]
                ):
                    # The sketch and grid halves snapshot at different
                    # instants; a name that moved between backends in that
                    # window must not end up live on BOTH (the one-
                    # logical-keyspace invariant).  Sketch wins: it was
                    # captured under the engine locks.
                    clashes.append(ent["name"])
                    continue
                ge = GridEntry(ent["kind"], self._dec_entry(ent["value"], blobs))
                ge.expire_at = exp
                if ent["name"] not in self._data:
                    self._note_keyspace(ent["name"], +1)
                self._data[ent["name"]] = ge
                if exp is not None:
                    self._ensure_sweeper()
            self.cond.notify_all()
        if clashes:
            import logging

            logging.getLogger(__name__).warning(
                "grid restore skipped %d name(s) held by the sketch "
                "backend (snapshot halves raced): %s",
                len(clashes), clashes[:5],
            )
        return True
