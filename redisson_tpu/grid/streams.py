"""RStream — → org/redisson/RedissonStream.java over the Redis stream
command family (XADD/XRANGE/XREAD/XGROUP/XREADGROUP/XACK/XPENDING/XCLAIM/
XTRIM, SURVEY.md §2.3 streams row): append-only log of field-map entries
with (ms, seq) ids, consumer groups with per-entry pending lists (PEL),
acks, idle-based claims.

Entry ids are strings "ms-seq" (Redis wire shape); internally (ms, seq)
tuples order the log.  Field maps are codec-encoded per field/value, so
round-trip semantics match the reference's codec behavior.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Iterable, Optional

from redisson_tpu.grid.base import GridObject, journaled


def _parse_id(s, *, default_seq: int = 0) -> tuple[int, int]:
    if isinstance(s, tuple):
        return s
    if s == "-":
        return (0, 0)
    if s == "+":
        return (2**63 - 1, 2**63 - 1)
    if "-" in str(s):
        ms, seq = str(s).split("-", 1)
        return (int(ms), int(seq))
    return (int(s), default_seq)


def _fmt_id(t: tuple[int, int]) -> str:
    return f"{t[0]}-{t[1]}"


class _StreamValue:
    __slots__ = ("entries", "last_id", "groups", "max_deleted_id", "added")

    def __init__(self):
        self.entries: dict[tuple, dict] = {}  # insertion-ordered by id
        self.last_id: tuple = (0, 0)
        self.groups: dict[str, dict] = {}
        self.max_deleted_id: tuple = (0, 0)
        self.added = 0  # entries-added counter (XINFO entries-added)


@journaled("add", "trim", "remove", "create_group", "remove_group",
           "read_group", "ack", "claim", "auto_claim")
class Stream(GridObject):
    KIND = "stream"

    @staticmethod
    def _new_value():
        return _StreamValue()

    # -- XADD / XDEL / XTRIM ----------------------------------------------

    def add(self, entries: dict, id: str = "*", *,
            maxlen: Optional[int] = None, nomkstream: bool = False) -> Optional[str]:
        """→ XADD.  ``entries``: field→value map; ``id="*"`` auto-assigns
        (ms, seq).  Returns the new entry id, or None with ``nomkstream``
        on a missing stream."""
        if not entries:
            raise ValueError("stream entry needs at least one field")
        with self._store.lock:
            if nomkstream and self._entry(create=False) is None:
                return None
            e = self._entry()
            st: _StreamValue = e.value
            if id == "*":
                ms = int(time.time() * 1000)
                if ms > st.last_id[0]:
                    new_id = (ms, 0)
                else:  # clock went backwards / same ms: bump seq
                    new_id = (st.last_id[0], st.last_id[1] + 1)
            else:
                new_id = _parse_id(id)
                if new_id <= st.last_id:
                    raise ValueError(
                        "XADD id must be greater than the stream's last id"
                    )
            st.entries[new_id] = {
                self._enc_key(k): self._enc(v) for k, v in entries.items()
            }
            st.last_id = new_id
            st.added += 1
            if maxlen is not None:
                self._trim_locked(st, maxlen)
            self._nc_bump()  # XLEN-class cached scalars retire
            self._store.cond.notify_all()  # wake blocked readers
            return _fmt_id(new_id)

    def _trim_locked(self, st: _StreamValue, maxlen: int) -> int:
        n = 0
        while len(st.entries) > maxlen:
            oldest = next(iter(st.entries))
            del st.entries[oldest]
            st.max_deleted_id = max(st.max_deleted_id, oldest)
            n += 1
        return n

    def trim(self, maxlen: int) -> int:
        """→ XTRIM MAXLEN: number of evicted entries."""
        with self._store.lock:
            e = self._entry(create=False)
            n = 0 if e is None else self._trim_locked(e.value, maxlen)
            if n:
                self._nc_bump()
            return n

    def remove(self, *ids: str) -> int:
        """→ XDEL."""
        with self._store.lock:
            e = self._entry(create=False)
            if e is None:
                return 0
            st: _StreamValue = e.value
            n = 0
            for s in ids:
                t = _parse_id(s)
                if st.entries.pop(t, None) is not None:
                    st.max_deleted_id = max(st.max_deleted_id, t)
                    n += 1
            if n:
                self._nc_bump()
            return n

    # -- reads -------------------------------------------------------------

    def _decode(self, fields: dict) -> dict:
        return {self._dec_key(k): self._dec(v) for k, v in fields.items()}

    def size(self) -> int:
        """→ XLEN.  Rides the engine near cache (ISSUE 14 satellite):
        the hottest stream-length polls answer from the host tier
        without the grid lock."""

        def compute():
            with self._store.lock:
                e = self._entry(create=False)
                return 0 if e is None else len(e.value.entries)

        return self._nc_scalar("stream", ("xlen",), compute)

    def range(self, start: str = "-", end: str = "+",
              count: Optional[int] = None) -> list:
        """→ XRANGE: [(id, fields)] ascending."""
        lo, hi = _parse_id(start), _parse_id(end, default_seq=2**63 - 1)
        with self._store.lock:
            e = self._entry(create=False)
            if e is None:
                return []
            out = [
                (_fmt_id(t), self._decode(f))
                for t, f in e.value.entries.items()
                if lo <= t <= hi
            ]
            return out if count is None else out[:count]

    def rev_range(self, start: str = "+", end: str = "-",
                  count: Optional[int] = None) -> list:
        """→ XREVRANGE: descending."""
        out = self.range(end, start)
        out.reverse()
        return out if count is None else out[:count]

    def read(self, from_id: str = "0-0", count: Optional[int] = None,
             block_seconds: Optional[float] = None) -> list:
        """→ XREAD [BLOCK]: entries with id STRICTLY greater than
        ``from_id`` ("$" = only entries added after this call)."""
        with self._store.cond:
            if from_id == "$":
                e = self._entry(create=False)
                after = e.value.last_id if e is not None else (0, 0)
            else:
                after = _parse_id(from_id)
            deadline = (
                None if block_seconds is None else time.monotonic() + block_seconds
            )
            while True:
                e = self._entry(create=False)
                if e is not None:
                    out = [
                        (_fmt_id(t), self._decode(f))
                        for t, f in e.value.entries.items()
                        if t > after
                    ]
                    if out:
                        return out if count is None else out[:count]
                if deadline is None:
                    return []
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return []
                self._store.cond.wait(timeout=min(remaining, 1.0))

    def get(self, id: str) -> Optional[dict]:
        with self._store.lock:
            e = self._entry(create=False)
            if e is None:
                return None
            f = e.value.entries.get(_parse_id(id))
            return None if f is None else self._decode(f)

    def last_id(self) -> str:
        with self._store.lock:
            e = self._entry(create=False)
            return _fmt_id(e.value.last_id if e is not None else (0, 0))

    # -- consumer groups ---------------------------------------------------

    def create_group(self, group: str, from_id: str = "$",
                     mkstream: bool = True) -> None:
        """→ XGROUP CREATE."""
        with self._store.lock:
            e = self._entry(create=mkstream)
            if e is None:
                raise RuntimeError(f"stream {self._name!r} does not exist")
            st: _StreamValue = e.value
            if group in st.groups:
                raise ValueError(f"BUSYGROUP: group {group!r} already exists")
            last = st.last_id if from_id == "$" else _parse_id(from_id)
            st.groups[group] = {
                "last_delivered": last,
                "pending": {},  # id -> {consumer, time_ms, count}
                "consumers": set(),
            }

    def remove_group(self, group: str) -> bool:
        """→ XGROUP DESTROY."""
        with self._store.lock:
            e = self._entry(create=False)
            if e is None:
                return False
            return e.value.groups.pop(group, None) is not None

    def list_groups(self) -> list[dict]:
        """→ XINFO GROUPS."""
        with self._store.lock:
            e = self._entry(create=False)
            if e is None:
                return []
            return [
                {
                    "name": g,
                    "consumers": len(d["consumers"]),
                    "pending": len(d["pending"]),
                    "last_delivered_id": _fmt_id(d["last_delivered"]),
                }
                for g, d in e.value.groups.items()
            ]

    def list_consumers(self, group: str) -> list[dict]:
        """→ XINFO CONSUMERS."""
        with self._store.lock:
            g = self._group(group)
            per = {c: 0 for c in g["consumers"]}
            for p in g["pending"].values():
                per[p["consumer"]] = per.get(p["consumer"], 0) + 1
            return [{"name": c, "pending": n} for c, n in per.items()]

    def _group(self, group: str) -> dict:
        e = self._entry(create=False)
        if e is None or group not in e.value.groups:
            raise ValueError(f"NOGROUP: no such group {group!r}")
        return e.value.groups[group]

    def read_group(self, group: str, consumer: str,
                   count: Optional[int] = None, ids: str = ">",
                   block_seconds: Optional[float] = None,
                   noack: bool = False) -> list:
        """→ XREADGROUP: ``ids=">"`` delivers NEW entries (advancing the
        group cursor and adding to the consumer's PEL — unless ``noack``,
        the XREADGROUP NOACK contract: delivered entries skip the PEL
        entirely); an explicit id re-reads this consumer's pending
        entries after it."""
        deadline = (
            None if block_seconds is None else time.monotonic() + block_seconds
        )
        with self._store.cond:
            while True:
                g = self._group(group)
                g["consumers"].add(consumer)
                e = self._entry(create=False)
                st: _StreamValue = e.value
                now_ms = int(time.time() * 1000)
                if ids == ">":
                    out = []
                    for t, f in st.entries.items():
                        if t > g["last_delivered"]:
                            out.append((_fmt_id(t), self._decode(f)))
                            if not noack:
                                g["pending"][t] = {
                                    "consumer": consumer,
                                    "time_ms": now_ms,
                                    "count": 1,
                                }
                            g["last_delivered"] = t
                            if count is not None and len(out) >= count:
                                break
                    if out or deadline is None:
                        return out
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        return []
                    self._store.cond.wait(timeout=min(remaining, 1.0))
                    continue
                after = _parse_id(ids)
                out = []
                for t, p in sorted(g["pending"].items()):
                    if t > after and p["consumer"] == consumer:
                        f = st.entries.get(t)
                        if f is None:
                            continue  # XDEL'd while pending
                        p["count"] += 1
                        out.append((_fmt_id(t), self._decode(f)))
                        if count is not None and len(out) >= count:
                            break
                return out

    def ack(self, group: str, *ids: str) -> int:
        """→ XACK."""
        with self._store.lock:
            g = self._group(group)
            n = 0
            for s in ids:
                if g["pending"].pop(_parse_id(s), None) is not None:
                    n += 1
            return n

    def pending(self, group: str) -> dict:
        """→ XPENDING summary: total + per-consumer counts + id range."""
        with self._store.lock:
            g = self._group(group)
            per: dict[str, int] = {}
            for p in g["pending"].values():
                per[p["consumer"]] = per.get(p["consumer"], 0) + 1
            ids = sorted(g["pending"])
            return {
                "total": len(ids),
                "lowest_id": _fmt_id(ids[0]) if ids else None,
                "highest_id": _fmt_id(ids[-1]) if ids else None,
                "consumers": per,
            }

    def pending_range(self, group: str, start: str = "-", end: str = "+",
                      count: Optional[int] = None,
                      consumer: Optional[str] = None) -> list[dict]:
        """→ XPENDING with range: [{id, consumer, idle_ms, delivered}]."""
        lo, hi = _parse_id(start), _parse_id(end, default_seq=2**63 - 1)
        now_ms = int(time.time() * 1000)
        with self._store.lock:
            g = self._group(group)
            out = []
            for t in sorted(g["pending"]):
                if not (lo <= t <= hi):
                    continue
                p = g["pending"][t]
                if consumer is not None and p["consumer"] != consumer:
                    continue
                out.append(
                    {
                        "id": _fmt_id(t),
                        "consumer": p["consumer"],
                        "idle_ms": now_ms - p["time_ms"],
                        "delivered": p["count"],
                    }
                )
                if count is not None and len(out) >= count:
                    break
            return out

    def claim(self, group: str, consumer: str, min_idle_ms: int,
              *ids: str) -> list:
        """→ XCLAIM: transfer ownership of idle pending entries; returns
        the claimed [(id, fields)]."""
        now_ms = int(time.time() * 1000)
        with self._store.lock:
            g = self._group(group)
            e = self._entry(create=False)
            st: _StreamValue = e.value
            g["consumers"].add(consumer)
            out = []
            for s in ids:
                t = _parse_id(s)
                p = g["pending"].get(t)
                if p is None or now_ms - p["time_ms"] < min_idle_ms:
                    continue
                f = st.entries.get(t)
                if f is None:  # deleted entry: drop from PEL (Redis 6.2+)
                    del g["pending"][t]
                    continue
                p.update(consumer=consumer, time_ms=now_ms)
                p["count"] += 1
                out.append((_fmt_id(t), self._decode(f)))
            return out

    def auto_claim(self, group: str, consumer: str, min_idle_ms: int,
                   start: str = "0-0", count: int = 100,
                   with_cursor: bool = False, justid: bool = False):
        """→ XAUTOCLAIM: claim up to ``count`` idle entries from ``start``.
        Ownership transfers ONLY for entries actually returned — claiming
        is done under one lock pass that stops at ``count``, so no entry
        is silently reassigned (and its idle clock reset) invisibly.
        ``with_cursor`` additionally returns the Redis next-cursor — the
        id to continue from when COUNT truncated the sweep, '0-0' when
        the whole PEL was examined (callers looping until 0-0 must not
        be told a truncated sweep was exhaustive) — plus the ids DELETED
        from the PEL during the sweep (entries removed from the stream
        since delivery), the third element of the XAUTOCLAIM reply."""
        now_ms = int(time.time() * 1000)
        lo = _parse_id(start)
        with self._store.lock:
            g = self._group(group)
            e = self._entry(create=False)
            st: _StreamValue = e.value
            g["consumers"].add(consumer)
            out = []
            deleted = []
            next_cursor = "0-0"
            pending_sorted = sorted(g["pending"])
            for i, t in enumerate(pending_sorted):
                if t < lo:
                    continue
                p = g["pending"][t]
                if now_ms - p["time_ms"] < min_idle_ms:
                    continue
                f = st.entries.get(t)
                if f is None:  # deleted entry: drop from PEL (Redis 6.2+)
                    del g["pending"][t]
                    deleted.append(_fmt_id(t))
                    continue
                p.update(consumer=consumer, time_ms=now_ms)
                if not justid:
                    # JUSTID leaves the delivery counter untouched (Redis
                    # contract): an inspection sweep must not push entries
                    # toward dead-letter thresholds keyed on the count.
                    p["count"] += 1
                out.append((_fmt_id(t), self._decode(f)))
                if len(out) >= count:
                    # Truncated: continue from the id AFTER this one.
                    later = [u for u in pending_sorted[i + 1:]
                             if u in g["pending"]]
                    if later:
                        next_cursor = _fmt_id(later[0])
                    break
            if with_cursor:
                return next_cursor, out, deleted
            return out


class ReliableTopic(GridObject):
    """→ org/redisson/RedissonReliableTopic.java: at-least-once topic
    backed by a stream — every listener is a consumer group cursor, so
    subscribers added later replay from their subscription point and slow
    listeners never lose messages (contrast fire-and-forget RTopic)."""

    KIND = "stream"

    def __init__(self, name, client):
        super().__init__(name, client)
        self._stream = Stream(name, client)
        self._listeners: dict[int, tuple[str, Any]] = {}
        self._next_id = 0
        self._pump: Optional[Any] = None

    def publish(self, message: Any) -> int:
        """Appends to the stream; returns subscriber count across EVERY
        handle of this topic (the shared stream's listener groups are the
        truth — this handle's _listeners alone reported 0 when the
        subscribers lived on another handle).  Delivery is signal-driven:
        Stream.add notifies the SHARED store condition, so the pump wakes
        for publishes from ANY handle."""
        self._stream.add({"m": message})
        with self._store.lock:
            e = self._stream._entry(create=False)
            if e is None:
                return 0
            return sum(
                1 for g in e.value.groups if g.startswith("listener:")
            )

    def _added_count(self) -> int:
        e = self._stream._entry(create=False)
        return 0 if e is None else e.value.added

    def add_listener(self, listener) -> int:
        import uuid

        with self._store.lock:
            lid = self._next_id
            self._next_id += 1
            group = f"listener:{uuid.uuid4().hex[:12]}"
            self._stream.create_group(group, from_id="$")
            self._listeners[lid] = (group, listener)
            if self._pump is None:
                t = threading.Thread(
                    target=self._pump_loop, name="rtpu-reliable-topic",
                    daemon=True,
                )
                self._pump = t
                t.start()
        return lid

    def remove_listener(self, listener_id: int) -> None:
        with self._store.cond:
            got = self._listeners.pop(listener_id, None)
            if got is not None:
                try:
                    self._stream.remove_group(got[0])
                except Exception:
                    pass
            if not self._listeners:
                # Last listener gone: the pump loop exits on its next
                # wake (it would otherwise spin for the process lifetime)
                # and a future add_listener starts a fresh one.
                self._store.cond.notify_all()

    def _pump_loop(self) -> None:
        while True:
            with self._store.lock:
                if not self._listeners:
                    # No subscribers: terminate instead of idling forever;
                    # add_listener re-arms a fresh pump.
                    if self._pump is threading.current_thread():
                        self._pump = None
                    return
                subs = list(self._listeners.items())
                seen = self._added_count()
            delivered = False
            for lid, (group, fn) in subs:
                try:
                    msgs = self._stream.read_group(group, "pump", count=64)
                except ValueError:
                    continue  # group removed concurrently
                for mid, fields in msgs:
                    try:
                        fn(self._name, fields["m"])
                    except Exception:  # listener errors must not kill the
                        pass  # pump (at-least-once: message still acked,
                        # matching the reference's listener-isolation)
                    self._stream.ack(group, mid)
                    delivered = True
            if not delivered:
                # Park on the SHARED store condition Stream.add notifies
                # (condvar, not a poll tax); the added-counter re-check
                # under the lock closes the publish-before-park window;
                # 1 s fallback bounds exotic writers that bypass XADD.
                with self._store.cond:
                    if self._added_count() == seen:
                        self._store.cond.wait(timeout=1.0)

    def count_listeners(self) -> int:
        with self._store.lock:
            return len(self._listeners)
