"""RTimeSeries — → org/redisson/RedissonTimeSeries.java (SURVEY.md §2.3
geo/time row): timestamp-ordered values with optional labels and per-entry
TTL, range queries in both directions, first/last/poll access.
"""

from __future__ import annotations

import bisect
import time
from typing import Any, Iterable, Optional

from redisson_tpu.grid.base import GridObject


class _TsValue:
    __slots__ = ("ts", "rows")  # parallel: sorted timestamps + row dicts

    def __init__(self):
        self.ts: list[int] = []
        self.rows: list[dict] = []  # {"v": bytes, "label": bytes|None, "exp": float|None}

    def prune_expired(self, now: float) -> None:
        keep_ts, keep_rows = [], []
        for t, r in zip(self.ts, self.rows):
            if r["exp"] is None or now < r["exp"]:
                keep_ts.append(t)
                keep_rows.append(r)
        self.ts, self.rows = keep_ts, keep_rows


class TimeSeries(GridObject):
    KIND = "timeseries"

    @staticmethod
    def _new_value():
        return _TsValue()

    def _live(self, create: bool = False) -> Optional[_TsValue]:
        e = self._entry(create=create)
        if e is None:
            return None
        e.value.prune_expired(time.time())
        return e.value

    # -- writes ------------------------------------------------------------

    def add(self, timestamp: int, value: Any, label: Any = None,
            ttl_seconds: Optional[float] = None) -> None:
        """→ RTimeSeries#add: same-timestamp add REPLACES (reference
        semantics — one value per timestamp)."""
        with self._store.lock:
            v = self._live(create=True)
            row = {
                "v": self._enc(value),
                "label": None if label is None else self._enc(label),
                "exp": None if ttl_seconds is None else time.time() + ttl_seconds,
            }
            i = bisect.bisect_left(v.ts, int(timestamp))
            if i < len(v.ts) and v.ts[i] == int(timestamp):
                v.rows[i] = row
            else:
                v.ts.insert(i, int(timestamp))
                v.rows.insert(i, row)

    def add_all(self, entries: Iterable[tuple], ttl_seconds: Optional[float] = None) -> None:
        for ts, value in entries:
            self.add(ts, value, ttl_seconds=ttl_seconds)

    def remove(self, timestamp: int) -> bool:
        with self._store.lock:
            v = self._live()
            if v is None:
                return False
            i = bisect.bisect_left(v.ts, int(timestamp))
            if i < len(v.ts) and v.ts[i] == int(timestamp):
                del v.ts[i]
                del v.rows[i]
                return True
            return False

    def remove_range(self, from_ts: int, to_ts: int) -> int:
        """Removes [from_ts, to_ts] inclusive (reference range semantics)."""
        with self._store.lock:
            v = self._live()
            if v is None:
                return 0
            lo = bisect.bisect_left(v.ts, int(from_ts))
            hi = bisect.bisect_right(v.ts, int(to_ts))
            n = hi - lo
            del v.ts[lo:hi]
            del v.rows[lo:hi]
            return n

    # -- reads -------------------------------------------------------------

    def get(self, timestamp: int) -> Any:
        with self._store.lock:
            v = self._live()
            if v is None:
                return None
            i = bisect.bisect_left(v.ts, int(timestamp))
            if i < len(v.ts) and v.ts[i] == int(timestamp):
                return self._dec(v.rows[i]["v"])
            return None

    def size(self) -> int:
        with self._store.lock:
            v = self._live()
            return 0 if v is None else len(v.ts)

    def range(self, from_ts: int, to_ts: int, limit: Optional[int] = None) -> list:
        """[(timestamp, value)] ascending over [from_ts, to_ts]."""
        with self._store.lock:
            v = self._live()
            if v is None:
                return []
            lo = bisect.bisect_left(v.ts, int(from_ts))
            hi = bisect.bisect_right(v.ts, int(to_ts))
            out = [
                (v.ts[i], self._dec(v.rows[i]["v"])) for i in range(lo, hi)
            ]
            return out if limit is None else out[:limit]

    def range_reversed(self, from_ts: int, to_ts: int, limit: Optional[int] = None) -> list:
        out = self.range(from_ts, to_ts)
        out.reverse()
        return out if limit is None else out[:limit]

    def entry_range(self, from_ts: int, to_ts: int) -> list:
        """[(timestamp, value, label|None)] ascending."""
        with self._store.lock:
            v = self._live()
            if v is None:
                return []
            lo = bisect.bisect_left(v.ts, int(from_ts))
            hi = bisect.bisect_right(v.ts, int(to_ts))
            return [
                (
                    v.ts[i],
                    self._dec(v.rows[i]["v"]),
                    None
                    if v.rows[i]["label"] is None
                    else self._dec(v.rows[i]["label"]),
                )
                for i in range(lo, hi)
            ]

    def first(self, count: int = 1) -> list:
        with self._store.lock:
            v = self._live()
            if v is None:
                return []
            return [self._dec(r["v"]) for r in v.rows[:count]]

    def last(self, count: int = 1) -> list:
        if count <= 0:  # [-0:] is the WHOLE list, not none of it
            return []
        with self._store.lock:
            v = self._live()
            if v is None:
                return []
            return [self._dec(r["v"]) for r in v.rows[-count:]][::-1]

    def first_timestamp(self) -> Optional[int]:
        with self._store.lock:
            v = self._live()
            return v.ts[0] if v and v.ts else None

    def last_timestamp(self) -> Optional[int]:
        with self._store.lock:
            v = self._live()
            return v.ts[-1] if v and v.ts else None

    def poll_first(self, count: int = 1) -> list:
        with self._store.lock:
            v = self._live()
            if v is None:
                return []
            out = [self._dec(r["v"]) for r in v.rows[:count]]
            del v.ts[:count]
            del v.rows[:count]
            return out

    def poll_last(self, count: int = 1) -> list:
        if count <= 0:  # [-0:] slices destroyed the ENTIRE series
            return []
        with self._store.lock:
            v = self._live()
            if v is None or not v.ts:
                return []
            n = min(count, len(v.ts))
            out = [self._dec(r["v"]) for r in v.rows[-n:]][::-1]
            del v.ts[-n:]
            del v.rows[-n:]
            return out
