"""Topics — → org/redisson/RedissonTopic.java (RTopic pub/sub),
RedissonPatternTopic (PSUBSCRIBE glob patterns).

The bus is host-side by design (SURVEY.md §2.4 pub/sub row): listener
callbacks run on the client's delivery executor, and this is the ingest
path that feeds the CMS streaming kernel (BASELINE config 5, §3.5).
"""

from __future__ import annotations

import fnmatch
import threading
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Callable, Optional

from redisson_tpu.analysis import witness as _witness
from redisson_tpu.objects.base import CamelCompatMixin


class TopicBus:
    """Per-client pub/sub hub (the PublishSubscribeService analog)."""

    def __init__(self, n_threads: int = 2):
        self._lock = _witness.named(threading.Lock(), "grid.topics.bus")
        self._listeners: dict[str, dict[int, Callable]] = {}
        self._pattern_listeners: dict[str, dict[int, Callable]] = {}
        self._next_id = 1
        self._pool = ThreadPoolExecutor(
            max_workers=n_threads, thread_name_prefix="rtpu-topic"
        )
        # Per-channel FIFO delivery: each channel's messages drain on ONE
        # worker at a time (cross-channel parallelism preserved) — with a
        # free-for-all pool, two mutations' events could be observed out
        # of order by the same listener.
        self._chan_queues: dict[str, list] = {}
        self._chan_active: set[str] = set()
        # Notified whenever a channel finishes its queued deliveries —
        # drain() waits here.
        self._idle_cv = threading.Condition(self._lock)

    def subscribe(self, channel: str, listener: Callable) -> int:
        with self._lock:
            lid = self._next_id
            self._next_id += 1
            self._listeners.setdefault(channel, {})[lid] = listener
            return lid

    def subscribe_pattern(self, pattern: str, listener: Callable) -> int:
        with self._lock:
            lid = self._next_id
            self._next_id += 1
            self._pattern_listeners.setdefault(pattern, {})[lid] = listener
            return lid

    def unsubscribe(self, channel: str, listener_id: Optional[int] = None) -> None:
        with self._lock:
            if listener_id is None:
                self._listeners.pop(channel, None)
            else:
                self._listeners.get(channel, {}).pop(listener_id, None)

    def unsubscribe_pattern(self, pattern: str, listener_id: Optional[int] = None) -> None:
        with self._lock:
            if listener_id is None:
                self._pattern_listeners.pop(pattern, None)
            else:
                self._pattern_listeners.get(pattern, {}).pop(listener_id, None)

    def publish(self, channel: str, message: Any) -> int:
        """Returns the number of receivers (PUBLISH reply semantics).
        Deliveries for one channel run in publish order (FIFO)."""
        with self._lock:
            targets = [
                (None, fn) for fn in self._listeners.get(channel, {}).values()
            ]
            for pat, subs in self._pattern_listeners.items():
                if fnmatch.fnmatchcase(channel, pat):
                    targets.extend((pat, fn) for fn in subs.values())
            if targets:
                self._chan_queues.setdefault(channel, []).append(
                    (targets, message)
                )
                if channel not in self._chan_active:
                    self._chan_active.add(channel)
                    self._pool.submit(self._drain_channel, channel)
        return len(targets)

    def _drain_channel(self, channel: str) -> None:
        while True:
            with self._lock:
                queue = self._chan_queues.get(channel)
                if not queue:
                    self._chan_active.discard(channel)
                    self._chan_queues.pop(channel, None)
                    self._idle_cv.notify_all()
                    return
                targets, message = queue.pop(0)
            for pat, fn in targets:
                if pat is None:
                    self._safe(fn, channel, message)
                else:
                    self._safe_pattern(fn, pat, channel, message)

    @staticmethod
    def _safe(fn, channel, message) -> None:
        try:
            fn(channel, message)
        except Exception:  # listener errors never kill delivery
            import logging

            logging.getLogger(__name__).exception("topic listener failed")

    @staticmethod
    def _safe_pattern(fn, pattern, channel, message) -> None:
        try:
            fn(pattern, channel, message)
        except Exception:
            import logging

            logging.getLogger(__name__).exception("pattern listener failed")

    def count_listeners(self, channel: str) -> int:
        with self._lock:
            n = len(self._listeners.get(channel, {}))
            n += sum(
                len(subs)
                for pat, subs in self._pattern_listeners.items()
                if fnmatch.fnmatchcase(channel, pat)
            )
            return n

    def drain(
        self, timeout: Optional[float] = None, channel: Optional[str] = None
    ) -> bool:
        """Barrier: block until every delivery queued before this call has
        COMPLETED (queues empty + no channel mid-callback).  Exact, not a
        pool rendezvous: the old worker-barrier broke silently at its
        5s timeout when deliveries outlasted it — callers (TopicCmsBridge
        teardown, the config-5 bench) then closed their listeners with
        messages still queued, and those events were silently dropped
        (caught as a NEGATIVE signed CMS estimate error, which a lossless
        pipe can never produce).  ``channel``: wait only for that
        channel's deliveries (listener-teardown scope).  Returns False
        only when ``timeout`` (None = wait indefinitely) elapsed with
        work still pending."""
        import time as _time

        def pending() -> bool:
            if channel is None:
                return bool(self._chan_queues or self._chan_active)
            return (
                channel in self._chan_queues or channel in self._chan_active
            )

        deadline = None if timeout is None else _time.monotonic() + timeout
        with self._idle_cv:
            while pending():
                if deadline is None:
                    self._idle_cv.wait(timeout=1.0)
                else:
                    remaining = deadline - _time.monotonic()
                    if remaining <= 0:
                        return False
                    self._idle_cv.wait(timeout=min(1.0, remaining))
        return True

    def shutdown(self) -> None:
        self._pool.shutdown(wait=False)


class Topic(CamelCompatMixin):
    """→ RTopic: add_listener(fn(channel, msg)) + publish."""

    def __init__(self, name: str, client):
        self._name = name
        self._client = client
        self._bus = client._topic_bus

    def get_name(self) -> str:
        return self._name

    def add_listener(self, listener: Callable) -> int:
        return self._bus.subscribe(self._name, listener)

    def remove_listener(self, listener_id: int) -> None:
        self._bus.unsubscribe(self._name, listener_id)

    def remove_all_listeners(self) -> None:
        self._bus.unsubscribe(self._name)

    def publish(self, message: Any) -> int:
        return self._bus.publish(self._name, message)

    def count_subscribers(self) -> int:
        return self._bus.count_listeners(self._name)


class PatternTopic(CamelCompatMixin):
    """→ RPatternTopic: glob-pattern subscription
    (listener(fn(pattern, channel, msg)))."""

    def __init__(self, pattern: str, client):
        self._pattern = pattern
        self._bus = client._topic_bus

    def get_pattern(self) -> str:
        return self._pattern

    def add_listener(self, listener: Callable) -> int:
        return self._bus.subscribe_pattern(self._pattern, listener)

    def remove_listener(self, listener_id: int) -> None:
        self._bus.unsubscribe_pattern(self._pattern, listener_id)


class ShardedTopic(Topic):
    """→ RedissonShardedTopic (SPUBLISH/SSUBSCRIBE): in Redis cluster the
    channel pins to one slot's shard; in-process there is one bus, so the
    semantic difference (no cross-shard broadcast fan-out) is moot — the
    API class exists so reference code ports verbatim."""
