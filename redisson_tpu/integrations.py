"""Framework integrations — the L8 layer (SURVEY.md §1).

The reference ships Spring Cache/session, Hibernate 2nd-level cache and
Tomcat session modules; their Python-idiomatic analogs are:

- ``cached``: a method/function memoization decorator over a named cache
  (→ Spring's @Cacheable/@CacheEvict pair on RedissonSpringCacheManager).
- ``CacheManagerAdapter``: maps cache names to JCache instances with
  per-cache TTL config (→ RedissonSpringCacheManager's CacheConfig map).
- ``SessionStore``: a web-session store with TTL and dict-like sessions
  (→ redisson-tomcat / Spring Session's RedissonSessionRepository);
  framework-agnostic: any WSGI/ASGI middleware can call load/save.
"""

from __future__ import annotations

import functools
import pickle
import uuid
from typing import Any, Optional

from redisson_tpu.grid.jcache import CacheManager as _GridCacheManager


def cached(client, cache_name: str, *, ttl_seconds: Optional[float] = None,
           key_fn=None):
    """→ @Cacheable: memoize through a named JCache.

    ``key_fn(*args, **kwargs)`` overrides the default repr-based key.
    The wrapper exposes ``cache_evict(*args, **kwargs)`` (→ @CacheEvict)
    and ``cache_clear()``.
    """
    cache = client.get_jcache(cache_name, default_ttl_seconds=ttl_seconds)

    def decorate(fn):
        def make_key(args, kwargs):
            if key_fn is not None:
                return key_fn(*args, **kwargs)
            # Function identity in the default key: two functions
            # memoized into one cache_name must not collide on equal
            # arguments (f(1) returning g's cached result).
            ident = (fn.__module__, fn.__qualname__)
            return pickle.dumps((ident, args, tuple(sorted(kwargs.items()))))

        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            key = make_key(args, kwargs)
            hit = cache.get(key)
            if hit is not None:
                return hit
            value = fn(*args, **kwargs)
            if value is not None:  # None is the miss sentinel, like Spring's
                cache.put(key, value)  # default null-caching-off behavior
            return value

        def cache_evict(*args, **kwargs):
            cache.remove(make_key(args, kwargs))

        wrapper.cache_evict = cache_evict
        wrapper.cache_clear = cache.clear
        wrapper.cache = cache
        return wrapper

    return decorate


class CacheManagerAdapter(_GridCacheManager):
    """→ RedissonSpringCacheManager: the grid CacheManager plus the
    per-name CacheConfig map (ttl) Spring's manager carries."""

    def __init__(self, client, configs: Optional[dict] = None):
        super().__init__(client)
        self._configs = dict(configs or {})

    def get_cache(self, name: str):
        if name not in self._caches:
            cfg = self._configs.get(name, {})
            return self.create_cache(
                name, default_ttl_seconds=cfg.get("ttl_seconds")
            )
        return super().get_cache(name)

    def get_cache_names(self) -> list:
        return sorted(set(self._configs) | set(self._caches))


class Session(dict):
    """One web session: a dict persisted by its SessionStore."""

    def __init__(self, store: "SessionStore", session_id: str, data: dict):
        super().__init__(data)
        self._store = store
        self.session_id = session_id

    def save(self) -> None:
        self._store.save(self)

    def invalidate(self) -> None:
        self._store.delete(self.session_id)
        self.clear()


class SessionStore:
    """→ redisson-tomcat / Spring Session: TTL'd sessions over the grid
    map catalog.  ``load`` refreshes the inactivity window on access
    (the maxInactiveInterval contract)."""

    def __init__(self, client, *, prefix: str = "session",
                 max_inactive_seconds: float = 1800.0):
        self._client = client
        self._prefix = prefix
        self._ttl = max_inactive_seconds

    def _bucket(self, session_id: str):
        return self._client.get_bucket(f"{self._prefix}:{session_id}")

    def create(self) -> Session:
        sid = uuid.uuid4().hex
        session = Session(self, sid, {})
        self.save(session)
        return session

    def load(self, session_id: str) -> Optional[Session]:
        b = self._bucket(session_id)
        data = b.get()
        if data is None:
            return None
        b.expire(self._ttl)  # touch: sliding inactivity window
        return Session(self, session_id, data)

    def save(self, session: Session) -> None:
        self._bucket(session.session_id).set(dict(session), ttl_seconds=self._ttl)

    def delete(self, session_id: str) -> bool:
        return self._bucket(session_id).delete()
