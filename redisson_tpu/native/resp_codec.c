/* RESP2 wire codec — the native hot loop of the front door.
 *
 * Role parity: org/redisson/client/handler/CommandDecoder (the reference
 * decodes RESP frames inside Netty's native-transport event loop; this
 * framework's serving tier is Python, so the per-byte frame scan is the
 * one place the host language binds — SURVEY.md §7 stance: native code
 * where the Python host loop is the measured bottleneck).
 *
 * One call parses as many COMPLETE pipelined command frames
 * (`*N\r\n` followed by N `$len\r\n<bytes>\r\n` bulks) as fit the caller's
 * descriptor capacity, writing per-argument (offset, length) descriptors
 * into flat arrays — zero copies; Python slices the argument bytes out of
 * its own buffer afterwards.
 *
 * Exit conditions (err):
 *   0 — clean stop: out of complete frames, or descriptor capacity hit.
 *   1 — protocol error at byte *consumed (caller: surface/close).
 *   2 — frame does not start with '*' (inline command etc.): caller
 *       falls back to the slow-path parser for this frame.
 * Frames already parsed before the stop are always valid; *consumed is
 * the exact byte count they occupy.
 *
 * Build: cc -O2 -shared -fPIC resp_codec.c -o _resp_codec.so
 * (loaded via ctypes — redisson_tpu/serve/native_codec.py).
 */

#include <stdint.h>
#include <string.h>

/* The build probes cc/gcc/g++/clang in order; under a C++ compiler the
 * symbols must not mangle (ctypes looks them up by C name). */
#ifdef __cplusplus
extern "C" {
#endif

long rtpu_resp_parse(const unsigned char *buf, long len,
                     long max_frames, long max_args_total,
                     long *counts, long *offs, long *lens,
                     long *consumed, long *err)
{
    long pos = 0, nframes = 0, nargs = 0;
    *err = 0;
    while (nframes < max_frames) {
        long p = pos;
        if (p >= len)
            break;
        if (buf[p] != '*') {
            *err = 2;
            break;
        }
        /* *N\r\n header */
        long q = p + 1, n = 0, digs = 0;
        while (q < len && buf[q] >= '0' && buf[q] <= '9') {
            n = n * 10 + (buf[q] - '0');
            q++;
            digs++;
            if (n > 1024 * 1024) { /* argv cap, matches Redis proto limit */
                *err = 1;
                goto out;
            }
        }
        if (q + 1 >= len)
            break; /* incomplete header */
        if (digs == 0 || buf[q] != '\r' || buf[q + 1] != '\n') {
            *err = 1;
            break;
        }
        q += 2;
        if (nargs + n > max_args_total) {
            /* Descriptor capacity: stop BEFORE this frame.  If it is the
             * FIRST frame, no progress is possible at any buffer size —
             * signal fallback so the caller's slow path (which has no
             * argc capacity) parses it instead of waiting forever. */
            if (nframes == 0)
                *err = 2;
            break;
        }
        long ok = 1;
        for (long i = 0; i < n; i++) {
            if (q >= len) {
                ok = 0;
                break;
            }
            if (buf[q] != '$') {
                *err = 1;
                goto out;
            }
            long r = q + 1, blen = 0, d2 = 0;
            while (r < len && buf[r] >= '0' && buf[r] <= '9') {
                blen = blen * 10 + (buf[r] - '0');
                r++;
                d2++;
                if (blen > 512L * 1024 * 1024) { /* proto-max-bulk-len */
                    *err = 1;
                    goto out;
                }
            }
            if (r + 1 >= len) {
                ok = 0;
                break;
            }
            if (d2 == 0 || buf[r] != '\r' || buf[r + 1] != '\n') {
                *err = 1;
                goto out;
            }
            r += 2;
            if (r + blen + 2 > len) {
                ok = 0;
                break;
            }
            if (buf[r + blen] != '\r' || buf[r + blen + 1] != '\n') {
                *err = 1;
                goto out;
            }
            offs[nargs + i] = r;
            lens[nargs + i] = blen;
            q = r + blen + 2;
        }
        if (!ok)
            break; /* incomplete frame: wait for more bytes */
        counts[nframes] = n;
        nframes++;
        nargs += n;
        pos = q;
    }
out:
    *consumed = pos;
    return nframes;
}

/* Serialize a batch of integer replies (`:n\r\n`) — the common reply shape
 * of SETBIT/SADD/HSET/... pipelines; one call per flush instead of one
 * Python string-build per reply. Returns bytes written, or -1 if the
 * output buffer is too small. */
long rtpu_resp_encode_ints(const long *vals, long n, unsigned char *out,
                           long cap)
{
    long w = 0;
    for (long i = 0; i < n; i++) {
        long v = vals[i];
        unsigned char tmp[24];
        long t = 0, neg = 0;
        if (w + 26 > cap)
            return -1;
        if (v < 0) {
            neg = 1;
            v = -v;
        }
        do {
            tmp[t++] = '0' + (unsigned char)(v % 10);
            v /= 10;
        } while (v);
        out[w++] = ':';
        if (neg)
            out[w++] = '-';
        while (t)
            out[w++] = tmp[--t];
        out[w++] = '\r';
        out[w++] = '\n';
    }
    return w;
}

/* Serialize a batch of bulk-string replies (`$len\r\n<bytes>\r\n`, or
 * `$-1\r\n` for nil when lens[i] < 0) — the common reply shape of fused
 * GET/MGET runs and container reads (HGETALL/LRANGE/SMEMBERS pipelines).
 * Values arrive concatenated in `payload` at (offs[i], lens[i]); one call
 * per reply batch instead of one Python string-build per value.  Returns
 * bytes written, or -1 if the output buffer is too small. */
long rtpu_resp_encode_bulks(const unsigned char *payload, const long *offs,
                            const long *lens, long n, unsigned char *out,
                            long cap)
{
    long w = 0;
    for (long i = 0; i < n; i++) {
        long L = lens[i];
        if (L < 0) {
            if (w + 5 > cap)
                return -1;
            memcpy(out + w, "$-1\r\n", 5);
            w += 5;
            continue;
        }
        /* "$" + <=20 digits + CRLF + payload + CRLF */
        if (w + L + 26 > cap)
            return -1;
        out[w++] = '$';
        unsigned char tmp[24];
        long t = 0, v = L;
        do {
            tmp[t++] = '0' + (unsigned char)(v % 10);
            v /= 10;
        } while (v);
        while (t)
            out[w++] = tmp[--t];
        out[w++] = '\r';
        out[w++] = '\n';
        memcpy(out + w, payload + offs[i], (size_t)L);
        w += L;
        out[w++] = '\r';
        out[w++] = '\n';
    }
    return w;
}

#ifdef __cplusplus
}
#endif
