/* RESP2 wire codec — the native hot loop of the front door.
 *
 * Role parity: org/redisson/client/handler/CommandDecoder (the reference
 * decodes RESP frames inside Netty's native-transport event loop; this
 * framework's serving tier is Python, so the per-byte frame scan is the
 * one place the host language binds — SURVEY.md §7 stance: native code
 * where the Python host loop is the measured bottleneck).
 *
 * One call parses as many COMPLETE pipelined command frames
 * (`*N\r\n` followed by N `$len\r\n<bytes>\r\n` bulks) as fit the caller's
 * descriptor capacity, writing per-argument (offset, length) descriptors
 * into flat arrays — zero copies; Python slices the argument bytes out of
 * its own buffer afterwards.
 *
 * Exit conditions (err):
 *   0 — clean stop: out of complete frames, or descriptor capacity hit.
 *   1 — protocol error at byte *consumed (caller: surface/close).
 *   2 — frame does not start with '*' (inline command etc.): caller
 *       falls back to the slow-path parser for this frame.
 * Frames already parsed before the stop are always valid; *consumed is
 * the exact byte count they occupy.
 *
 * Build: cc -O2 -shared -fPIC resp_codec.c -o _resp_codec.so
 * (loaded via ctypes — redisson_tpu/serve/native_codec.py).
 */

#include <errno.h>
#include <stdint.h>
#include <string.h>
#include <unistd.h>

/* The build probes cc/gcc/g++/clang in order; under a C++ compiler the
 * symbols must not mangle (ctypes looks them up by C name). */
#ifdef __cplusplus
extern "C" {
#endif

/* Family classification for the reactor's merged window (must mirror
 * _Reactor._family_key in serve/reactor.py): commands of one fusable
 * family chunk together inside a dispatch round.  Returns the family
 * class only — the grouping OBJECT (argv[1]) is already a parsed
 * descriptor on the Python side. */
static long rtpu_classify(const unsigned char *p, long n)
{
    unsigned char u[10];
    if (n < 3 || n > 10)
        return 0;
    for (long i = 0; i < n; i++) {
        unsigned char c = p[i];
        if (c >= 'a' && c <= 'z')
            c = (unsigned char)(c - 32);
        u[i] = c;
    }
    switch (n) {
    case 3:
        if (!memcmp(u, "GET", 3))
            return 3;
        break;
    case 4:
        if (!memcmp(u, "MGET", 4))
            return 3;
        break;
    case 6:
        if (!memcmp(u, "BF.ADD", 6))
            return 1;
        if (!memcmp(u, "SETBIT", 6) || !memcmp(u, "GETBIT", 6))
            return 2;
        break;
    case 7:
        if (!memcmp(u, "BF.MADD", 7))
            return 1;
        break;
    case 9:
        if (!memcmp(u, "BF.EXISTS", 9))
            return 1;
        if (!memcmp(u, "CMS.QUERY", 9))
            return 4;
        break;
    case 10:
        if (!memcmp(u, "BF.MEXISTS", 10))
            return 1;
        break;
    }
    return 0;
}

/* Shared frame scan: rtpu_resp_parse with an optional per-frame family
 * output (fams != 0 additionally classifies argv[0] of every complete
 * frame — the run-detection half of the tick loop). */
static long rtpu_parse_core(const unsigned char *buf, long len,
                            long max_frames, long max_args_total,
                            long *counts, long *offs, long *lens,
                            long *fams, long *consumed, long *err)
{
    long pos = 0, nframes = 0, nargs = 0;
    *err = 0;
    while (nframes < max_frames) {
        long p = pos;
        if (p >= len)
            break;
        if (buf[p] != '*') {
            *err = 2;
            break;
        }
        /* *N\r\n header */
        long q = p + 1, n = 0, digs = 0;
        while (q < len && buf[q] >= '0' && buf[q] <= '9') {
            n = n * 10 + (buf[q] - '0');
            q++;
            digs++;
            if (n > 1024 * 1024) { /* argv cap, matches Redis proto limit */
                *err = 1;
                goto out;
            }
        }
        if (q + 1 >= len)
            break; /* incomplete header */
        if (digs == 0 || buf[q] != '\r' || buf[q + 1] != '\n') {
            *err = 1;
            break;
        }
        q += 2;
        if (nargs + n > max_args_total) {
            /* Descriptor capacity: stop BEFORE this frame.  If it is the
             * FIRST frame, no progress is possible at any buffer size —
             * signal fallback so the caller's slow path (which has no
             * argc capacity) parses it instead of waiting forever. */
            if (nframes == 0)
                *err = 2;
            break;
        }
        long ok = 1;
        for (long i = 0; i < n; i++) {
            if (q >= len) {
                ok = 0;
                break;
            }
            if (buf[q] != '$') {
                *err = 1;
                goto out;
            }
            long r = q + 1, blen = 0, d2 = 0;
            while (r < len && buf[r] >= '0' && buf[r] <= '9') {
                blen = blen * 10 + (buf[r] - '0');
                r++;
                d2++;
                if (blen > 512L * 1024 * 1024) { /* proto-max-bulk-len */
                    *err = 1;
                    goto out;
                }
            }
            if (r + 1 >= len) {
                ok = 0;
                break;
            }
            if (d2 == 0 || buf[r] != '\r' || buf[r + 1] != '\n') {
                *err = 1;
                goto out;
            }
            r += 2;
            if (r + blen + 2 > len) {
                ok = 0;
                break;
            }
            if (buf[r + blen] != '\r' || buf[r + blen + 1] != '\n') {
                *err = 1;
                goto out;
            }
            offs[nargs + i] = r;
            lens[nargs + i] = blen;
            q = r + blen + 2;
        }
        if (!ok)
            break; /* incomplete frame: wait for more bytes */
        counts[nframes] = n;
        if (fams)
            fams[nframes] =
                (n > 0) ? rtpu_classify(buf + offs[nargs], lens[nargs]) : 0;
        nframes++;
        nargs += n;
        pos = q;
    }
out:
    *consumed = pos;
    return nframes;
}

long rtpu_resp_parse(const unsigned char *buf, long len,
                     long max_frames, long max_args_total,
                     long *counts, long *offs, long *lens,
                     long *consumed, long *err)
{
    return rtpu_parse_core(buf, len, max_frames, max_args_total, counts,
                           offs, lens, (long *)0, consumed, err);
}

/* One reactor tick for one readable connection: drain the fd into the
 * caller's buffer (read(2) loop — nonblocking socket), then parse every
 * complete frame AND classify each frame's command family, all in one
 * native call.  Python is left holding only dispatch decisions.
 *
 * In:  buf[0..have) holds leftover bytes from the previous tick; cap is
 *      the buffer capacity; budget caps bytes read this call.
 * Out: *nread    bytes appended by read(2) (buf now holds have+*nread);
 *      *eof      1 when the peer closed (read returned 0) or the socket
 *                errored fatally (anything but EAGAIN/EWOULDBLOCK/EINTR);
 *      *consumed bytes occupied by the returned frames (caller compacts);
 *      *err      as rtpu_resp_parse (0 clean / 1 protocol / 2 fallback).
 * Returns the number of complete frames described in counts/offs/lens,
 * with fams[i] holding each frame's family class.
 *
 * The read loop stops at EAGAIN, at the byte budget, or when the buffer
 * fills (the caller grows it when a single frame exceeds cap). */
long rtpu_resp_tick(long fd, unsigned char *buf, long cap, long have,
                    long budget, long max_frames, long max_args_total,
                    long *counts, long *offs, long *lens, long *fams,
                    long *consumed, long *nread, long *eof, long *err)
{
    long got = 0;
    *eof = 0;
    while (got < budget && have + got < cap) {
        long want = budget - got;
        if (want > cap - (have + got))
            want = cap - (have + got);
        long n = (long)read((int)fd, buf + have + got, (size_t)want);
        if (n > 0) {
            got += n;
            if (n < want)
                break; /* short read: socket drained for now */
            continue;
        }
        if (n == 0) {
            *eof = 1;
            break;
        }
        if (errno == EINTR)
            continue;
        if (errno != EAGAIN && errno != EWOULDBLOCK)
            *eof = 1; /* fatal socket error: treat as peer-gone */
        break;
    }
    *nread = got;
    return rtpu_parse_core(buf, have + got, max_frames, max_args_total,
                           counts, offs, lens, fams, consumed, err);
}

/* Serialize a batch of integer replies (`:n\r\n`) — the common reply shape
 * of SETBIT/SADD/HSET/... pipelines; one call per flush instead of one
 * Python string-build per reply. Returns bytes written, or -1 if the
 * output buffer is too small. */
long rtpu_resp_encode_ints(const long *vals, long n, unsigned char *out,
                           long cap)
{
    long w = 0;
    for (long i = 0; i < n; i++) {
        long v = vals[i];
        unsigned char tmp[24];
        long t = 0, neg = 0;
        if (w + 26 > cap)
            return -1;
        if (v < 0) {
            neg = 1;
            v = -v;
        }
        do {
            tmp[t++] = '0' + (unsigned char)(v % 10);
            v /= 10;
        } while (v);
        out[w++] = ':';
        if (neg)
            out[w++] = '-';
        while (t)
            out[w++] = tmp[--t];
        out[w++] = '\r';
        out[w++] = '\n';
    }
    return w;
}

/* Serialize a batch of bulk-string replies (`$len\r\n<bytes>\r\n`, or
 * `$-1\r\n` for nil when lens[i] < 0) — the common reply shape of fused
 * GET/MGET runs and container reads (HGETALL/LRANGE/SMEMBERS pipelines).
 * Values arrive concatenated in `payload` at (offs[i], lens[i]); one call
 * per reply batch instead of one Python string-build per value.  Returns
 * bytes written, or -1 if the output buffer is too small. */
long rtpu_resp_encode_bulks(const unsigned char *payload, const long *offs,
                            const long *lens, long n, unsigned char *out,
                            long cap)
{
    long w = 0;
    for (long i = 0; i < n; i++) {
        long L = lens[i];
        if (L < 0) {
            if (w + 5 > cap)
                return -1;
            memcpy(out + w, "$-1\r\n", 5);
            w += 5;
            continue;
        }
        /* "$" + <=20 digits + CRLF + payload + CRLF */
        if (w + L + 26 > cap)
            return -1;
        out[w++] = '$';
        unsigned char tmp[24];
        long t = 0, v = L;
        do {
            tmp[t++] = '0' + (unsigned char)(v % 10);
            v /= 10;
        } while (v);
        while (t)
            out[w++] = tmp[--t];
        out[w++] = '\r';
        out[w++] = '\n';
        memcpy(out + w, payload + offs[i], (size_t)L);
        w += L;
        out[w++] = '\r';
        out[w++] = '\n';
    }
    return w;
}

#ifdef __cplusplus
}
#endif
