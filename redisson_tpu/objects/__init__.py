"""RObject layer — parity with org/redisson/api/ interfaces + the flat
``Redisson*.java`` impls (SURVEY.md §1 L5).

Sketch objects (BloomFilter, HyperLogLog, BitSet, CountMinSketch) delegate
to a SketchEngine: the TPU engine (tenancy pools + TpuCommandExecutor) when
``Config.use_tpu_sketch()`` is on, else the host-golden engine (the
"Redis-backed" analog, also the honest benchmark baseline).
"""

from redisson_tpu.objects.bloom_filter import BloomFilter
from redisson_tpu.objects.bitset import BitSet
from redisson_tpu.objects.count_min_sketch import CountMinSketch
from redisson_tpu.objects.hyperloglog import HyperLogLog

__all__ = ["BloomFilter", "BitSet", "CountMinSketch", "HyperLogLog"]
