"""Object-layer base plumbing: RObject idiom + camelCase compatibility.

→ org/redisson/RedissonObject.java (name addressing, delete/rename/exists)
and org/redisson/api/RObject.java.  Java users call ``tryInit``/``addAll``;
we expose snake_case Python APIs and transparently alias camelCase so the
reference API shape survives verbatim.
"""

from __future__ import annotations

import re

import numpy as np

from redisson_tpu.codecs import encode_batch
from redisson_tpu.utils import hashing

_CAMEL_RE = re.compile(r"(?<!^)(?=[A-Z])")


def camel_to_snake(name: str) -> str:
    return _CAMEL_RE.sub("_", name).lower()


class CamelCompatMixin:
    """bloomFilter.tryInit(...) works exactly like bloom_filter.try_init."""

    def __getattr__(self, item):
        if not item.startswith("_"):
            snake = camel_to_snake(item)
            if snake != item:
                try:
                    # getattr (not object.__getattribute__) so snake-case
                    # names served by a subclass __getattr__ — e.g. the
                    # synthesized *_async forms — resolve for camelCase too
                    # (putAsync → put_async).  No recursion: snake != item.
                    return getattr(self, snake)
                except AttributeError:
                    pass
        raise AttributeError(
            f"{type(self).__name__!r} object has no attribute {item!r}"
        )


class MappedFuture:
    """Future adapter applying a transform on .result() — used by the
    deferred (batch-pipelined) forms of sync-named methods."""

    def __init__(self, fut, transform):
        self._fut = fut
        self._transform = transform

    def result(self, *a, **kw):
        return self._transform(self._fut.result(*a, **kw))

    get = result

    def done(self):
        return self._fut.done()


class CompletedFuture:
    """Already-resolved future (RFuture parity for host-grid ops)."""

    def __init__(self, value):
        self._value = value

    def result(self, *a, **kw):
        return self._value

    get = result

    @staticmethod
    def done():
        return True


class RObject(CamelCompatMixin):
    """Name-addressed object bound to a client engine.

    ``_DEFERRED`` maps sync-named methods to attributes returning a future
    whose resolved value matches the SYNC return contract — the batch
    facade routes queued sync calls through these so a natural batch
    pipelines instead of executing sequentially (SURVEY.md §3.4)."""

    KIND: str = ""
    _DEFERRED: dict = {}

    def __init__(self, name: str, client):
        self._name = name
        self._client = client
        self._engine = client._engine
        self._codec = client.config.codec

    def get_name(self) -> str:
        return self._name

    @property
    def name(self) -> str:
        return self._name

    def is_exists(self) -> bool:
        return self._engine.exists(self._name)

    def delete(self) -> bool:
        return self._engine.delete(self._name)

    def rename(self, new_name: str) -> None:
        if not self._engine.rename(self._name, new_name):
            # Failed rename (missing/expired source) must NOT repoint the
            # handle — it would silently start mutating whatever already
            # lives under new_name.
            raise RuntimeError(f"object {self._name!r} does not exist")
        self._name = new_name

    # -- expiry (→ org/redisson/RedissonExpirable.java) --------------------

    def expire(self, ttl_s: float) -> bool:
        """Schedule deletion ``ttl_s`` seconds from now (EXPIRE)."""
        return self._engine.expire(self._name, ttl_s)

    def expire_at(self, timestamp: float) -> bool:
        """Absolute-deadline expiry (EXPIREAT, unix seconds)."""
        return self._engine.expire_at(self._name, timestamp)

    def clear_expire(self) -> bool:
        """Remove a pending TTL (PERSIST)."""
        return self._engine.clear_expire(self._name)

    def remain_time_to_live(self) -> int:
        """Remaining TTL in ms; -1 no TTL, -2 absent (PTTL)."""
        return self._engine.remain_ttl_ms(self._name)

    # -- dump/restore (→ org/redisson/RedissonObject.java#dump) ------------

    def dump(self) -> bytes:
        """Opaque serialized state (DUMP); raises if absent."""
        data = self._engine.dump(self._name)
        if data is None:
            raise RuntimeError(f"object {self._name!r} does not exist")
        return data

    def restore(self, data: bytes, replace: bool = False) -> None:
        """Recreate this object from ``dump`` bytes (RESTORE)."""
        self._engine.restore(self._name, data, replace=replace)

    # -- hashing helpers shared by sketch objects --------------------------

    def _encode(self, objs) -> tuple[np.ndarray, np.ndarray]:
        if np.isscalar(objs) or isinstance(objs, (str, bytes)):
            objs = [objs]
        return encode_batch(self._codec, objs)

    def _hash_lanes(self, objs):
        blocks, lengths = self._encode(objs)
        return hashing.murmur3_x86_128(blocks, lengths)

    def _hash128(self, objs):
        blocks, lengths = self._encode(objs)
        return hashing.hash128_np(blocks, lengths)
