"""BitSet — parity with org/redisson/api/RBitSet.java /
org/redisson/RedissonBitSet.java (SURVEY.md §2.2).

Redis-bitmap semantics: auto-grow on set, SETBIT returns the previous bit,
BITCOUNT/BITPOS, cross-key BITOP AND/OR/XOR/NOT, bulk range set/clear.
Single-bit batches are vectorized; range ops are word-mask kernels.
"""

from __future__ import annotations

import numpy as np

from redisson_tpu.objects.base import RObject
from redisson_tpu.tenancy import PoolKind


class BitSet(RObject):
    KIND = PoolKind.BITSET

    # Batch pipelining (SURVEY.md §3.4).
    _DEFERRED = {
        "set_many": "set_many_async",
        "get_many": "get_many_async",
    }

    # -- single/batch bit ops ---------------------------------------------

    def get(self, index: int) -> bool:
        return bool(self._engine.bitset_get(self._name, [index]).result()[0])

    def get_many(self, indexes) -> np.ndarray:
        return self._engine.bitset_get(self._name, np.asarray(indexes)).result()

    def set(self, index, value: bool = True) -> bool:
        """→ RBitSet#set(index, value): returns previous bit value."""
        if np.ndim(index) == 0:
            return bool(
                self._engine.bitset_set(self._name, [int(index)], value).result()[0]
            )
        # Array argument: same contract as set_many — the PREVIOUS value
        # per index (the old branch fetched them and returned a constant
        # True).
        return self.set_many(np.asarray(index), value)

    def set_many(self, indexes, value: bool = True) -> np.ndarray:
        """Vectorized SETBIT: previous value per index."""
        return self._engine.bitset_set(self._name, np.asarray(indexes), value).result()

    # RFuture-idiom async variants (→ RBitSetAsync#setAsync/getAsync).

    def get_many_async(self, indexes):
        return self._engine.bitset_get(self._name, np.asarray(indexes))

    def set_many_async(self, indexes, value: bool = True):
        return self._engine.bitset_set(self._name, np.asarray(indexes), value)

    def clear_bit(self, index: int) -> bool:
        """→ RBitSet#clear(index)."""
        return bool(
            self._engine.bitset_set(self._name, [int(index)], False).result()[0]
        )

    def flip(self, index: int) -> bool:
        """→ RBitSet#flip: returns the NEW bit value (java semantics)."""
        prev = self._engine.bitset_flip(self._name, [int(index)]).result()[0]
        return not bool(prev)

    # -- ranges ------------------------------------------------------------

    def set_range(self, from_index: int, to_index: int) -> None:
        """→ RBitSet#set(from, to) — [from, to) like the reference."""
        self._engine.bitset_set_range(self._name, from_index, to_index, True).result()

    def clear_range(self, from_index: int, to_index: int) -> None:
        self._engine.bitset_set_range(self._name, from_index, to_index, False).result()

    def clear(self, from_index=None, to_index=None) -> None:
        """→ RBitSet#clear() / clear(from, to)."""
        if from_index is None:
            self._engine.delete(self._name)
        else:
            self.clear_range(from_index, to_index)

    # -- queries -----------------------------------------------------------

    def cardinality(self) -> int:
        return self._engine.bitset_cardinality(self._name)

    def length(self) -> int:
        """Highest set bit + 1 (→ RBitSet#length)."""
        return self._engine.bitset_length(self._name)

    def size(self) -> int:
        """Allocated capacity in bits (→ RBitSet#size: bytes*8 in Redis)."""
        return self._engine.bitset_capacity_bits(self._name)

    def is_empty(self) -> bool:
        return self.cardinality() == 0

    def first_set_bit(self) -> int:
        return self._engine.bitset_bitpos(self._name, 1)

    def first_clear_bit(self) -> int:
        return self._engine.bitset_bitpos(self._name, 0)

    # -- cross-key ops -----------------------------------------------------

    def and_op(self, *names: str) -> None:
        """→ RBitSet#and(String...): this &= and(others)."""
        self._engine.bitset_bitop(self._name, (self._name, *names), "and")

    def or_op(self, *names: str) -> None:
        self._engine.bitset_bitop(self._name, (self._name, *names), "or")

    def xor_op(self, *names: str) -> None:
        self._engine.bitset_bitop(self._name, (self._name, *names), "xor")

    def not_op(self) -> None:
        """→ RBitSet#not(): in-place complement over allocated size."""
        self._engine.bitset_bitop(self._name, (self._name,), "not")

    def to_byte_array(self) -> bytes:
        return self._engine.bitset_to_bytes(self._name)

    def as_bit_array(self) -> np.ndarray:
        """Bool array view (asBitSet analog)."""
        raw = np.frombuffer(self.to_byte_array(), dtype=np.uint8)
        return np.unpackbits(raw, bitorder="little").astype(bool)
