"""BloomFilter — parity with org/redisson/api/RBloomFilter.java /
org/redisson/RedissonBloomFilter.java (SURVEY.md §2.2).

Same public shape (tryInit/add/contains/count/getSize/...), same (m, k)
formulas, same Kirsch–Mitzenmacher index math — but add/contains ship one
vectorized device batch instead of k SETBIT/GETBIT commands per key.
camelCase aliases work via CamelCompatMixin (``bf.tryInit(...)``).
"""

from __future__ import annotations

import numpy as np

from redisson_tpu.objects.base import MappedFuture, RObject
from redisson_tpu.tenancy import PoolKind


class BloomFilter(RObject):
    KIND = PoolKind.BLOOM

    # Batch pipelining: sync-named calls ride these async forms inside
    # Batch.execute (resolved values match the sync contracts).
    _DEFERRED = {
        "add": "add_deferred",
        "add_all": "add_all_deferred",
        "contains": "contains_deferred",
        "contains_all": "contains_all_deferred",
        "contains_each": "contains_all_async",
    }

    def add_deferred(self, obj):
        return MappedFuture(self.add_all_async([obj]), lambda v: bool(v[0]))

    def add_all_deferred(self, objs):
        return MappedFuture(self.add_all_async(objs), lambda v: int(np.sum(v)))

    def contains_deferred(self, obj):
        return MappedFuture(self.contains_all_async([obj]), lambda v: bool(v[0]))

    def contains_all_deferred(self, objs):
        return MappedFuture(
            self.contains_all_async(objs), lambda v: int(np.sum(v))
        )

    # -- lifecycle ---------------------------------------------------------

    def try_init(self, expected_insertions: int, false_probability: float) -> bool:
        """→ RBloomFilter#tryInit: returns False if already initialized."""
        return self._engine.bloom_try_init(
            self._name, expected_insertions, false_probability
        )

    def _params(self) -> dict:
        p = self._engine.params(self._name)
        if p is None:
            raise RuntimeError(f"bloom filter {self._name!r} is not initialized")
        return p

    def get_size(self) -> int:
        """→ RBloomFilter#getSize (bit count m)."""
        return self._params()["size"]

    def get_hash_iterations(self) -> int:
        return self._params()["hash_iterations"]

    def get_expected_insertions(self) -> int:
        return self._params()["expected_insertions"]

    def get_false_probability(self) -> float:
        return self._params()["false_probability"]

    # -- data path ---------------------------------------------------------

    def add(self, obj) -> bool:
        """→ RBloomFilter#add(T): True iff at least one bit was newly set.
        ``obj`` is ONE key (wrapped explicitly — a tuple/list argument is
        a legal single key under pickle-style codecs; the batch forms
        would have hashed its ELEMENTS as separate keys)."""
        return bool(self.add_all_async([obj]).result()[0])

    def add_all(self, objs) -> int:
        """→ RBloomFilter#add(Collection): number of newly-added elements."""
        return int(np.sum(self.add_all_async(objs).result()))

    def add_all_async(self, objs):
        return self._engine.bloom_add_encoded(self._name, *self._encode(objs))

    add_async = add_all_async

    def contains(self, obj) -> bool:
        """One key, explicitly wrapped (see add)."""
        return bool(self.contains_all_async([obj]).result()[0])

    def contains_all(self, objs) -> int:
        """→ RBloomFilter#contains(Collection): how many are (probably)
        present."""
        return int(np.sum(self.contains_each(objs)))

    def contains_each(self, objs) -> np.ndarray:
        """Vectorized membership: bool per input (TPU-native extension used
        by the benchmark harness)."""
        return self.contains_all_async(objs).result()

    def contains_all_async(self, objs):
        return self._engine.bloom_contains_encoded(self._name, *self._encode(objs))

    contains_async = contains_all_async

    def mixed_async(self, objs, flags):
        """Ordered add/contains mix in ONE engine call (the front-door
        fused-run entry, ISSUE 6): ``flags[i]`` True adds ``objs[i]``
        (result: newly added), False tests membership.  Intra-batch
        sequencing matches issuing the ops one at a time."""
        return self._engine.bloom_mixed_encoded(
            self._name, *self._encode(objs), flags
        )

    def contains_many(self, batches) -> list:
        """Pipelined bulk membership: dispatch EVERY batch, then collect
        all results in one reply flush — the RBatch idiom (a Redisson
        batch of containsAsync calls executes as one pipeline with one
        reply read, → org/redisson/command/CommandBatchService.java,
        SURVEY.md §3.4).  On the TPU engine the flush is the device-side
        result mailbox: G packed result arrays concatenate on device and
        come home in ONE D2H (each host fetch costs a full link round
        trip).  Returns one bool array per input batch.

        Same-dtype integer ndarray batches additionally coalesce into a
        SINGLE launch (host concat → one H2D → one scan-chunked kernel →
        one fetch): membership is read-only, so splitting the result
        back per batch is exact, and the whole group costs three link
        transfers however many batches ride it.  The single-launch form
        requires a route to the scan-chunked ``*_keys_st`` kernels (or
        the host engine) — the coalesced/replicated ``bloom_mixed_keys``
        path has no scan chunking, and a multi-million-op un-chunked
        device-hash launch fails compile on HBM — so those engines keep
        the per-batch pipelined form."""
        from redisson_tpu.executor.tpu_executor import defer_host_fetch

        batches = list(batches)
        eng = self._engine
        executor = getattr(eng, "executor", None)
        single_launch_ok = (
            getattr(eng, "coalescer", None) is None
            and not self.is_replicated()
            and (
                executor is None  # host engine: one vectorized call
                or getattr(executor, "supports_device_hash", False)
            )
        )
        if (
            single_launch_ok
            and len(batches) > 1
            and all(
                isinstance(b, np.ndarray)
                and b.ndim == 1
                and b.dtype.kind in "iu"
                for b in batches
            )
            and len({b.dtype for b in batches}) == 1
        ):
            flat = self.contains_all_async(
                np.concatenate(batches)
            ).result()
            out = []
            off = 0
            for b in batches:
                # .copy(): a view would pin the whole flat result for as
                # long as any ONE batch's slice is retained.
                out.append(flat[off : off + len(b)].copy())
                off += len(b)
            return out
        with defer_host_fetch():  # no per-launch D2H: ONE grouped fetch
            futs = [self.contains_all_async(b) for b in batches]
        return self._client.collect(futs)

    # -- read replication (SURVEY §2.4 replication row) ---------------------

    def set_replicated(self) -> bool:
        """Copy this filter's row to EVERY mesh shard: reads spread
        round-robin across copies (contains() is read-heavy — the
        ReadMode.SLAVE analog), writes broadcast to all.  False on a
        single-device executor (nothing to spread across)."""
        return self._engine.bloom_replicate(self._name)

    def is_replicated(self) -> bool:
        return self._engine.bloom_is_replicated(self._name)

    def count(self) -> int:
        """→ RBloomFilter#count: estimated number of inserted elements."""
        return int(self._engine.bloom_count(self._name).result())
