"""CountMinSketch — the NEW RObject (no reference counterpart;
BASELINE.json requires it with the RObject idiom: tryInit/add/estimate/topK,
name-addressed, codec-encoded keys — SURVEY.md §2.2).

Geometry: depth d × width w counters per tenant; point estimates are the
classic min-over-rows upper bound.  Heavy-hitter tracking (benchmark
config 5) is ENGINE-shared and name-addressed (engines.TopKStore): every
handle to one sketch sees one candidate table; each add batch offers its
heaviest candidates (argpartition over the post-update estimate stream
that rides back with the batch), and ``top_k()`` re-estimates candidates
on device so the ranking reflects current counts exactly.
"""

from __future__ import annotations

import math

import numpy as np

from redisson_tpu.objects.base import RObject
from redisson_tpu.tenancy import PoolKind


class CountMinSketch(RObject):
    KIND = PoolKind.CMS

    # Batch pipelining (SURVEY.md §3.4).
    _DEFERRED = {
        "add": "add_deferred",
        "add_all": "add_all_async",
        "estimate": "estimate_deferred",
        "estimate_all": "estimate_all_async",
    }

    def add_deferred(self, obj, count: int = 1):
        from redisson_tpu.objects.base import MappedFuture

        return MappedFuture(
            self.add_all_async([obj], [count]), lambda v: int(v[0])
        )

    def estimate_deferred(self, obj):
        from redisson_tpu.objects.base import MappedFuture

        return MappedFuture(self.estimate_all_async([obj]), lambda v: int(v[0]))

    def estimate_all_async(self, objs):
        H1, H2 = self._hash128(objs)
        return self._engine.cms_estimate(self._name, H1, H2)

    # -- lifecycle ---------------------------------------------------------

    def try_init(self, depth: int, width: int, track_top_k: int = 0) -> bool:
        """Create with explicit geometry.  ``track_top_k``: keep a live
        top-K candidate table updated on every add (shared across every
        handle to this name)."""
        created = self._engine.cms_try_init(self._name, int(depth), int(width))
        if track_top_k and created:
            # Only the CREATING init arms tracking: tryInit on an existing
            # object must change nothing regardless of params (a failed
            # init silently enabling tracking taxed every handle's adds).
            self._engine.topk.configure(self._name, int(track_top_k))
        return created

    def try_init_by_error(
        self, epsilon: float, confidence: float, track_top_k: int = 0
    ) -> bool:
        """Standard CMS sizing: w = ceil(e/eps), d = ceil(ln(1/(1-conf)))."""
        w = math.ceil(math.e / epsilon)
        d = max(1, math.ceil(math.log(1.0 / (1.0 - confidence))))
        return self.try_init(d, w, track_top_k)

    def _params(self) -> dict:
        p = self._engine.params(self._name)
        if p is None:
            raise RuntimeError(f"count-min sketch {self._name!r} is not initialized")
        return p

    def get_depth(self) -> int:
        return self._params()["depth"]

    def total_count(self) -> int:
        """Total inserted weight (the RedisBloom CMS.INFO 'count' field):
        row-0 cell sum — every increment lands once per depth row."""
        self._params()
        return self._engine.cms_total(self._name)

    def get_width(self) -> int:
        return self._params()["width"]

    # -- data path ---------------------------------------------------------

    def add(self, obj, count: int = 1) -> int:
        """Add and return the post-update estimate for obj."""
        return int(self.add_all([obj], [count])[0])

    def add_all(self, objs, counts=None) -> np.ndarray:
        return self.add_all_async(objs, counts).result()

    def add_all_async(self, objs, counts=None):
        # Materialize FIRST: a generator would be exhausted by the hash
        # pass, leaving _make_offer an empty key list (counters updated,
        # top-K candidates silently never recorded).
        if not isinstance(objs, np.ndarray):
            objs = list(objs)
        H1, H2 = self._hash128(objs)
        if counts is None:
            counts = np.ones(len(H1), np.uint32)
        fut = self._engine.cms_add(
            self._name, H1, H2, np.asarray(counts, np.uint32)
        )
        k = self._engine.topk.track(self._name)
        if not k:
            return fut
        return _OfferOnResult(fut, self._make_offer(objs, k))

    def _make_offer(self, objs, k: int):
        """Top-K candidate feed shared by add_all_async and add_all_seq:
        the batch's heaviest UNIQUE keys (≤4k) go to the engine table."""
        name, engine = self._name, self._engine
        objs_ref = list(objs) if not isinstance(objs, np.ndarray) else objs

        def offer(est):
            # Select the batch's heaviest UNIQUE keys (a heavy key appears
            # many times per batch; taking top ops would offer only its
            # duplicates), then push ≤4k candidates to the shared table.
            est = np.asarray(est)
            n_offer = min(4 * max(k, 16), est.shape[0])
            if isinstance(objs_ref, np.ndarray):
                uniq, inv = np.unique(objs_ref, return_inverse=True)
                per_key = np.zeros(len(uniq), est.dtype)
                np.maximum.at(per_key, inv, est)
                keys_list, ests_arr = uniq, per_key
            else:
                best: dict = {}
                for o, e in zip(objs_ref, est):
                    e = int(e)
                    if best.get(o, -1) < e:
                        best[o] = e
                keys_list = list(best)
                ests_arr = np.fromiter(best.values(), dtype=np.int64)
            if n_offer < len(keys_list):
                top = np.argpartition(ests_arr, -n_offer)[-n_offer:]
            else:
                top = np.arange(len(keys_list))
            # Keep keys as their ORIGINAL scalar types (.tolist() would
            # turn np.uint64 into int, which codecs encode differently —
            # re-estimation would then miss every candidate).
            keys = [keys_list[i] for i in top]
            engine.topk.offer(name, keys, ests_arr[top])
            return est

        return offer

    def add_all_seq(self, objs, counts=None) -> np.ndarray:
        """Streaming variant of add_all (the Pallas heavy-hitter kernel,
        BASELINE config 5): each op's returned estimate is its
        AT-SEQUENCE-POINT value — its own update applied, LATER ops in
        the batch excluded (five adds of one key return 1,2,3,4,5).
        add_all's vectorized path instead returns post-whole-batch
        estimates (5,5,5,5,5); the final table is identical either way."""
        if not isinstance(objs, np.ndarray):
            objs = list(objs)  # generators: see add_all_async
        H1, H2 = self._hash128(objs)
        if counts is None:
            counts = np.ones(len(H1), np.uint32)
        fut = self._engine.cms_add_seq(
            self._name, H1, H2, np.asarray(counts, np.uint32)
        )
        res = np.asarray(fut.result())
        k = self._engine.topk.track(self._name)
        if k:
            # Sequential estimates are per-op lower than batch-final; the
            # shared table max-merges, so offering them is still sound —
            # same unique-key/cap selection as add_all_async.
            self._make_offer(objs, k)(res)
        return res

    def estimate(self, obj) -> int:
        # [obj], never np.atleast_1d: coercing a python int to np.int64
        # changes its codec encoding, silently estimating a different key.
        return int(self.estimate_all([obj])[0])

    def estimate_all(self, objs) -> np.ndarray:
        return self.estimate_all_async(objs).result()

    def merge(self, *other_names: str) -> None:
        self._engine.cms_merge(self._name, other_names)

    # -- top-K tracking (engine-shared, see module docstring) --------------

    def top_k(self, k: int | None = None):
        """[(key, estimated_count)] heaviest-first.  Candidates come from
        the engine-shared table; their counts are RE-ESTIMATED on device
        at call time, so the ranking reflects all adds from every handle."""
        k = k or self._engine.topk.track(self._name) or 10
        cands = self._engine.topk.candidates(self._name)
        if not cands:
            return []
        ests = self.estimate_all(cands)
        # int64 BEFORE negation: -uint32 wraps, ranking zero-count stale
        # candidates as the heaviest hitters.
        order = np.argsort(-ests.astype(np.int64), kind="stable")[:k]
        return [(cands[i], int(ests[i])) for i in order]


class _OfferOnResult:
    """Future adapter: feeds the engine's top-K table exactly once when the
    batch's estimates materialize."""

    def __init__(self, fut, offer):
        self._fut = fut
        self._offer = offer
        self._done_val = None
        self._offered = False

    def result(self, *a, **kw):
        v = self._fut.result(*a, **kw)
        if not self._offered:
            self._offered = True
            self._done_val = self._offer(v)
        return self._done_val if self._done_val is not None else v

    def get(self):
        return self.result()

    def done(self):
        return self._fut.done()
