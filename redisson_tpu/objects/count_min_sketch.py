"""CountMinSketch — the NEW RObject (no reference counterpart;
BASELINE.json requires it with the RObject idiom: tryInit/add/estimate/topK,
name-addressed, codec-encoded keys — SURVEY.md §2.2).

Geometry: depth d × width w counters per tenant; point estimates are the
classic min-over-rows upper bound.  A host-side top-K tracker consumes the
post-update estimates that ride back with each add batch (the streaming
heavy-hitter path of benchmark config 5).
"""

from __future__ import annotations

import heapq
import math

import numpy as np

from redisson_tpu.objects.base import RObject
from redisson_tpu.tenancy import PoolKind


class CountMinSketch(RObject):
    KIND = PoolKind.CMS

    def __init__(self, name, client):
        super().__init__(name, client)
        self._topk: dict = {}
        self._track = 0

    # -- lifecycle ---------------------------------------------------------

    def try_init(self, depth: int, width: int, track_top_k: int = 0) -> bool:
        """Create with explicit geometry.  ``track_top_k``: keep a live
        top-K candidate table updated on every add."""
        created = self._engine.cms_try_init(self._name, int(depth), int(width))
        if created or track_top_k:
            # A no-op tryInit (already initialized, no explicit request)
            # must not silently disable this instance's tracker.
            self._track = int(track_top_k)
        return created

    def try_init_by_error(
        self, epsilon: float, confidence: float, track_top_k: int = 0
    ) -> bool:
        """Standard CMS sizing: w = ceil(e/eps), d = ceil(ln(1/(1-conf)))."""
        w = math.ceil(math.e / epsilon)
        d = max(1, math.ceil(math.log(1.0 / (1.0 - confidence))))
        return self.try_init(d, w, track_top_k)

    def _params(self) -> dict:
        p = self._engine.params(self._name)
        if p is None:
            raise RuntimeError(f"count-min sketch {self._name!r} is not initialized")
        return p

    def get_depth(self) -> int:
        return self._params()["depth"]

    def get_width(self) -> int:
        return self._params()["width"]

    # -- data path ---------------------------------------------------------

    def add(self, obj, count: int = 1) -> int:
        """Add and return the post-update estimate for obj."""
        return int(self.add_all([obj], [count])[0])

    def add_all(self, objs, counts=None) -> np.ndarray:
        res = self.add_all_async(objs, counts).result()
        if self._track:
            self._update_topk(objs, res)
        return res

    def add_all_async(self, objs, counts=None):
        H1, H2 = self._hash128(objs)
        if counts is None:
            counts = np.ones(len(H1), np.uint32)
        return self._engine.cms_add(self._name, H1, H2, np.asarray(counts, np.uint32))

    def estimate(self, obj) -> int:
        return int(self.estimate_all(np.atleast_1d(obj) if not isinstance(obj, (str, bytes)) else [obj])[0])

    def estimate_all(self, objs) -> np.ndarray:
        H1, H2 = self._hash128(objs)
        return self._engine.cms_estimate(self._name, H1, H2).result()

    def merge(self, *other_names: str) -> None:
        self._engine.cms_merge(self._name, other_names)

    # -- top-K tracking ----------------------------------------------------

    def _update_topk(self, objs, estimates) -> None:
        if isinstance(objs, np.ndarray):
            objs = objs.tolist()
        for o, e in zip(objs, estimates):
            self._topk[o] = int(e)
        if len(self._topk) > 4 * max(self._track, 16):
            keep = heapq.nlargest(
                2 * self._track, self._topk.items(), key=lambda kv: kv[1]
            )
            self._topk = dict(keep)

    def top_k(self, k: int | None = None):
        """[(key, estimated_count)] heaviest-first among tracked candidates."""
        k = k or self._track
        return heapq.nlargest(k, self._topk.items(), key=lambda kv: kv[1])
