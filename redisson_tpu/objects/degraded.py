"""Degraded-mode host mirrors — graceful degradation for ISSUE 3.

When a circuit breaker opens for a sketch kind (executor/health.py), the
engine stops dispatching that kind to the device and serves reads AND
writes from a host-side golden-model mirror of each affected object
(ops/golden.py — the same models every kernel is property-tested
against).  The mirror is seeded from the object's device row at failover
time, accumulates the degraded-window ops with exact golden semantics,
and encodes back to the device row layout when the breaker closes
(reconcile-on-close) — so the device resumes from precisely the state
the mirror served.

Layout codecs (device row <-> golden model):

- bloom / bitset — ``uint32`` bitmap words; bit *i* lives at word
  ``i >> 5``, bit ``i & 31`` (little-endian within the word), so
  ``np.unpackbits(row.view(uint8), bitorder="little")`` is the exact
  inverse of the device packing.
- hll — rows ARE the register array (``uint8[16384]``), no transform.
- cms — rows are the row-major ``uint32[d*w]`` counter table.

Thread-safety: the engine serializes mirror application and reconcile
under one mirror lock; models here assume external synchronization.
"""

from __future__ import annotations

import numpy as np

from redisson_tpu.ops import golden


def _bits_from_words(row: np.ndarray, nbits: int) -> np.ndarray:
    """Decode a device bitmap row (uint32 words) to bool[nbits]."""
    words = np.ascontiguousarray(np.asarray(row, np.uint32))
    if words.dtype.byteorder == ">":  # pragma: no cover — BE platform
        words = words.byteswap().view(words.dtype.newbyteorder("<"))
    bits = np.unpackbits(words.view(np.uint8), bitorder="little")
    return bits[:nbits].astype(bool)


def _words_from_bits(bits: np.ndarray, row_units: int) -> np.ndarray:
    """Encode bool bits back to a uint32[row_units] device row."""
    packed = np.packbits(np.asarray(bits, bool), bitorder="little")
    out = np.zeros(row_units * 4, np.uint8)
    out[: packed.shape[0]] = packed[: row_units * 4]
    return out.view("<u4").astype(np.uint32, copy=False)


class BloomMirror:
    kind = "bloom"

    def __init__(self, row: np.ndarray, row_units: int, m: int, k: int):
        self.row_units = int(row_units)
        self.m = int(m)
        self.k = int(k)
        self.model = golden.GoldenBloomFilter(m, k)
        self.model.bits = _bits_from_words(row, m)
        self.ops = 0

    def mixed(self, h1m, h2m, is_add) -> np.ndarray:
        """Sequential add/contains batch — exact arrival-order semantics,
        matching the device's bloom_mixed contract."""
        h1m = np.asarray(h1m, np.uint32)
        h2m = np.asarray(h2m, np.uint32)
        is_add = np.asarray(is_add, bool)
        out = np.zeros(len(h1m), bool)
        for j in range(len(h1m)):
            a, b = h1m[j : j + 1], h2m[j : j + 1]
            if is_add[j]:
                out[j] = bool(self.model.add_hashed(a, b)[0])
            else:
                out[j] = bool(self.model.contains_hashed(a, b)[0])
        self.ops += len(h1m)
        return out

    def count(self) -> int:
        return self.model.cardinality_estimate()

    def encode(self, row_units=None) -> np.ndarray:
        return _words_from_bits(self.model.bits, row_units or self.row_units)


class BitsetMirror:
    """Wraps :class:`golden.GoldenBitSet` — one bitset reference
    implementation, shared with the property tests, not a second copy
    to keep bit-identical.  The model grows on demand (the live entry
    can migrate to a larger size class while degraded — bitset_ensure
    is not breaker-gated); encode() sizes to the CURRENT pool at
    reconcile."""

    kind = "bitset"

    def __init__(self, row: np.ndarray, row_units: int):
        self.row_units = int(row_units)
        self.model = golden.GoldenBitSet(0)
        self.model.bits = _bits_from_words(row, row_units * 32)
        self.ops = 0

    @property
    def bits(self) -> np.ndarray:
        return self.model.bits

    def mixed(self, idx, opcodes) -> np.ndarray:
        """Unified set/clear/flip/get with previous-bit results and exact
        sequential duplicate semantics (the bitset_mixed contract),
        built on the model's sequential set/get."""
        from redisson_tpu.ops import bitset as bitset_ops

        idx = np.asarray(idx, np.int64)
        ops = np.asarray(opcodes, np.uint32)
        prev = np.zeros(len(idx), bool)
        for j in range(len(idx)):
            i = idx[j : j + 1]
            op = int(ops[j])
            if op == bitset_ops.OP_SET:
                prev[j] = bool(self.model.set(i, True)[0])
            elif op == bitset_ops.OP_CLEAR:
                prev[j] = bool(self.model.set(i, False)[0])
            elif op == bitset_ops.OP_FLIP:
                cur = bool(self.model.get(i)[0])
                self.model.set(i, not cur)
                prev[j] = cur
            else:  # read (OP_GET)
                prev[j] = bool(self.model.get(i)[0])
        self.ops += len(idx)
        return prev

    def set_range(self, from_bit: int, to_bit: int, value: bool) -> None:
        """SETRANGE analog — [from_bit, to_bit) assignment (the
        bitset_set_range contract on both engines)."""
        self.model._grow(int(to_bit))
        self.model.bits[int(from_bit):int(to_bit)] = bool(value)
        self.ops += 1

    def replace_bits(self, bits: np.ndarray) -> None:
        """Wholesale replacement — BITOP dest semantics (prior value
        never leaks into the result)."""
        self.model.bits = np.array(bits, dtype=bool)
        self.ops += 1

    def bitpos(self, target_bit: int) -> int:
        matches = np.nonzero(self.bits == bool(target_bit))[0]
        if matches.size:
            return int(matches[0])
        return -1 if target_bit else self.bits.size

    def cardinality(self) -> int:
        return self.model.cardinality()

    def length(self) -> int:
        return self.model.length()

    def encode(self, row_units=None) -> np.ndarray:
        # Reconcile targets the entry's CURRENT pool (a degraded-window
        # grow may have migrated it to a larger size class).
        return _words_from_bits(self.bits, row_units or self.row_units)


class HllMirror:
    kind = "hll"

    def __init__(self, row: np.ndarray, row_units: int):
        self.row_units = int(row_units)
        self.regs = np.asarray(row, np.uint8).copy()
        self.ops = 0

    def add_changed(self, c0, c1, c2) -> np.ndarray:
        idx, rank = golden.hll_index_rank(
            np.asarray(c0, np.uint32),
            np.asarray(c1, np.uint32),
            np.asarray(c2, np.uint32),
        )
        changed = np.zeros(len(idx), bool)
        for j in range(len(idx)):  # sequential: exact per-op changed flags
            i = int(idx[j])
            if rank[j] > self.regs[i]:
                self.regs[i] = rank[j]
                changed[j] = True
        self.ops += len(idx)
        return changed

    def merge_rows(self, rows) -> None:
        """PFMERGE into this mirror: max of registers per source row
        (device rows ARE the register array, so sources may be device
        reads or other mirrors' encode() output)."""
        for r in rows:
            regs = np.asarray(r, np.uint8)[: self.regs.shape[0]]
            np.maximum(self.regs, regs, out=self.regs)
        self.ops += 1

    def count(self) -> int:
        hist = np.bincount(self.regs, minlength=golden.HLL_Q + 2)
        return int(round(golden.ertl_estimate(hist)))

    def encode(self, row_units=None) -> np.ndarray:
        return self.regs.copy()


class CmsMirror:
    kind = "cms"

    def __init__(self, row: np.ndarray, row_units: int, d: int, w: int):
        self.row_units = int(row_units)
        self.model = golden.GoldenCountMinSketch(d, w)
        self.model.counts = (
            np.asarray(row, np.uint32)[: d * w].reshape(d, w).copy()
        )
        self.ops = 0

    def update_estimate(self, h1w, h2w, weights) -> np.ndarray:
        """Apply-then-estimate over the whole batch — the vectorized
        cms_update_and_estimate contract (estimates observe the batch)."""
        h1w = np.asarray(h1w, np.uint32)
        h2w = np.asarray(h2w, np.uint32)
        weights = np.asarray(weights, np.uint32)
        if np.any(weights):
            upd = weights != 0
            self.model.add_hashed(h1w[upd], h2w[upd], weights[upd])
        self.ops += len(h1w)
        return self.model.estimate_hashed(h1w, h2w).astype(np.uint32)

    def merge_rows(self, rows) -> None:
        """CMS.MERGE into this mirror: counters SUM per source row
        (row-major uint32[d*w] tables, same geometry — the engine
        enforces the geometry check before calling)."""
        d, w = self.model.counts.shape
        for r in rows:
            self.model.counts += (
                np.asarray(r, np.uint32)[: d * w].reshape(d, w)
            )
        self.ops += 1

    def total(self) -> int:
        return int(self.model.counts[0].astype(np.uint64).sum())

    def reset(self) -> None:
        self.model.counts[:] = 0

    def encode(self, row_units=None) -> np.ndarray:
        out = np.zeros(row_units or self.row_units, np.uint32)
        flat = self.model.counts.reshape(-1)
        out[: flat.shape[0]] = flat
        return out


def mirror_for_entry(entry, row: np.ndarray):
    """Build the kind-appropriate mirror from an entry + its device row."""
    from redisson_tpu.tenancy import PoolKind

    u = entry.pool.row_units
    if entry.kind == PoolKind.BLOOM:
        return BloomMirror(
            row, u, entry.params["size"], entry.params["hash_iterations"]
        )
    if entry.kind == PoolKind.BITSET:
        return BitsetMirror(row, u)
    if entry.kind == PoolKind.HLL:
        return HllMirror(row, u)
    if entry.kind == PoolKind.CMS:
        return CmsMirror(
            row, u, entry.params["depth"], entry.params["width"]
        )
    raise ValueError(f"no degraded mirror for kind {entry.kind!r}")
