"""Sketch-state durability: TTL, DUMP/RESTORE, and snapshots.

Role parity (SURVEY.md §5 checkpoint row):
- ``expire``/``remain_ttl_ms`` — org/redisson/RedissonExpirable.java: a
  named sketch can carry an absolute expiry deadline; expired objects
  vanish from the keyspace (lazy check on lookup + a background sweeper,
  the same two-tier discipline Redis applies to expired keys).
- ``dump``/``restore`` — org/redisson/RedissonObject.java#dump/restore:
  one object's device row + params serialized to opaque bytes.
- ``snapshot``/``restore_snapshot`` — the client-side answer to Redis
  RDB persistence: device pools D2H'd to an .npz + registry metadata
  JSON; ``Config.snapshot_dir``/``snapshot_interval_s`` arm periodic
  snapshots and restore-on-create (the keys were accepted-and-ignored in
  rounds 1-2 — now live).

Mixed into TpuSketchEngine (objects/engines.py).
"""

from __future__ import annotations

import io
import json
import os
import struct
import threading
import time
from typing import Optional

import numpy as np

from redisson_tpu import chaos as _chaos
from redisson_tpu.analysis import witness as _witness

_DUMP_VERSION = 2
_DUMP_MAGIC = b"RTPU"
_SNAP_META = "sketch_meta.json"
_SNAP_POOLS = "sketch_pools.npz"


def _crc_stream(f, chunk: int = 1 << 22) -> int:
    """CRC32 of an open binary file in bounded chunks — a multi-GB pool
    blob must not be read resident just to checksum it."""
    import zlib

    crc = 0
    while True:
        buf = f.read(chunk)
        if not buf:
            return crc
        crc = zlib.crc32(buf, crc)


def safe_load_npy(buf: io.BytesIO) -> np.ndarray:
    """np.load for UNTRUSTED dump payloads: a forged .npy header can
    declare an arbitrarily large shape and make np.load allocate
    terabytes before reading a byte — validate the declared size against
    the bytes actually present BEFORE allocating."""
    version = np.lib.format.read_magic(buf)
    if version == (1, 0):
        shape, fortran, dtype = np.lib.format.read_array_header_1_0(buf)
    elif version == (2, 0):
        shape, fortran, dtype = np.lib.format.read_array_header_2_0(buf)
    else:
        raise ValueError(f"unsupported npy version {version}")
    if dtype.hasobject:
        raise ValueError("object arrays are not allowed in dumps")
    count = int(np.prod(shape, dtype=np.int64)) if shape else 1
    nbytes = count * dtype.itemsize
    remaining = len(buf.getbuffer()) - buf.tell()
    if nbytes > remaining:
        raise ValueError(
            f"npy payload declares {nbytes} bytes but only {remaining} follow"
        )
    data = buf.read(nbytes)
    arr = np.frombuffer(data, dtype=dtype, count=count)
    if fortran:  # pragma: no cover — np.save emits C-order for C arrays
        return arr.reshape(shape, order="F")
    return arr.reshape(shape)


class SketchDurabilityMixin:
    """Requires: self.registry, self.executor, self._drain(), self.delete(),
    and the degraded-mirror surface (self._mirrors, self._mirror_lock,
    self._host_row — engines.py): persistence taken while a breaker is
    open must capture mirror-acked writes, not the stale device rows.
    """

    @staticmethod
    def _entry_rows(entry) -> list:
        """Every device row an entry owns (primary + read replicas) — the
        ONE place this enumeration lives (delete/expiry/rename/restore
        all free through it).  A HOST/DISK-resident entry (ISSUE 14,
        row < 0) owns none."""
        if entry.row is None or entry.row < 0:
            return []
        return list(entry.replica_rows) if entry.replica_rows else [entry.row]

    def _reap_rows(self, pool, rows, epoch: int) -> None:
        """Zero-then-free detached rows, guarded by the pool's topology
        epoch: a live change_topology that ran between the caller's
        detach and this call already freed the rows in its wholesale
        free-list rebuild (the entry was detached, so its rows weren't in
        ``used``) — zeroing/freeing again would wipe or double-free a row
        possibly reallocated since.  Atomic with the swap via the
        dispatch lock (the swap bumps the epoch while holding it)."""
        with pool._dispatch_lock:
            if pool.topology_epoch != epoch:
                return
            for row in rows:
                # rtpulint: disable=RT001 zero-then-free must be atomic vs reallocation under the dispatch lock (THE _reap_rows discipline residency.reclaim cites): releasing between would hand out a dirty row
                self.executor.zero_row(pool, row)  # RLock: reentrant
                pool.free_row(row)

    # -- TTL / expiry (RedissonExpirable analog) ---------------------------

    def _expire_if_due(self, entry) -> bool:
        """True if the entry was expired (and reaped) just now.  Reaps by
        entry IDENTITY (detach_if), so a racing reaper can never remove a
        fresh object re-created under the same name; detach-then-zero-
        then-free keeps the row un-reusable until it is clean."""
        if entry is not None and entry.expire_at is not None:
            if time.time() >= entry.expire_at:
                detached = self.registry.detach_if(entry.name, entry)
                if detached is not None:
                    epoch = entry.pool.topology_epoch
                    self._drain()
                    self._reap_rows(entry.pool, self._entry_rows(entry), epoch)
                    # Shared heavy-hitter table dies with the object (a
                    # successor under this name must not inherit ghosts).
                    self.topk.drop(entry.name)
                    # Near-cache entries die with it too (structural
                    # epoch advance — a successor continues the
                    # sequence, see cache/nearcache.py).
                    nc = getattr(self, "nearcache", None)
                    if nc is not None:
                        nc.drop_object(entry.name)
                    # Residency state (heat / host mirror accounting /
                    # disk blob) dies with the object too.
                    rm = getattr(self, "residency", None)
                    if rm is not None:
                        rm.drop(entry.name)
                    if self._mirrors:
                        with self._mirror_lock:
                            self._mirrors.pop(entry.name, None)
                return True
        return False

    def _live_lookup(self, name: str):
        entry = self.registry.lookup(name)
        if entry is not None and self._expire_if_due(entry):
            return None
        return entry

    def expire(self, name: str, ttl_s: float) -> bool:
        """PEXPIRE analog: schedule deletion ``ttl_s`` seconds from now."""
        return self.expire_at(name, time.time() + ttl_s)

    def expire_at(self, name: str, ts: float) -> bool:
        with self._journal_gate:
            entry = self._live_lookup(name)
            if entry is None:
                return False
            entry.expire_at = float(ts)
            # Journaled as the absolute deadline (the PEXPIREAT form):
            # replay re-arms it, and a deadline already past at recovery
            # lazily reaps — replay interleaves with TTL expiry exactly
            # like the live path.
            seq = self._journal_rec("obj.expire", name, at=float(ts))
            self._ensure_sweeper()
        # Durability fence outside the gate: waiting on the fsync under
        # it would serialize every writer behind one barrier.
        return self._ack(True, seq)

    def clear_expire(self, name: str) -> bool:
        """PERSIST analog: True if a TTL was removed."""
        with self._journal_gate:
            entry = self._live_lookup(name)
            if entry is None or entry.expire_at is None:
                return False
            entry.expire_at = None
            seq = self._journal_rec("obj.persist", name)
        return self._ack(True, seq)  # fence outside the gate

    def remain_ttl_ms(self, name: str) -> int:
        """PTTL convention: -2 absent, -1 no TTL, else remaining ms."""
        entry = self._live_lookup(name)
        if entry is None:
            return -2
        if entry.expire_at is None:
            return -1
        return max(0, int((entry.expire_at - time.time()) * 1000))

    def _ensure_sweeper(self) -> None:
        """Background expiry sweep, started lazily on the first TTL.
        Double-checked under the registry lock: two threads setting their
        first TTLs concurrently must not each start a sweeper (the orphan
        would keep reaping after _stop_sweeper, ADVICE r3 low)."""
        if getattr(self, "_sweeper", None) is not None:
            return
        with self.registry._lock:
            if getattr(self, "_sweeper", None) is not None:
                return
            stop = threading.Event()

            def sweep():
                while not stop.wait(0.25):
                    for entry in self.registry.entries():
                        if entry.expire_at is not None:
                            self._expire_if_due(entry)

            t = threading.Thread(
                target=sweep, name="rtpu-sketch-sweeper", daemon=True
            )
            self._sweeper = (t, stop)
            t.start()

    def _stop_sweeper(self) -> None:
        sw = getattr(self, "_sweeper", None)
        if sw is not None:
            sw[1].set()
            self._sweeper = None

    # -- DUMP / RESTORE (RedissonObject#dump/restore analog) ---------------

    def dump(self, name: str) -> Optional[bytes]:
        """Serialized object state, or None if absent (upstream raises on
        missing key at RESTORE time, not DUMP).

        Wire format is DATA-ONLY (no pickle — dump blobs may cross trust
        boundaries, and the reference's DUMP/RESTORE format is data-only,
        ADVICE r3): ``RTPU | u32 header_len | json header | npy row``."""
        entry = self._live_lookup(name)
        if entry is None:
            return None
        if _chaos.ENABLED:  # snapshot-I/O fault point (ISSUE 3)
            _chaos.fire("snapshot.save")
        # Mirror-aware: a degraded entry's truth is its host mirror —
        # dumping the device row would roll back every mirror-acked
        # write on a later RESTORE.
        row = self._host_row(entry)
        header = json.dumps(
            {
                "v": _DUMP_VERSION,
                "kind": entry.kind,
                "class_key": list(entry.pool.spec.class_key),
                "params": dict(entry.params),
                # CMS: the heavy-hitter candidate table travels with the
                # counters (a restore that kept counts but forgot which
                # keys were heavy would return an empty top_k()).
                "topk": self.topk.export_state(name),
            }
        ).encode("utf-8")
        buf = io.BytesIO()
        np.save(buf, np.asarray(row), allow_pickle=False)
        return (
            _DUMP_MAGIC + struct.pack("<I", len(header)) + header + buf.getvalue()
        )

    def restore(self, name: str, data: bytes, replace: bool = False) -> None:
        """Recreate an object from ``dump`` bytes.  BUSYKEY analog: raises
        if the name exists and ``replace`` is False."""
        with self._journal_gate:
            self._restore_impl(name, data, replace)
            # Journaled as the raw dump blob (wholesale state replace):
            # replay routes back through restore() itself.
            seq = self._journal_rec(
                "obj.restore", name, data=data, replace=bool(replace)
            )
        self._ack(None, seq)  # fence outside the gate

    def _restore_impl(self, name: str, data: bytes, replace: bool) -> None:
        if _chaos.ENABLED:  # snapshot-I/O fault point (ISSUE 3)
            _chaos.fire("snapshot.load", data=data)
        if len(data) < 8 or data[:4] != _DUMP_MAGIC:
            raise ValueError("not a sketch dump (bad magic)")
        (hlen,) = struct.unpack("<I", data[4:8])
        d = json.loads(data[8 : 8 + hlen].decode("utf-8"))
        d["class_key"] = tuple(d.get("class_key", ()))
        d["row"] = safe_load_npy(io.BytesIO(data[8 + hlen :]))
        if d.get("v") != _DUMP_VERSION:
            raise ValueError(f"unsupported dump version: {d.get('v')}")
        # Validate the untrusted candidate table BEFORE any mutation — a
        # malformed blob must not leave a half-restored object behind.
        topk_decoded = type(self.topk).decode_state(d.get("topk"), name)
        if self._live_lookup(name) is not None:
            if not replace:
                raise ValueError(f"BUSYKEY: {name!r} already exists")
            self.delete(name)
        self._guard_foreign(name)  # one keyspace: RESTORE can't shadow grid
        entry, created = self.registry.try_create(
            name, d["kind"], d["class_key"], d["params"]
        )
        if not created:  # raced with a concurrent creator
            raise ValueError(f"BUSYKEY: {name!r} already exists")
        row = np.asarray(d["row"])
        if row.shape[0] != entry.pool.row_units:
            raise ValueError(
                f"dump row has {row.shape[0]} units, pool expects "
                f"{entry.pool.row_units}"
            )
        if entry.row < 0:
            # Born cold (created past the device budget, ISSUE 14):
            # the restored state lives in a HOST mirror until heat
            # promotes it.
            self._install_residency_mirror(entry, row=row)
        else:
            self.executor.write_row(entry.pool, entry.row, row)
        # Unconditional: also CLEARS any ghost table when the dump
        # carries no candidates.
        self.topk.import_decoded(topk_decoded, name)
        # RESTORE replaces readable state wholesale: retire every cached
        # read of this name (structural epoch advance).
        nc = getattr(self, "nearcache", None)
        if nc is not None:
            nc.drop_object(name)

    # -- Snapshots (client-side RDB analog) --------------------------------

    def snapshot(self, directory: str) -> None:
        """Atomic full-state snapshot: every pool array D2H + registry
        metadata.  Written to tmp files (fsynced) then renamed (directory
        fsynced), so neither a concurrent restore nor a host crash after
        the rename ever sees a torn or empty snapshot.

        Journal coordination (ISSUE 10): the journal GATE is held across
        drain → cut → capture, so the cut seq recorded in the metadata
        exactly partitions records into snapshot-covered (retired by
        mark_snapshot once the files are durable) and tail (replayed at
        recovery).  See the gate comment in engines.__init__."""
        if _chaos.ENABLED:  # snapshot-I/O fault point (ISSUE 3)
            _chaos.fire("snapshot.save")
        os.makedirs(directory, exist_ok=True)
        # ONE snapshot at a time, capture through mark_snapshot: two
        # concurrent snapshot() calls (BGSAVE thread vs the periodic
        # snapshotter vs shutdown) could otherwise install an OLDER
        # capture over a newer one whose mark_snapshot already retired
        # the journal segments between their cuts — losing acked writes
        # on the next recovery (and corrupting the shared tmp files).
        with self._snapshot_lock:
            journal = getattr(self, "journal", None)
            with self._journal_gate:
                # rtpulint: disable=RT001 the drain barrier MUST run inside the snapshot lock: it is what makes the cut/capture consistent, and the only waiters on this lock are other whole-snapshot callers (BGSAVE/periodic/shutdown), never the write path
                self._drain()
                journal_cut = journal.cut() if journal is not None else 0
                meta, arrays = self._snapshot_capture()
            meta["journal_seq"] = journal_cut
            self._snapshot_write(directory, meta, arrays)
            self._last_save_ts = time.time()
            if journal is not None:
                # The snapshot covering records <= cut is durable on
                # disk: retire the covered segments (the BGREWRITEAOF
                # analog).
                journal.mark_snapshot(journal_cut)
            # Residency-blob GC barrier (ISSUE 14): the latest durable
            # snapshot now names exactly these blob files — retired
            # blobs outside the set may delete.
            rm = getattr(self, "residency", None)
            if rm is not None:
                rm.note_snapshot_refs(meta.get("residency_blobs", ()))
            # Companion-state hook (the client wires the grid keyspace
            # here): runs outside the engine locks (still inside the
            # snapshot lock — the grid files race identically), so
            # periodic snapshots persist the WHOLE logical keyspace,
            # not just sketch pools.
            hook = getattr(self, "snapshot_extra", None)
            if hook is not None:
                hook(directory)

    def _snapshot_capture(self):
        """Point-in-time capture of (meta, arrays) under the engine
        locks; no file I/O here."""
        # Lock ORDER: mirror lock, then registry._lock, then the dispatch
        # lock — the registry/dispatch order is what try_create/
        # bloom_replicate use (registry then pool.alloc_row; inverting
        # deadlocked a periodic snapshot against object creation, ADVICE
        # r3 high), and _reconcile_kind establishes mirror BEFORE both
        # (it holds the mirror lock across registry.lookup + write_row).
        # Holding all three makes the capture point-in-time consistent:
        # no tenant create/delete/grow, no mirror op or reconcile, can
        # interleave with the D2H reads.
        with self._mirror_lock, self.registry._lock, \
                self.executor._dispatch_lock:
            pools = self.registry.pools()
            arrays = {}
            pool_meta = []
            for i, pool in enumerate(pools):
                arrays[f"pool_{i}"] = self.executor.state_to_host(pool)
                pool_meta.append(
                    {
                        "key": list(pool.spec.key),
                        "kind": pool.spec.kind,
                        "class_key": list(pool.spec.class_key),
                        "capacity": pool.capacity,
                    }
                )
            if self._mirrors:
                # Degraded overlay: a mirrored entry's truth lives host-
                # side — patch its rows (primary + replicas) into the
                # captured arrays so a snapshot taken mid-degradation
                # keeps mirror-acked writes instead of the stale device
                # state.
                s_cur = getattr(self.executor, "S", 1)
                thresh = getattr(
                    self.config.tpu_sketch, "mbit_threshold_words", 0
                )
                pool_idx = {id(p): i for i, p in enumerate(pools)}
                for e in self.registry.entries():
                    mirror = self._mirrors.get(e.name)
                    if mirror is None:
                        continue
                    i = pool_idx[id(e.pool)]
                    if not arrays[f"pool_{i}"].flags.writeable:
                        # state_to_host returns a read-only view of the
                        # device buffer — copy before patching.
                        arrays[f"pool_{i}"] = arrays[f"pool_{i}"].copy()
                    data = np.asarray(mirror.encode(e.pool.row_units))
                    for r in self._entry_rows(e):
                        self._overlay_row(
                            arrays[f"pool_{i}"], pool_meta[i],
                            s_cur, thresh, r, data,
                        )
            # Residency tiers (ISSUE 14): a HOST-resident tenant's
            # truth is its mirror — captured as a standalone array; a
            # DISK-resident tenant's truth is its blob — captured by
            # exact filename + CRC (blobs are versioned, and GC never
            # deletes a file the latest snapshot names, so a restore +
            # journal-tail replay can never double-apply).  A born-cold
            # tenant with neither has all-zero state and restores as a
            # first-touch zero mirror.
            rm = getattr(self, "residency", None)
            disk_index = rm.disk_index() if rm is not None else {}
            blob_refs = []
            tenants = []
            for j, e in enumerate(self.registry.entries()):
                t = {
                    "name": e.name,
                    "kind": e.kind,
                    "pool_key": list(e.pool.spec.key),
                    "row": e.row,
                    "params": e.params,
                    "expire_at": e.expire_at,
                    "replica_rows": e.replica_rows,
                    "residency": getattr(e, "residency", "device"),
                }
                if e.row is not None and e.row < 0:
                    mirror = self._mirrors.get(e.name)
                    info = disk_index.get(e.name)
                    if mirror is not None:
                        key = f"tier_{j}"
                        arrays[key] = np.asarray(
                            mirror.encode(e.pool.row_units)
                        )
                        t["residency"] = "host"
                        t["tier_array"] = key
                    elif info is not None:
                        t["residency"] = "disk"
                        t["blob"] = info["file"]
                        t["blob_crc"] = int(info["crc"])
                        t["blob_nbytes"] = int(info["nbytes"])
                        blob_refs.append(info["file"])
                    else:
                        t["residency"] = "host"  # born cold: zeros
                tenants.append(t)
        meta = {
            "residency_blobs": blob_refs,
            "version": _DUMP_VERSION,
            "pools": pool_meta,
            "tenants": tenants,
            # Heavy-hitter candidate tables (engine-shared TopKStore):
            # without them a restore keeps every CMS counter but forgets
            # which keys were heavy — top_k() would come back empty.
            "topk": self.topk.export_state(),
            # Topology stamp: restores onto a DIFFERENT shard count remap
            # row-by-row (the explicit device-array remap that stands in
            # for cluster resharding, SURVEY §2.4).
            "num_shards": getattr(self.executor, "S", 1),
            "mbit_threshold_words": getattr(
                self.config.tpu_sketch, "mbit_threshold_words", 0
            ),
        }
        return meta, arrays

    def _snapshot_write(self, directory: str, meta: dict, arrays) -> None:
        """Crash-safe install (ISSUE 10 satellite): tmp files are
        FSYNCED before the rename and the directory after — without
        either, a host crash after os.replace could publish an empty or
        torn snapshot that restore_snapshot then trusts (the rename is
        only atomic against concurrent READERS, not against power loss
        of un-flushed data).  The metadata also stamps the pool blob's
        CRC: a crash in the tiny window between the two renames (new
        pools + old meta) is then DETECTED at restore instead of
        silently installing mismatched tenant tables."""
        tmp_npz = os.path.join(directory, _SNAP_POOLS + ".tmp.npz")
        tmp_meta = os.path.join(directory, _SNAP_META + ".tmp")
        np.savez(tmp_npz, **arrays)
        with open(tmp_npz, "rb") as f:
            crc = _crc_stream(f)
            os.fsync(f.fileno())
        meta = dict(meta)
        meta["pools_crc"] = crc
        with open(tmp_meta, "w") as f:
            json.dump(meta, f)
            f.flush()
            os.fsync(f.fileno())
        if _chaos.ENABLED:
            # Crash point between write and rename (the satellite's
            # chaos test): a fault here must leave the PREVIOUS
            # snapshot fully intact and loadable.
            _chaos.fire("snapshot.rename")
        os.replace(tmp_npz, os.path.join(directory, _SNAP_POOLS))
        os.replace(tmp_meta, os.path.join(directory, _SNAP_META))
        from redisson_tpu.durability.journal import _fsync_dir

        _fsync_dir(directory)

    def restore_snapshot(self, directory: str) -> bool:
        """Load a snapshot written by ``snapshot``; True if one was found.
        Called at engine init (before any traffic), so no drain needed.

        Resharding: a snapshot taken at shard count S_old restores onto
        ANY shard count — when topologies differ, tenant rows are
        extracted from the old layout host-side and written through the
        current executor row-by-row (the explicit device-array remap
        SURVEY §2.4 names in place of MOVED-redirect resharding)."""
        meta_path = os.path.join(directory, _SNAP_META)
        pools_path = os.path.join(directory, _SNAP_POOLS)
        if not (os.path.exists(meta_path) and os.path.exists(pools_path)):
            return False
        if _chaos.ENABLED:  # snapshot-I/O fault point (ISSUE 3)
            _chaos.fire("snapshot.load")
        with open(meta_path) as f:
            meta = json.load(f)
        if "pools_crc" in meta:
            # Torn-install detection (ISSUE 10 satellite): a crash in
            # the window between the pools and meta renames leaves a
            # new blob under an old manifest — refusing beats silently
            # installing mismatched tenant tables over live rows.
            with open(pools_path, "rb") as f:
                actual = _crc_stream(f)
            if actual != int(meta["pools_crc"]):
                raise ValueError(
                    "torn snapshot: pool blob CRC does not match its "
                    "metadata (crash between renames?) — refusing to "
                    "restore"
                )
        # Journal recovery barrier: records with seq <= this are covered
        # by the snapshot; the tail replays on top (ISSUE 10).
        self._restored_journal_seq = int(meta.get("journal_seq") or 0)
        # Validate candidate tables before any mutation (see restore()).
        topk_decoded = type(self.topk).decode_state(meta.get("topk"))
        data = np.load(pools_path)
        s_new = getattr(self.executor, "S", 1)
        new_thresh = getattr(self.config.tpu_sketch, "mbit_threshold_words", 0)
        if "num_shards" in meta:
            s_old = int(meta["num_shards"])
        elif meta["pools"]:
            # Legacy snapshot (no topology stamp): the array shape tells —
            # sharded states are 2-D [S, local], single-device flat.
            arr0 = data["pool_0"]
            s_old = arr0.shape[0] if arr0.ndim == 2 else 1
        else:
            s_old = s_new
        # Missing threshold stamp (legacy): assume unchanged config.
        old_thresh = int(meta.get("mbit_threshold_words", new_thresh))
        # Verbatim install is only valid when the LAYOUT matches — shard
        # count AND (on a mesh) the m-shard threshold, which changes how
        # bitset pools arrange words without changing array shapes.
        same_topology = s_old == s_new and (
            s_new == 1 or old_thresh == new_thresh
        )
        from typing import Callable

        remap_rows: dict[tuple, Callable[[int], np.ndarray]] = {}
        # Residency tiers (ISSUE 14): HOST/DISK tenants install AFTER
        # the registry/dispatch locks release — mirror installs take
        # the mirror lock, which orders BEFORE registry/dispatch
        # engine-wide (snapshot capture), and restore runs at engine
        # init, single-threaded, so deferred install loses nothing.
        pending_mirrors: list = []  # (entry, row_array | None)
        pending_disk: list = []     # (name, file, crc, nbytes)
        # Same lock order as snapshot(): registry before dispatch.
        with self.registry._lock, self.executor._dispatch_lock:
            if same_topology and self.registry.entries():
                # The verbatim install below resets every pool's free list
                # and overwrites the tenant table — on a live keyspace that
                # would hand occupied rows to new objects (silent aliasing,
                # ADVICE r3 medium).  Atomic refusal BEFORE any mutation.
                live = self.registry.names()
                raise ValueError(
                    f"BUSYKEY: {live[:3]!r} already exist — snapshot "
                    f"restore needs an empty keyspace"
                )
            for i, pm in enumerate(meta["pools"]):
                pool = self.registry.pool_for(pm["kind"], tuple(pm["class_key"]))
                arr = data[f"pool_{i}"]
                if same_topology:
                    # The snapshot's capacity is already executor-valid
                    # (produced by this executor shape) — install VERBATIM.
                    # Re-rounding could clamp a grown capacity back down
                    # (giant rows) and hand occupied rows to new tenants.
                    pool.capacity = int(pm["capacity"])
                    pool._free = list(range(pool.capacity - 1, -1, -1))
                    pool.generation += 1
                    self.executor.state_from_host(pool, arr)
                else:
                    remap_rows[tuple(pm["key"])] = self._extract_rows(
                        arr, pm, s_old, old_thresh
                    )
            by_key = {tuple(p.spec.key): p for p in self.registry.pools()}
            if not same_topology:
                # Atomic refusal: verify EVERY snapshot name is free
                # before creating any, so a BUSYKEY never leaves a
                # half-restored keyspace behind.
                busy = [
                    t["name"]
                    for t in meta["tenants"]
                    if self.registry.lookup(t["name"]) is not None
                ]
                if busy:
                    raise ValueError(
                        f"BUSYKEY: {busy[:3]!r} already exist — "
                        f"reshard-restore needs an empty keyspace"
                    )
            for t in meta["tenants"]:
                from redisson_tpu.tenancy.registry import TenantEntry

                tier = t.get("residency", "device")
                if tier != "device" or int(t["row"]) < 0:
                    # HOST/DISK tenant: no device row in ANY topology —
                    # tier state is layout-independent, so the same
                    # install serves both restore paths.
                    pool = by_key.get(tuple(t["pool_key"]))
                    if pool is None:
                        pool = self.registry.pool_for(
                            t["kind"], tuple(t["pool_key"])[1:]
                        )
                    entry = TenantEntry(
                        t["name"], t["kind"], pool, -1,
                        dict(t["params"]), t.get("expire_at"), None,
                        residency=tier,
                    )
                    self.registry._tenants[t["name"]] = entry
                    if tier == "disk":
                        pending_disk.append((
                            t["name"], t["blob"],
                            int(t.get("blob_crc", 0)),
                            int(t.get("blob_nbytes", 0)),
                        ))
                    elif t.get("tier_array"):
                        pending_mirrors.append(
                            (entry, np.asarray(data[t["tier_array"]]))
                        )
                    # else: born cold — zeros on first touch.
                    if t.get("expire_at") is not None:
                        self._ensure_sweeper()
                    continue
                if same_topology:
                    pool = by_key[tuple(t["pool_key"])]
                    row = int(t["row"])
                    replicas = t.get("replica_rows")
                    restored = TenantEntry(
                        t["name"], t["kind"], pool, row, dict(t["params"]),
                        t.get("expire_at"), replicas,
                    )
                    for r in self._entry_rows(restored):
                        if r in pool._free:
                            pool._free.remove(r)
                    self.registry._tenants[t["name"]] = restored
                else:
                    # Reshard: old row numbers are topology-specific —
                    # allocate fresh placement and write the extracted
                    # row through the CURRENT executor.  Read replicas
                    # are dropped (their placement was per-old-shard);
                    # re-replicate on demand.
                    getter = remap_rows[tuple(t["pool_key"])]
                    entry, created = self.registry.try_create(
                        t["name"], t["kind"], tuple(t["pool_key"])[1:],
                        dict(t["params"]),
                    )
                    if not created:  # raced a concurrent creator post-check
                        raise ValueError(
                            f"BUSYKEY: {t['name']!r} already exists — "
                            f"reshard-restore needs an empty keyspace"
                        )
                    entry.expire_at = t.get("expire_at")
                    # rtpulint: disable=RT001 reshard-restore must be atomic vs concurrent lookups/creates: both locks stay held for the whole install or a half-restored keyspace becomes visible (BUSYKEY refusal above is the fast path out)
                    self.executor.write_row(
                        entry.pool, entry.row, getter(int(t["row"]))
                    )
                if t.get("expire_at") is not None:
                    self._ensure_sweeper()
        for entry, rowdata in pending_mirrors:
            self._install_residency_mirror(entry, row=rowdata)
        if pending_disk:
            rm = getattr(self, "residency", None)
            if rm is None:
                raise ValueError(
                    "snapshot names DISK-resident tenants but this "
                    "engine has no residency manager"
                )
            for name, fname, crc, nb in pending_disk:
                rm.adopt_blob(name, fname, crc, nb)
        self.topk.import_decoded(topk_decoded)
        # Whole-keyspace event: every cached read predates the restored
        # state (nearcache may be absent: engine init builds it AFTER
        # restore_snapshot runs).
        nc = getattr(self, "nearcache", None)
        if nc is not None:
            nc.invalidate_all()
        return True

    # -- Online reshard (SURVEY §2.4 cluster row) --------------------------

    def change_topology(self, num_shards: int) -> bool:
        """Live reshard — the ClusterConnectionManager slot-remap /
        MasterSlaveEntry#changeMaster analog: swap the running engine onto
        a new shard count WITHOUT restart or keyspace wipe, with zero lost
        writes under concurrent traffic.

        Protocol:
        1. registry._lock — new op lookups/creates block for the swap's
           duration (ops already past lookup keep flowing into the
           coalescer; they stay valid, see 5);
        2. drain the coalescer — everything queued dispatches on the OLD
           executor and layout;
        3. dispatch lock — device state quiescent;
        4. D2H every pool, decode rows via the topology-aware extractor
           (the snapshot-reshard machinery), compose the new layout
           host-side, install a fresh executor that INHERITS the dispatch
           lock object (queued dispatch closures late-bind
           ``self.executor``, so segments submitted mid-swap run on the
           new executor);
        5. release — row numbers are topology-STABLE (only their physical
           placement changes), so ops that captured a row before the swap
           stay correct verbatim.

        Read replication is disabled by the swap (placement was
        per-old-shard); the replica rows themselves stay QUARANTINED —
        written with the filter's data in the new layout and never
        returned to the free list — because a producer may have read
        ``entry.replica_rows`` before the swap and submit ops targeting
        them after it (writes land harmlessly in a valid copy, reads
        still see correct bits).  Quarantined rows are permanently
        retired from the pool — a bounded leak of (S_old-1) rows per
        replicated object per reshard, the price of the zero-lost-writes
        guarantee (a snapshot/restore cycle reclaims them).
        Re-replicate on demand.  Returns False if the topology is
        unchanged.  On failure the engine rolls back to the old topology
        (config, executor, every pool) — no partial swap survives."""
        s_new = int(num_shards)
        s_old = getattr(self.executor, "S", 1)
        if s_new == s_old:
            return False
        if s_new < 1:
            raise ValueError(f"num_shards must be >= 1, got {s_new}")
        from redisson_tpu.executor.tpu_executor import TpuCommandExecutor

        with self.registry._lock:
            with _witness.allow_blocking(
                "swap protocol: drain blocks under the registry lock "
                "by design (see change_topology docstring step 1-2)"
            ):
                # rtpulint: disable=RT001 the documented swap protocol: registry lock blocks NEW lookups while queued ops drain on the old layout — draining outside the lock would let a post-drain op capture the old executor mid-swap
                self._drain()
            old_exec = self.executor
            old_thresh = getattr(
                self.config.tpu_sketch, "mbit_threshold_words", 0
            )
            with old_exec._dispatch_lock:
                self.config.tpu_sketch.num_shards = s_new
                try:
                    if s_new > 1:
                        from redisson_tpu.executor.sharded_executor import (
                            ShardedTpuCommandExecutor,
                        )

                        new_exec = ShardedTpuCommandExecutor(self.config)
                    else:
                        new_exec = TpuCommandExecutor(self.config)
                except Exception:
                    self.config.tpu_sketch.num_shards = s_old
                    raise
                # ONE dispatch lock for the engine's lifetime: closures in
                # queued segments and pool.alloc_row hold references to
                # this object — swapping it would split the mutual
                # exclusion domain.
                new_exec._dispatch_lock = old_exec._dispatch_lock
                # Observability continuity: the successor keeps recording
                # into the same registry/aggregate (a reshard must not
                # silently zero the op counters).
                new_exec.obs = old_exec.obs
                new_exec.metrics = old_exec.metrics
                entries = self.registry.entries()
                # Phase 1 — PURE: compose every pool's new-layout array and
                # free list host-side; nothing is mutated until all pools
                # composed (a failure here leaves the engine untouched).
                plans = []  # (pool, cap_new, new_arr, new_free)
                for pool in self.registry.pools():
                    arr = old_exec.state_to_host(pool)
                    pm = {
                        "kind": pool.spec.kind,
                        "class_key": list(pool.spec.class_key),
                        "capacity": pool.capacity,
                    }
                    getter = self._extract_rows(arr, pm, s_old, old_thresh)
                    u = pool.spec.row_units
                    dtype = pool.spec.dtype
                    mbit_new = s_new > 1 and new_exec._mbit_layout(
                        u, pool.spec.kind
                    )
                    # Row numbers are preserved: capacity only rounds UP
                    # (to an S-multiple for the row-sharded layout); never
                    # re-clamped down (a grown pool must keep its rows).
                    if s_new == 1 or mbit_new:
                        cap_new = pool.capacity
                    else:
                        cap_new = -(-pool.capacity // s_new) * s_new
                    live = [e for e in entries if e.pool is pool]
                    # Every row in-flight ops may target survives the swap
                    # with its data: primaries AND read replicas (see
                    # docstring — replicas are quarantined, not freed).
                    keep_rows: list[int] = []
                    for e in live:
                        keep_rows.extend(self._entry_rows(e))
                    if s_new == 1:
                        new_arr = np.zeros(cap_new * u + 1, dtype)
                        for r in keep_rows:
                            new_arr[r * u : (r + 1) * u] = getter(r)
                    elif mbit_new:
                        wl = u // s_new
                        new_arr = np.zeros((s_new, cap_new * wl + 1), dtype)
                        for r in keep_rows:
                            data = getter(r)
                            for s in range(s_new):
                                new_arr[s, r * wl : (r + 1) * wl] = (
                                    data[s * wl : (s + 1) * wl]
                                )
                    else:
                        new_arr = np.zeros(
                            (s_new, cap_new // s_new * u + 1), dtype
                        )
                        for r in keep_rows:
                            local = r // s_new
                            new_arr[
                                r % s_new, local * u : (local + 1) * u
                            ] = getter(r)
                    used = set(keep_rows)
                    new_free = [
                        r for r in range(cap_new - 1, -1, -1) if r not in used
                    ]
                    plans.append((pool, cap_new, new_arr, new_free))
                # Phase 2 — MUTATE, journaled: any failure restores every
                # pool, the config, and the executor binding.
                journal = []
                try:
                    for pool, cap_new, new_arr, new_free in plans:
                        journal.append(
                            (
                                pool,
                                pool.state,
                                pool.capacity,
                                pool._free,
                                pool.generation,
                                pool.topology_epoch,
                                pool._factory,
                            )
                        )
                        pool.capacity = cap_new
                        pool._free = new_free
                        pool.generation += 1
                        # Reap sequences (delete/expiry/rename/migration)
                        # that detached BEFORE this swap must not
                        # zero/free again: their rows were reclaimed by
                        # the rebuild above (engines._reap_rows checks
                        # this epoch under the dispatch lock we hold).
                        pool.topology_epoch += 1
                        pool._factory = new_exec
                        new_exec.state_from_host(pool, new_arr)
                except Exception:
                    for pool, st, cap, free, gen, ep, fac in journal:
                        pool.state = st
                        pool.capacity = cap
                        pool._free = free
                        pool.generation = gen
                        pool.topology_epoch = ep
                        pool._factory = fac
                    self.config.tpu_sketch.num_shards = s_old
                    raise
                # Point of no return — all device state installed.
                for e in entries:
                    e.replica_rows = None  # quarantined, not freed
                self.registry._factory = new_exec
                self.executor = new_exec
                pw = getattr(self, "prewarmer", None)
                if pw is not None:
                    # Rebind the pre-warmer to the successor and re-run
                    # every registered ladder against the new layout —
                    # without this it would hold the retired executor
                    # forever, silently skipping warm tasks while
                    # prewarm_wait still reported a warmed cache (the
                    # compile cliff would return after any reshard).
                    pw.rebind_executor(new_exec)
                # Retire the old executor LAST: a caller that read
                # engine.executor before this swap and is blocked on the
                # dispatch lock gets FORWARDED to the successor when it
                # acquires (see _locked in tpu_executor.py); runs-metadata
                # dispatches that can't forward raise retryable into the
                # coalescer's retry loop instead.
                old_exec._successor = new_exec
                # Topology changed under every cached read: whole-
                # keyspace near-cache invalidation (defensive — values
                # are layout-independent, but a mid-swap read may have
                # raced the install).
                nc = getattr(self, "nearcache", None)
                if nc is not None:
                    nc.invalidate_all()
                old_exec._retired = True
        return True

    @staticmethod
    def _extract_rows(arr: np.ndarray, pm: dict, s_old: int, mbit_thresh: int):
        """Row getter over a snapshot pool array from a DIFFERENT topology:
        decodes the old executor layout host-side (flat single-device,
        [S, rows_local*U+1] row-sharded, or [S, cap*(U/S)+1] m-sharded)."""
        from redisson_tpu.tenancy import PoolKind
        from redisson_tpu.tenancy.registry import spec_for

        spec = spec_for(pm["kind"], tuple(pm["class_key"]))
        u = spec.row_units
        if s_old == 1:
            def get(row: int) -> np.ndarray:
                return arr[row * u : (row + 1) * u]
            return get
        mbit = (
            pm["kind"] == PoolKind.BITSET
            and mbit_thresh
            and u >= mbit_thresh
            and u % s_old == 0
        )
        if mbit:
            wl = u // s_old
            def get(row: int) -> np.ndarray:
                return np.concatenate(
                    [arr[s, row * wl : (row + 1) * wl] for s in range(s_old)]
                )
            return get

        def get(row: int) -> np.ndarray:
            local = row // s_old
            return arr[row % s_old, local * u : (local + 1) * u]
        return get

    @staticmethod
    def _overlay_row(
        arr: np.ndarray, pm: dict, s: int, mbit_thresh: int,
        row: int, data: np.ndarray,
    ) -> None:
        """Inverse of ``_extract_rows`` for ONE row: write ``data`` into
        a captured host pool array at ``row``'s position in the CURRENT
        executor layout (flat single-device, row-sharded, or m-sharded).
        Used by snapshot() to overlay degraded-mirror state."""
        from redisson_tpu.tenancy import PoolKind
        from redisson_tpu.tenancy.registry import spec_for

        spec = spec_for(pm["kind"], tuple(pm["class_key"]))
        u = spec.row_units
        data = np.asarray(data)[:u]
        if s == 1:
            arr[row * u : (row + 1) * u] = data
            return
        mbit = (
            pm["kind"] == PoolKind.BITSET
            and mbit_thresh
            and u >= mbit_thresh
            and u % s == 0
        )
        if mbit:
            wl = u // s
            for sh in range(s):
                arr[sh, row * wl : (row + 1) * wl] = (
                    data[sh * wl : (sh + 1) * wl]
                )
            return
        local = row // s
        arr[row % s, local * u : (local + 1) * u] = data

    def _start_snapshotter(self, directory: str, interval_s: float) -> None:
        stop = threading.Event()

        def loop():
            while not stop.wait(interval_s):
                try:
                    self.snapshot(directory)
                except Exception:  # pragma: no cover — best-effort persistence
                    pass

        t = threading.Thread(target=loop, name="rtpu-snapshotter", daemon=True)
        self._snapshotter = (t, stop)
        t.start()

    def _stop_snapshotter(self) -> None:
        sn = getattr(self, "_snapshotter", None)
        if sn is not None:
            sn[1].set()
            # Join: a snapshot may be mid-write; the shutdown path's own
            # final snapshot must not interleave with it on the same
            # files (tmp names are unique, but last-writer-wins on the
            # rename — the FINAL snapshot must be the final state).
            sn[0].join(timeout=30.0)
            self._snapshotter = None
