"""Sketch-state durability: TTL, DUMP/RESTORE, and snapshots.

Role parity (SURVEY.md §5 checkpoint row):
- ``expire``/``remain_ttl_ms`` — org/redisson/RedissonExpirable.java: a
  named sketch can carry an absolute expiry deadline; expired objects
  vanish from the keyspace (lazy check on lookup + a background sweeper,
  the same two-tier discipline Redis applies to expired keys).
- ``dump``/``restore`` — org/redisson/RedissonObject.java#dump/restore:
  one object's device row + params serialized to opaque bytes.
- ``snapshot``/``restore_snapshot`` — the client-side answer to Redis
  RDB persistence: device pools D2H'd to an .npz + registry metadata
  JSON; ``Config.snapshot_dir``/``snapshot_interval_s`` arm periodic
  snapshots and restore-on-create (the keys were accepted-and-ignored in
  rounds 1-2 — now live).

Mixed into TpuSketchEngine (objects/engines.py).
"""

from __future__ import annotations

import json
import os
import pickle
import threading
import time
from typing import Optional

import numpy as np

_DUMP_VERSION = 1
_SNAP_META = "sketch_meta.json"
_SNAP_POOLS = "sketch_pools.npz"


class SketchDurabilityMixin:
    """Requires: self.registry, self.executor, self._drain(), self.delete().
    """

    @staticmethod
    def _entry_rows(entry) -> list:
        """Every device row an entry owns (primary + read replicas) — the
        ONE place this enumeration lives (delete/expiry/rename/restore
        all free through it)."""
        return list(entry.replica_rows) if entry.replica_rows else [entry.row]

    # -- TTL / expiry (RedissonExpirable analog) ---------------------------

    def _expire_if_due(self, entry) -> bool:
        """True if the entry was expired (and reaped) just now.  Reaps by
        entry IDENTITY (detach_if), so a racing reaper can never remove a
        fresh object re-created under the same name; detach-then-zero-
        then-free keeps the row un-reusable until it is clean."""
        if entry is not None and entry.expire_at is not None:
            if time.time() >= entry.expire_at:
                detached = self.registry.detach_if(entry.name, entry)
                if detached is not None:
                    self._drain()
                    for row in self._entry_rows(entry):
                        self.executor.zero_row(entry.pool, row)
                        entry.pool.free_row(row)
                    # Shared heavy-hitter table dies with the object (a
                    # successor under this name must not inherit ghosts).
                    self.topk.drop(entry.name)
                return True
        return False

    def _live_lookup(self, name: str):
        entry = self.registry.lookup(name)
        if entry is not None and self._expire_if_due(entry):
            return None
        return entry

    def expire(self, name: str, ttl_s: float) -> bool:
        """PEXPIRE analog: schedule deletion ``ttl_s`` seconds from now."""
        return self.expire_at(name, time.time() + ttl_s)

    def expire_at(self, name: str, ts: float) -> bool:
        entry = self._live_lookup(name)
        if entry is None:
            return False
        entry.expire_at = float(ts)
        self._ensure_sweeper()
        return True

    def clear_expire(self, name: str) -> bool:
        """PERSIST analog: True if a TTL was removed."""
        entry = self._live_lookup(name)
        if entry is None or entry.expire_at is None:
            return False
        entry.expire_at = None
        return True

    def remain_ttl_ms(self, name: str) -> int:
        """PTTL convention: -2 absent, -1 no TTL, else remaining ms."""
        entry = self._live_lookup(name)
        if entry is None:
            return -2
        if entry.expire_at is None:
            return -1
        return max(0, int((entry.expire_at - time.time()) * 1000))

    def _ensure_sweeper(self) -> None:
        """Background expiry sweep, started lazily on the first TTL."""
        if getattr(self, "_sweeper", None) is not None:
            return
        stop = threading.Event()

        def sweep():
            while not stop.wait(0.25):
                for entry in self.registry.entries():
                    if entry.expire_at is not None:
                        self._expire_if_due(entry)

        t = threading.Thread(target=sweep, name="rtpu-sketch-sweeper", daemon=True)
        self._sweeper = (t, stop)
        t.start()

    def _stop_sweeper(self) -> None:
        sw = getattr(self, "_sweeper", None)
        if sw is not None:
            sw[1].set()
            self._sweeper = None

    # -- DUMP / RESTORE (RedissonObject#dump/restore analog) ---------------

    def dump(self, name: str) -> Optional[bytes]:
        """Serialized object state, or None if absent (upstream raises on
        missing key at RESTORE time, not DUMP)."""
        entry = self._live_lookup(name)
        if entry is None:
            return None
        self._drain()
        row = self.executor.read_row(entry.pool, entry.row)
        return pickle.dumps(
            {
                "v": _DUMP_VERSION,
                "kind": entry.kind,
                "class_key": tuple(entry.pool.spec.class_key),
                "params": dict(entry.params),
                "row": row,
            }
        )

    def restore(self, name: str, data: bytes, replace: bool = False) -> None:
        """Recreate an object from ``dump`` bytes.  BUSYKEY analog: raises
        if the name exists and ``replace`` is False."""
        d = pickle.loads(data)
        if d.get("v") != _DUMP_VERSION:
            raise ValueError(f"unsupported dump version: {d.get('v')}")
        if self._live_lookup(name) is not None:
            if not replace:
                raise ValueError(f"BUSYKEY: {name!r} already exists")
            self.delete(name)
        self._guard_foreign(name)  # one keyspace: RESTORE can't shadow grid
        entry, created = self.registry.try_create(
            name, d["kind"], d["class_key"], d["params"]
        )
        if not created:  # raced with a concurrent creator
            raise ValueError(f"BUSYKEY: {name!r} already exists")
        row = np.asarray(d["row"])
        if row.shape[0] != entry.pool.row_units:
            raise ValueError(
                f"dump row has {row.shape[0]} units, pool expects "
                f"{entry.pool.row_units}"
            )
        self.executor.write_row(entry.pool, entry.row, row)

    # -- Snapshots (client-side RDB analog) --------------------------------

    def snapshot(self, directory: str) -> None:
        """Atomic full-state snapshot: every pool array D2H + registry
        metadata.  Written to tmp files then renamed, so a concurrent
        restore never sees a torn snapshot."""
        os.makedirs(directory, exist_ok=True)
        self._drain()
        # The dispatch lock freezes pool.state swaps (donation) and registry
        # growth for the duration of the D2H reads.
        with self.executor._dispatch_lock:
            pools = self.registry.pools()
            arrays = {}
            pool_meta = []
            for i, pool in enumerate(pools):
                arrays[f"pool_{i}"] = self.executor.state_to_host(pool)
                pool_meta.append(
                    {
                        "key": list(pool.spec.key),
                        "kind": pool.spec.kind,
                        "class_key": list(pool.spec.class_key),
                        "capacity": pool.capacity,
                    }
                )
            tenants = [
                {
                    "name": e.name,
                    "kind": e.kind,
                    "pool_key": list(e.pool.spec.key),
                    "row": e.row,
                    "params": e.params,
                    "expire_at": e.expire_at,
                    "replica_rows": e.replica_rows,
                }
                for e in self.registry.entries()
            ]
        meta = {
            "version": _DUMP_VERSION,
            "pools": pool_meta,
            "tenants": tenants,
            # Topology stamp: restores onto a DIFFERENT shard count remap
            # row-by-row (the explicit device-array remap that stands in
            # for cluster resharding, SURVEY §2.4).
            "num_shards": getattr(self.executor, "S", 1),
            "mbit_threshold_words": getattr(
                self.config.tpu_sketch, "mbit_threshold_words", 0
            ),
        }
        tmp_npz = os.path.join(directory, _SNAP_POOLS + ".tmp.npz")
        tmp_meta = os.path.join(directory, _SNAP_META + ".tmp")
        np.savez(tmp_npz, **arrays)
        with open(tmp_meta, "w") as f:
            json.dump(meta, f)
        os.replace(tmp_npz, os.path.join(directory, _SNAP_POOLS))
        os.replace(tmp_meta, os.path.join(directory, _SNAP_META))

    def restore_snapshot(self, directory: str) -> bool:
        """Load a snapshot written by ``snapshot``; True if one was found.
        Called at engine init (before any traffic), so no drain needed.

        Resharding: a snapshot taken at shard count S_old restores onto
        ANY shard count — when topologies differ, tenant rows are
        extracted from the old layout host-side and written through the
        current executor row-by-row (the explicit device-array remap
        SURVEY §2.4 names in place of MOVED-redirect resharding)."""
        meta_path = os.path.join(directory, _SNAP_META)
        pools_path = os.path.join(directory, _SNAP_POOLS)
        if not (os.path.exists(meta_path) and os.path.exists(pools_path)):
            return False
        with open(meta_path) as f:
            meta = json.load(f)
        data = np.load(pools_path)
        s_new = getattr(self.executor, "S", 1)
        new_thresh = getattr(self.config.tpu_sketch, "mbit_threshold_words", 0)
        if "num_shards" in meta:
            s_old = int(meta["num_shards"])
        elif meta["pools"]:
            # Legacy snapshot (no topology stamp): the array shape tells —
            # sharded states are 2-D [S, local], single-device flat.
            arr0 = data["pool_0"]
            s_old = arr0.shape[0] if arr0.ndim == 2 else 1
        else:
            s_old = s_new
        # Missing threshold stamp (legacy): assume unchanged config.
        old_thresh = int(meta.get("mbit_threshold_words", new_thresh))
        # Verbatim install is only valid when the LAYOUT matches — shard
        # count AND (on a mesh) the m-shard threshold, which changes how
        # bitset pools arrange words without changing array shapes.
        same_topology = s_old == s_new and (
            s_new == 1 or old_thresh == new_thresh
        )
        from typing import Callable

        remap_rows: dict[tuple, Callable[[int], np.ndarray]] = {}
        with self.executor._dispatch_lock:
            for i, pm in enumerate(meta["pools"]):
                pool = self.registry.pool_for(pm["kind"], tuple(pm["class_key"]))
                arr = data[f"pool_{i}"]
                if same_topology:
                    # The snapshot's capacity is already executor-valid
                    # (produced by this executor shape) — install VERBATIM.
                    # Re-rounding could clamp a grown capacity back down
                    # (giant rows) and hand occupied rows to new tenants.
                    pool.capacity = int(pm["capacity"])
                    pool._free = list(range(pool.capacity - 1, -1, -1))
                    pool.generation += 1
                    self.executor.state_from_host(pool, arr)
                else:
                    remap_rows[tuple(pm["key"])] = self._extract_rows(
                        arr, pm, s_old, old_thresh
                    )
            by_key = {tuple(p.spec.key): p for p in self.registry.pools()}
            if not same_topology:
                # Atomic refusal: verify EVERY snapshot name is free
                # before creating any, so a BUSYKEY never leaves a
                # half-restored keyspace behind.
                busy = [
                    t["name"]
                    for t in meta["tenants"]
                    if self.registry.lookup(t["name"]) is not None
                ]
                if busy:
                    raise ValueError(
                        f"BUSYKEY: {busy[:3]!r} already exist — "
                        f"reshard-restore needs an empty keyspace"
                    )
            for t in meta["tenants"]:
                from redisson_tpu.tenancy.registry import TenantEntry

                if same_topology:
                    pool = by_key[tuple(t["pool_key"])]
                    row = int(t["row"])
                    replicas = t.get("replica_rows")
                    restored = TenantEntry(
                        t["name"], t["kind"], pool, row, dict(t["params"]),
                        t.get("expire_at"), replicas,
                    )
                    for r in self._entry_rows(restored):
                        if r in pool._free:
                            pool._free.remove(r)
                    self.registry._tenants[t["name"]] = restored
                else:
                    # Reshard: old row numbers are topology-specific —
                    # allocate fresh placement and write the extracted
                    # row through the CURRENT executor.  Read replicas
                    # are dropped (their placement was per-old-shard);
                    # re-replicate on demand.
                    getter = remap_rows[tuple(t["pool_key"])]
                    entry, created = self.registry.try_create(
                        t["name"], t["kind"], tuple(t["pool_key"])[1:],
                        dict(t["params"]),
                    )
                    if not created:  # raced a concurrent creator post-check
                        raise ValueError(
                            f"BUSYKEY: {t['name']!r} already exists — "
                            f"reshard-restore needs an empty keyspace"
                        )
                    entry.expire_at = t.get("expire_at")
                    self.executor.write_row(
                        entry.pool, entry.row, getter(int(t["row"]))
                    )
                if t.get("expire_at") is not None:
                    self._ensure_sweeper()
        return True

    @staticmethod
    def _extract_rows(arr: np.ndarray, pm: dict, s_old: int, mbit_thresh: int):
        """Row getter over a snapshot pool array from a DIFFERENT topology:
        decodes the old executor layout host-side (flat single-device,
        [S, rows_local*U+1] row-sharded, or [S, cap*(U/S)+1] m-sharded)."""
        from redisson_tpu.tenancy import PoolKind
        from redisson_tpu.tenancy.registry import spec_for

        spec = spec_for(pm["kind"], tuple(pm["class_key"]))
        u = spec.row_units
        if s_old == 1:
            def get(row: int) -> np.ndarray:
                return arr[row * u : (row + 1) * u]
            return get
        mbit = (
            pm["kind"] == PoolKind.BITSET
            and mbit_thresh
            and u >= mbit_thresh
            and u % s_old == 0
        )
        if mbit:
            wl = u // s_old
            def get(row: int) -> np.ndarray:
                return np.concatenate(
                    [arr[s, row * wl : (row + 1) * wl] for s in range(s_old)]
                )
            return get

        def get(row: int) -> np.ndarray:
            local = row // s_old
            return arr[row % s_old, local * u : (local + 1) * u]
        return get

    def _start_snapshotter(self, directory: str, interval_s: float) -> None:
        stop = threading.Event()

        def loop():
            while not stop.wait(interval_s):
                try:
                    self.snapshot(directory)
                except Exception:  # pragma: no cover — best-effort persistence
                    pass

        t = threading.Thread(target=loop, name="rtpu-snapshotter", daemon=True)
        self._snapshotter = (t, stop)
        t.start()

    def _stop_snapshotter(self) -> None:
        sn = getattr(self, "_snapshotter", None)
        if sn is not None:
            sn[1].set()
            self._snapshotter = None
