"""Sketch engines: the backend behind BloomFilter/HyperLogLog/BitSet/CMS.

Two implementations of one interface, selected by
``Config.use_tpu_sketch()`` — the north-star mode switch:

- ``TpuSketchEngine``: tenant registry + size-class device pools +
  TpuCommandExecutor (stacked arrays, batched kernels).
- ``HostSketchEngine``: the golden NumPy models, playing the role the Redis
  server plays for the reference (→ SURVEY.md §2.2: the sketch math the
  client never implements).  It is also the honest comparison baseline for
  the benchmark configs.

Both consume identical host-side hash material (the object layer hashes
once with the shared murmur twins), so FPP/estimates agree bit-for-bit
between modes — the ≤2% FPP-drift gate reduces to kernel correctness.
"""

from __future__ import annotations

import math
import threading
import time
from typing import Optional

import numpy as np

from redisson_tpu import chaos as _chaos
from redisson_tpu import overload as _ovl
from redisson_tpu.analysis import witness as _witness
from redisson_tpu.executor import LazyResult, TpuCommandExecutor
from redisson_tpu.objects.durability import SketchDurabilityMixin
from redisson_tpu.ops import golden
from redisson_tpu.tenancy import PoolKind, TenantRegistry
from redisson_tpu.tenancy.registry import class_words_for_bits
from redisson_tpu.utils import hashing


class ImmediateResult(LazyResult):
    """Host-engine results are already materialized."""

    def __init__(self, value):
        super().__init__(value)


class TopKStore:
    """Engine-shared heavy-hitter candidate tables (BASELINE config 5).

    Name-addressed: every CountMinSketch handle for ``name`` — from any
    number of client facades — sees ONE table (round-2 review flagged the
    per-instance dict: two handles to the same sketch disagreed).  The
    table holds candidate keys with their last-seen estimates, max-merged
    and pruned; ``top_k()`` re-estimates candidates on device for
    exactness, so the table only needs to not LOSE heavy keys."""

    def __init__(self):
        self._lock = _witness.named(threading.Lock(), "engine.topk")
        self._tables: dict[str, dict] = {}

    def configure(self, name: str, k: int) -> None:
        with self._lock:
            t = self._tables.get(name)
            if t is None:
                self._tables[name] = {"k": int(k), "cands": {}}
            else:
                t["k"] = max(t["k"], int(k))

    def track(self, name: str) -> int:
        with self._lock:
            t = self._tables.get(name)
            return 0 if t is None else t["k"]

    def offer(self, name: str, keys, estimates) -> None:
        """Max-merge a batch's post-update estimates.  Only the batch's
        heaviest 4k candidates are offered by callers (argpartition over
        the estimate stream), so the table stays small under 100M-event
        ingest."""
        import heapq

        with self._lock:
            t = self._tables.get(name)
            if t is None:
                return
            cands = t["cands"]
            for key, est in zip(keys, estimates):
                e = int(est)
                if cands.get(key, 0) < e:
                    cands[key] = e
            cap = 4 * max(t["k"], 16)
            if len(cands) > 2 * cap:
                keep = heapq.nlargest(cap, cands.items(), key=lambda kv: kv[1])
                t["cands"] = dict(keep)

    def candidates(self, name: str) -> list:
        with self._lock:
            t = self._tables.get(name)
            return [] if t is None else list(t["cands"])

    def drop(self, name: str) -> None:
        with self._lock:
            self._tables.pop(name, None)

    def rename(self, old: str, new: str) -> None:
        with self._lock:
            t = self._tables.pop(old, None)
            if t is not None:
                self._tables[new] = t

    # -- durability (data-only: snapshots + CMS dump blobs carry the
    # candidate tables — losing them on restore would forget every heavy
    # hitter even though the counters survive) ----------------------------

    # Candidate keys must round-trip with their ORIGINAL scalar type —
    # the codec encodes np.uint64(5) and 5 to different bytes (see
    # count_min_sketch.py offer note), so a type-collapsing export would
    # make restored top_k() re-estimate the wrong cells.
    _KEY_TAGS = {
        int: ("i", int),
        np.uint64: ("u8", int),
        np.uint32: ("u4", int),
        np.int64: ("i8", int),
        np.int32: ("i4", int),
        str: ("s", str),
    }
    _TAG_DECODE = {
        "i": int,
        "u8": np.uint64,
        "u4": np.uint32,
        "i8": np.int64,
        "i4": np.int32,
        "s": str,
        "b": bytes.fromhex,
    }
    MAX_K = 1 << 20  # prune-cap sanity bound for imported tables

    @classmethod
    def _encode_cands(cls, name: str, t: dict) -> dict:
        cands = []
        skipped = set()
        for k_, v_ in t["cands"].items():
            enc = cls._KEY_TAGS.get(type(k_))
            if enc is not None:
                cands.append([enc[0], enc[1](k_), int(v_)])
            elif isinstance(k_, bytes):
                cands.append(["b", k_.hex(), int(v_)])
            else:
                skipped.add(type(k_).__name__)
        if skipped:
            import warnings

            warnings.warn(
                f"top-K candidates of {name!r} with non-serializable key "
                f"types {sorted(skipped)} were not exported; they will "
                f"re-enter the table from future traffic"
            )
        return {"k": int(t["k"]), "cands": cands}

    @classmethod
    def _decode_cands(cls, d: dict) -> dict:
        """Strict decode of an UNTRUSTED table blob: unknown tags or
        malformed values raise ValueError (callers validate BEFORE any
        state mutation); k is clamped to the prune-cap sanity bound."""
        cands = {}
        for entry in d.get("cands", []):
            if not isinstance(entry, (list, tuple)) or len(entry) != 3:
                raise ValueError(f"bad topk entry: {entry!r}")
            tag, val, est = entry
            dec = cls._TAG_DECODE.get(tag)
            if dec is None:
                raise ValueError(f"bad topk key tag: {tag!r}")
            cands[dec(val)] = int(est)
        k = int(d.get("k", 0))
        if not 0 <= k <= cls.MAX_K:
            raise ValueError(f"topk k={k} out of range")
        return {"k": k, "cands": cands}

    def export_state(self, name: Optional[str] = None):
        """JSON-safe copy of one table (or all) for snapshots/dumps."""
        with self._lock:
            if name is not None:
                t = self._tables.get(name)
                return None if t is None else self._encode_cands(name, t)
            return {
                n: self._encode_cands(n, t) for n, t in self._tables.items()
            }

    @classmethod
    def decode_state(cls, state, name: Optional[str] = None):
        """Validate+decode an untrusted blob WITHOUT touching the store —
        restore paths call this before any state mutation, then install
        the returned value via import_decoded."""
        if name is not None:
            return cls._decode_cands(state) if state else None
        return {n: cls._decode_cands(d) for n, d in (state or {}).items()}

    def import_decoded(self, decoded, name: Optional[str] = None) -> None:
        with self._lock:
            if name is not None:
                self._tables.pop(name, None)  # never keep a ghost table
                if decoded:
                    self._tables[name] = decoded
                return
            for n, d in (decoded or {}).items():
                self._tables[n] = d

    def import_state(self, state, name: Optional[str] = None) -> None:
        self.import_decoded(self.decode_state(state, name), name)


class _ConcatLazy:
    """LazyResult adapter concatenating per-group results in op order —
    used when a mid-segment migration split one coalesced launch into
    consecutive per-pool launches (futures slice by [start, start+n)
    against the concatenation, which preserves op order)."""

    def __init__(self, parts):
        self._parts = parts
        self._done = None

    def result(self, timeout=None):
        # ``timeout`` accepted for signature parity with HintedFuture /
        # LazyResult (callers treat the future types interchangeably);
        # the per-part fetches are synchronous, so it is ignored.
        if self._done is None:
            self._done = np.concatenate([p.result() for p in self._parts])
            self._parts = None
        return self._done

    def get(self):
        return self.result()

    def done(self) -> bool:
        return self._done is not None


class _EpochGuard:
    """Entry+exit write-epoch bump around a mutating engine call (see
    cache/nearcache.py module doc: the entry bump retires stale serving
    the moment the write is in flight; the exit bump retires installs
    whose reads were captured inside the entry→submit window)."""

    __slots__ = ("_bump", "_name")

    def __init__(self, bump, name):
        self._bump = bump
        self._name = name

    def __enter__(self):
        self._bump(self._name)
        return self

    def __exit__(self, *exc):
        self._bump(self._name)
        return False


class _MappedFuture:
    """Future adapter applying a transform on .result()."""

    def __init__(self, fut, transform):
        self._fut = fut
        self._transform = transform

    def result(self, *a, **kw):
        return self._transform(self._fut.result(*a, **kw))

    def get(self):
        return self.result()

    def done(self):
        return self._fut.done()


class _DurableResult:
    """Ack gate for ``appendfsync=always`` (ISSUE 10): the caller's
    ``.result()`` returns only after the op's journal record is fsynced
    — group commit batches the fsyncs, so a burst of writers amortizes
    one disk barrier.  Wraps any result-like (HintedFuture, LazyResult,
    ImmediateResult, _MappedFuture)."""

    __slots__ = ("_res", "_journal", "_seq")

    def __init__(self, res, journal, seq):
        self._res = res
        self._journal = journal
        self._seq = seq

    def result(self, timeout=None):
        v = self._res.result(timeout)
        if not self._journal.wait_durable(self._seq, timeout):
            # A timed-out durability wait must NOT ack: returning the
            # value here would report a write durable that a crash can
            # still lose — the one lie this class exists to prevent.
            raise TimeoutError(
                f"journal record {self._seq} not fsynced within "
                f"{timeout}s (appendfsync=always durability fence)"
            )
        return v

    def get(self):
        return self.result()

    def done(self):
        inner = getattr(self._res, "done", None)
        return (
            (inner() if inner is not None else True)
            and self._journal.is_durable(self._seq)
        )

    def add_done_callback(self, fn):
        # Delegated un-gated: quota releases etc. key off the DEVICE
        # resolution; the durability gate applies to the ack (result()).
        self._res.add_done_callback(fn)


class TpuSketchEngine(SketchDurabilityMixin):
    def __init__(self, config):
        from redisson_tpu.executor.coalescer import BatchCoalescer
        from redisson_tpu.serve.metrics import Metrics

        self.config = config
        self._dist_initialized = False
        if config.tpu_sketch.coordinator_address:
            # Multi-host: join the JAX distributed runtime BEFORE any
            # device discovery (docs/MULTIHOST.md) — after this,
            # jax.devices() spans every process's chips and the sharded
            # executor's mesh covers them transparently.  Guarded: a
            # second engine in the process (client restart) must not
            # re-initialize.
            import jax

            already = getattr(jax.distributed, "is_initialized", None)
            if not (already is not None and already()):
                jax.distributed.initialize(
                    config.tpu_sketch.coordinator_address,
                    num_processes=config.tpu_sketch.num_processes,
                    process_id=config.tpu_sketch.process_id,
                )
                self._dist_initialized = True
        if config.tpu_sketch.num_shards > 1:
            from redisson_tpu.executor.sharded_executor import (
                ShardedTpuCommandExecutor,
            )

            self.executor = ShardedTpuCommandExecutor(config)
        else:
            self.executor = TpuCommandExecutor(config)
        self.registry = TenantRegistry(
            self.executor,
            initial_capacity=config.tpu_sketch.initial_tenants_per_class,
            dispatch_lock=self.executor._dispatch_lock,
        )
        self.metrics = Metrics()
        # Labeled observability bundle (obs package): per-tenant/op
        # counters, lifecycle spans, slowlog, health gauges.  Shared by
        # the coalescer, the executor, the client facade, and any RESP
        # server fronting this client.
        from redisson_tpu.obs import Observability

        self.obs = Observability(
            trace_sample_rate=getattr(config, "trace_sample_rate", 0.0),
            trace_max_spans=getattr(config, "trace_max_spans", 2048),
            latency_threshold_ms=getattr(
                config, "latency_monitor_threshold_ms", 0
            ),
        )
        self.executor.obs = self.obs
        # Near cache (ISSUE 4): the epoch-guarded host read tier — hot
        # single-key reads answer from host memory regardless of link
        # phase.  Built even when disabled so the epoch bookkeeping is
        # already coherent when a live `CONFIG SET nearcache yes` lands.
        # Multi-controller lockstep gate (same rule as mailbox_collect):
        # a cache hit SKIPS a device dispatch, and eviction order depends
        # on per-process-randomized hash() sharding — controllers would
        # diverge in which reads dispatch, breaking SPMD program order.
        from redisson_tpu.cache import ShardedLRUStore, SketchNearCache

        import jax

        ncc = config.tpu_sketch
        self.nearcache = SketchNearCache(
            ShardedLRUStore(
                max_bytes=ncc.nearcache_max_bytes,
                nshards=ncc.nearcache_shards,
                tenant_quota_bytes=ncc.nearcache_tenant_quota_bytes,
                on_evict=lambda tenant, nbytes: (
                    self.obs.nearcache_evictions.inc()
                ),
            ),
            obs=self.obs,
            enabled=ncc.nearcache and jax.process_count() == 1,
            max_batch=ncc.nearcache_max_batch,
        )
        if jax.process_count() > 1:
            # Refuse live re-enables too (CONFIG SET nearcache yes):
            # one controller turning it on alone would desync the fleet.
            self.nearcache.locked_off = True
        # Self-healing dispatch (ISSUE 3): per-(shard, opcode) circuit
        # breakers + per-executor health machine.  When a breaker opens,
        # affected sketches fail over to host golden mirrors
        # (objects/degraded.py) and reconcile back on close.
        from redisson_tpu.executor.health import DispatchHealth

        self.health = DispatchHealth(
            failure_threshold=config.tpu_sketch.breaker_failure_threshold,
            open_s=config.tpu_sketch.breaker_open_ms / 1000.0,
        )
        # Per-tenant fair load shedding (ISSUE 7): token-bucket rate
        # limits + in-flight quotas enforced at the submit boundary.
        # Built even when both limits are 0 (inactive) so a live
        # CONFIG SET tenant-rate-limit lands on a running engine.
        from redisson_tpu.tenancy.registry import TenantGovernor

        self.governor = TenantGovernor(
            rate_limit=config.tpu_sketch.tenant_rate_limit,
            burst=config.tpu_sketch.tenant_burst_ops,
            max_inflight=config.tpu_sketch.tenant_max_inflight,
            obs=self.obs,
        )
        self.health.reconcile_cb = self._reconcile_kind
        self.health.obs = self.obs  # LATENCY breaker-open events
        self._mirrors: dict = {}  # name -> degraded-mode OR demoted mirror
        self._mirror_lock = _witness.named(
            threading.RLock(), "engine.mirror"
        )
        # Bumped (under the lock) whenever reconcile writes mirrors back
        # to the device: a seed row read before the bump may predate the
        # write-back and must be discarded (see _degraded).
        self._mirror_epoch = 0
        # Chaos-injection accounting lands in this engine's registry
        # (module-level engine: the most recent engine owns the counter).
        # The closure is remembered so shutdown() can unhook it — a
        # module-global observer would otherwise pin this engine (and
        # its device pools) past shutdown.
        self._chaos_observer = (
            lambda point, kind: self.obs.faults_injected.inc((point, kind))
        )
        _chaos.set_observer(self._chaos_observer)
        self.topk = TopKStore()
        # Wired by the client to the grid store's ``exists`` — one logical
        # keyspace across both backends (WRONGTYPE on cross-backend reuse).
        self.foreign_exists = None
        self.coalescer = None
        if config.tpu_sketch.coalesce:
            import jax

            # Mailbox drains group launches by each controller's OWN
            # completion timing — divergent concat programs across
            # processes would break multi-controller lockstep, same as
            # the periodic snapshotter below.
            self.coalescer = BatchCoalescer(
                batch_window_us=config.tpu_sketch.batch_window_us,
                max_batch=config.tpu_sketch.max_batch,
                metrics=self.metrics,
                max_inflight=config.tpu_sketch.max_inflight,
                retry_attempts=config.retry_attempts,
                retry_interval_s=config.retry_interval_ms / 1000.0,
                max_queued_ops=config.tpu_sketch.max_queued_ops,
                adaptive_inflight=config.tpu_sketch.adaptive_inflight,
                min_inflight=config.tpu_sketch.min_inflight,
                adaptive_window=config.tpu_sketch.adaptive_window,
                min_window_us=config.tpu_sketch.min_window_us,
                max_window_us=config.tpu_sketch.max_window_us,
                group_collect=(
                    self.executor.collect_group
                    if config.tpu_sketch.mailbox_collect
                    and jax.process_count() == 1
                    else None
                ),
                obs=self.obs,
                retry_max_backoff_s=(
                    config.tpu_sketch.retry_max_backoff_ms / 1000.0
                ),
                retry_jitter=config.tpu_sketch.retry_jitter,
                health=self.health,
                max_batch_slow_phase=(
                    config.tpu_sketch.max_batch_slow_phase
                ),
                fetch_timeout_s=(
                    config.tpu_sketch.fetch_timeout_ms / 1000.0
                ),
            )
        else:
            # Direct-dispatch mode: the executor is the only recorder of
            # ops_total/batches_total (with a coalescer in front, the
            # coalescer records them — both would double-count).  Fixes
            # sharded/coalesce=False runs reporting zero ops.
            self.executor.metrics = self.metrics
        # AOT bucket pre-warming (executor/prewarm.py): a background
        # thread compiles the (opcode, bucket) jit ladder on pool attach
        # so serving-path ops never pay a first-touch compile.
        self.prewarmer = None
        self._prewarm_seen: set = set()
        if config.tpu_sketch.prewarm:
            from redisson_tpu.executor.prewarm import BucketPrewarmer

            self.prewarmer = BucketPrewarmer(
                self.executor,
                max_batch=config.tpu_sketch.max_batch,
                max_state_bytes=config.tpu_sketch.prewarm_max_state_bytes,
                obs=self.obs,
            )
        # Crash-safe durability tier (ISSUE 10): append-only op journal
        # + point-in-time recovery (durability/journal.py).  The commit
        # GATE makes one mutation's journal-append + dispatch atomic
        # against the snapshot's drain → cut → capture sequence: without
        # it a record could land before the cut while its device effect
        # lands after the capture — truncated from the journal AND
        # missing from the snapshot (a lost acked write).  A plain RLock
        # (not witness-named) on purpose: it is strictly the OUTERMOST
        # lock of every path that takes it (public mutation entry points
        # and snapshot(), both entered lock-free), so it can never
        # participate in an ordering cycle, and naming it would flag the
        # drains/dispatches the gated bodies legitimately perform.
        # Tiered sketch storage (ISSUE 14): the heat-based residency
        # ladder — device rows are a CACHE over host golden mirrors
        # over disk blobs (storage/residency.py).  Built BEFORE the
        # restore/recovery block below so a snapshot can reinstate
        # HOST/DISK tenants; the alloc gate and the background thread
        # arm AFTER recovery (replay must see the pre-crash tiers, not
        # race a budget enforcer).
        from redisson_tpu.storage import ResidencyManager

        self.residency = ResidencyManager(
            self, config.tpu_sketch, obs=self.obs
        )
        self.journal = None
        self._journal_replaying = False
        self._journal_gate = threading.RLock()
        # Snapshot serialization: SAVE, BGSAVE's thread, the periodic
        # snapshotter, BGREWRITEAOF and shutdown may all call snapshot()
        # concurrently — without one writer at a time, an OLDER capture
        # can overwrite a newer one AFTER the newer one already retired
        # journal segments (mark_snapshot), losing the acked tail; the
        # shared tmp paths would also interleave.  Plain Lock, strictly
        # outermost (ordering: snapshot lock → journal gate → engine
        # locks; no mutation path ever takes it).
        self._snapshot_lock = threading.Lock()
        self._restored_journal_seq = 0
        self._last_save_ts = 0.0
        self._register_health_gauges()
        # Checkpoint/resume (SURVEY.md §5): restore device state from the
        # configured snapshot dir, then recover the journal tail, then
        # arm periodic snapshots (strictly in that order — the
        # snapshotter must never run concurrently with replay).
        if config.snapshot_dir:
            self.restore_snapshot(config.snapshot_dir)
        if getattr(config, "journal_dir", None):
            self._journal_attach(config.journal_dir, recover=True)
        # Residency ladder goes LIVE only after recovery: creates past
        # the device budget now birth HOST-resident, and the
        # maintenance thread starts once a budget is armed.
        self.registry.alloc_gate = self.residency.device_full
        if (
            config.tpu_sketch.residency_device_rows > 0
            or config.tpu_sketch.residency_max_host_bytes > 0
        ):
            self.residency.start()
        if config.snapshot_dir:
            if config.snapshot_interval_s > 0:
                import jax

                if jax.process_count() > 1:
                    # The timer thread fires at independent wall-clock
                    # times per controller, and snapshot() dispatches
                    # device work — that breaks multi-controller lockstep
                    # (docs/MULTIHOST.md "Lockstep discipline").  Explicit
                    # snapshot() calls, issued at the same program point
                    # on every controller, remain supported.
                    import warnings

                    warnings.warn(
                        "periodic snapshots are disabled under multi-host: "
                        "call snapshot() explicitly at a coordinated point "
                        "on every controller (docs/MULTIHOST.md)"
                    )
                else:
                    self._start_snapshotter(
                        config.snapshot_dir, config.snapshot_interval_s
                    )

    def _register_health_gauges(self) -> None:
        """Executor-health gauges, sampled at scrape/snapshot time (ISSUE
        1 tentpole part 4): queue depth, in-flight window, completion
        backlog, tenant/pool occupancy, per-device memory."""
        reg = self.obs.registry
        c = self.coalescer
        if c is not None:
            reg.gauge_callback(
                "rtpu_coalescer_queued_ops",
                "ops queued ahead of the flush thread",
                lambda: c._queued_ops,
            )
            reg.gauge_callback(
                "rtpu_inflight_launches",
                "dispatched-but-uncollected launches",
                lambda: c._uncollected,
            )
            reg.gauge_callback(
                "rtpu_inflight_limit",
                "adaptive (AIMD) in-flight launch window",
                lambda: c._inflight_limit,
            )
            reg.gauge_callback(
                "rtpu_completion_backlog",
                "launches awaiting the completer thread",
                lambda: c._completions.qsize(),
            )
            reg.gauge_callback(
                "rtpu_flush_window_us",
                "live adaptive flush window",
                lambda: c.window_s * 1e6,
            )
            reg.gauge_callback(
                "rtpu_flush_merge_cap",
                "live pop-time merge cap (max_batch, or "
                "max_batch_slow_phase while the link phase is slow)",
                c.merge_cap,
            )
            reg.gauge_callback(
                "rtpu_admission_est_wait_us",
                "last admission-control queue-wait estimate",
                lambda: c.last_est_wait_s * 1e6,
            )
        if self.prewarmer is not None:
            reg.gauge_callback(
                "rtpu_prewarm_pending",
                "bucket warm tasks not yet compiled",
                self.prewarmer.pending,
            )
        # Self-healing dispatch (ISSUE 3): breaker + degradation gauges.
        reg.gauge_callback(
            "rtpu_breaker_state",
            "circuit state by shard/op (0 closed, 1 open, 2 half-open)",
            self.health.board.state_codes,
            labelnames=("shard", "op"),
        )
        reg.gauge_callback(
            "rtpu_degraded_objects",
            "sketches currently serving from the host golden mirror "
            "because a breaker is open (demoted-tier mirrors are NOT "
            "degraded and count in rtpu_residency_host_bytes instead)",
            lambda: max(
                0, len(self._mirrors) - self.residency.host_objects()
            ),
        )
        # Tiered residency (ISSUE 14): fast-tier occupancy + the host/
        # disk tier footprints (SWAPIN/SWAPOUT-style observability; the
        # promotion/demotion/spill/load counters live in the obs
        # bundle).
        reg.gauge_callback(
            "rtpu_residency_device_rows",
            "device rows in use across all sketch pools (the residency "
            "ladder's fast tier; compare residency_device_rows budget)",
            self.residency.device_rows_used,
        )
        reg.gauge_callback(
            "rtpu_residency_host_bytes",
            "host bytes held by demoted-tier golden mirrors",
            self.residency.host_bytes,
        )
        reg.gauge_callback(
            "rtpu_residency_disk_bytes",
            "bytes held by spilled per-object disk blobs",
            self.residency.disk_bytes,
        )
        # Near cache (ISSUE 4): live occupancy (hits/misses/evictions
        # are counters registered by the obs bundle itself).
        reg.gauge_callback(
            "rtpu_nearcache_bytes",
            "host bytes resident in the sketch near cache",
            self.nearcache.store.bytes,
        )
        reg.gauge_callback(
            "rtpu_nearcache_entries",
            "entries resident in the sketch near cache",
            self.nearcache.store.entries,
        )
        # Durability tier (ISSUE 10): journal lag + segment count.
        # Registered unconditionally (0 while journaling is off) so a
        # live CONFIG SET appendonly yes is visible without re-wiring.
        reg.gauge_callback(
            "rtpu_journal_lag_ops",
            "journal records appended but not yet fsynced",
            lambda: (
                0 if self.journal is None else self.journal.lag_ops()
            ),
        )
        reg.gauge_callback(
            "rtpu_journal_segments",
            "live journal segment files",
            lambda: (
                0 if self.journal is None
                else self.journal.stats()["segments"]
            ),
        )

        # One registry.stats() snapshot serves BOTH gauges per scrape:
        # stats() holds the tenancy lock (contended by the serving
        # path's try_create/lookup) while building the full dict, so the
        # short-TTL memo halves the scrape-time lock hold.
        import time as _time

        stats_memo = {"t": -1.0, "v": None}

        def _stats():
            now = _time.monotonic()
            if stats_memo["v"] is None or now - stats_memo["t"] > 0.2:
                stats_memo["v"] = self.registry.stats()
                stats_memo["t"] = now
            return stats_memo["v"]

        def _tenant_counts():
            return {
                (k,): v for k, v in _stats()["tenants_by_kind"].items()
            }

        def _pool_rows():
            out = {}
            for key, st in _stats()["pools"].items():
                kind = key[0]
                cls = "x".join(str(x) for x in key[1:]) or "-"
                out[(kind, cls, "used")] = st["used_rows"]
                out[(kind, cls, "capacity")] = st["capacity"]
            return out

        def _devmem():
            from redisson_tpu.serve.metrics import Profiler

            out = {}
            for dev, stats in Profiler.device_memory().items():
                for stat, v in (stats or {}).items():
                    if v is not None:
                        out[(dev, stat)] = v
            return out

        reg.gauge_callback(
            "rtpu_tenants", "registered sketch tenants by kind",
            _tenant_counts, labelnames=("kind",),
        )
        reg.gauge_callback(
            "rtpu_pool_rows", "size-class pool rows by kind/class/state",
            _pool_rows, labelnames=("kind", "class", "state"),
        )
        reg.gauge_callback(
            "rtpu_device_memory_bytes", "per-device memory stats",
            _devmem, labelnames=("device", "stat"),
        )

    def shutdown(self) -> None:
        _chaos.unset_observer(self._chaos_observer)
        self.health.shutdown()
        self.residency.shutdown()
        self._stop_snapshotter()
        self._stop_sweeper()
        if self.config.snapshot_dir:
            try:
                self.snapshot(self.config.snapshot_dir)
            except Exception:  # pragma: no cover — best-effort persistence
                pass
        # Journal close AFTER the final snapshot (which cut+retired the
        # covered segments): drain pending records + final fsync, so a
        # clean shutdown leaves a zero-replay journal.
        j = self.journal
        if j is not None:
            self.journal = None
            if self.coalescer is not None:
                self.coalescer.journal_lag_s = None
            try:
                j.close()
            except Exception:  # pragma: no cover — best-effort persistence
                pass
        if self.prewarmer is not None:
            self.prewarmer.shutdown()
        if self.coalescer is not None:
            self.coalescer.shutdown()
        if self._dist_initialized:  # pair with jax.distributed.initialize
            import jax

            try:
                jax.distributed.shutdown()
            except Exception:  # pragma: no cover — runtime already gone
                pass
            self._dist_initialized = False

    def _drain(self) -> None:
        """Direct state reads must observe all queued coalesced ops."""
        if self.coalescer is not None:
            self.coalescer.drain()

    def _nc_mutate(self, name: str, structural: bool = False):
        """Near-cache write discipline for a mutating op on ``name``:
        bump the write epoch at entry AND exit (structural ops bump the
        structural epoch too — they retire monotone positives).  Every
        path that can change the object's readable state must cross this
        (or drop_object/invalidate_all) — mirror-degraded, replicated,
        and sharded writes included, which it gets for free by wrapping
        the ENGINE entry points those paths all flow through."""
        nc = self.nearcache
        return _EpochGuard(
            nc.note_structural if structural else nc.note_write, name
        )

    def prewarm_wait(self, timeout=None) -> bool:
        """Block until the AOT bucket pre-warmer has compiled every
        scheduled ladder (True on drained; trivially True when pre-warm
        is off)."""
        if self.prewarmer is None:
            return True
        return self.prewarmer.wait_idle(timeout)

    # -- crash-safe durability tier (ISSUE 10): op journal -----------------

    def _journal_attach(self, jdir: str, recover: bool,
                        fresh: bool = False) -> None:
        """Open (and optionally recover) the op journal.  ``recover``
        replays the post-snapshot tail through the host golden engine
        into device rows (durability/recovery.py); ``fresh`` wipes any
        existing segments first (the live-enable path: pre-enable state
        is covered by the coordinating snapshot, stale segments from an
        earlier lineage must not replay on the next boot)."""
        from redisson_tpu.durability import OpJournal, replay_journal

        cfg = self.config
        j = OpJournal(
            jdir,
            fsync_policy=getattr(cfg, "journal_fsync", "everysec"),
            max_segment_bytes=getattr(
                cfg, "journal_max_segment_bytes", 64 << 20
            ),
            obs=self.obs,
            fresh=fresh,
        )
        if recover:
            n = replay_journal(self, j, self._restored_journal_seq)
            if n:
                self.obs.journal_replayed.inc((), n)
        self.journal = j
        if self.coalescer is not None:
            # Journal lag rides the admission estimate under ``always``
            # (a slow disk sheds deadline-carrying load instead of
            # queueing it unboundedly) — see coalescer.estimate_wait_s.
            self.coalescer.journal_lag_s = j.lag_s

    def journal_set_enabled(self, enabled: bool) -> None:
        """Live ``CONFIG SET appendonly yes|no``.  Enabling starts a
        FRESH journal lineage and, when a snapshot dir is configured,
        takes a coordinating snapshot so recovery = snapshot + tail
        (the Redis enable-appendonly-triggers-rewrite behavior);
        without one, only post-enable mutations are recoverable.
        Disabling closes the journal after a final drain+fsync."""
        if enabled:
            jdir = getattr(self.config, "journal_dir", None)
            if not jdir:
                raise ValueError(
                    "journal_dir is not configured (set Config.journal_dir "
                    "before enabling appendonly)"
                )
            with self._journal_gate:
                # Idempotency re-checked INSIDE the gate: two racing
                # enables must not both attach — the loser's fresh=True
                # wipe would orphan the winner's live segments and leak
                # a second writer on the same directory.
                if self.journal is not None:
                    return
                self._journal_attach(jdir, recover=False, fresh=True)
            if self.config.snapshot_dir:
                self.snapshot(self.config.snapshot_dir)
        else:
            with self._journal_gate:
                j, self.journal = self.journal, None
                if self.coalescer is not None:
                    self.coalescer.journal_lag_s = None
            if j is not None:
                j.close()

    def journal_set_policy(self, policy: str) -> None:
        """Live ``CONFIG SET appendfsync always|everysec|no``."""
        self.config.journal_fsync = policy
        j = self.journal
        if j is not None:
            j.set_policy(policy)

    def journal_fence(self, timeout=None) -> bool:
        """The WAIT fence: force an fsync covering every record appended
        so far and block until it lands (True; False on timeout).
        Trivially True with journaling off."""
        j = self.journal
        if j is None:
            return True
        return j.wait_durable(timeout=timeout)

    def _journal_rec(self, op: str, name: str, **fields) -> Optional[int]:
        """Append one ACCEPTED-mutation record; returns its seq, or None
        when journaling is off (or this is recovery replay — a recovery
        must never journal its own replay)."""
        j = self.journal
        if j is None or self._journal_replaying:
            return None
        rec = {"op": op, "name": name}
        rec.update(fields)
        return j.append(rec)

    def _durable(self, res, seq: Optional[int]):
        """Gate a result-like's ack on record durability under
        ``appendfsync=always`` (no-op under the other policies: their
        durability window is the fsync cadence, not the ack)."""
        j = self.journal
        if seq is None or j is None or j.policy != "always":
            return res
        return _DurableResult(res, j, seq)

    def _ack(self, value, seq: Optional[int]):
        """Durability fence for synchronously-returning mutations
        (delete/rename/expire/merge/...): under ``always`` the method
        returns — acks — only after its record is fsynced."""
        j = self.journal
        if seq is not None and j is not None and j.policy == "always":
            j.wait_durable(seq)
        return value

    def _commit(self, res, op: str, name: str, **fields):
        """Journal an accepted mutation and gate its ack: the one-call
        form for result-returning engine methods."""
        return self._durable(res, self._journal_rec(op, name, **fields))

    # -- graceful degradation (ISSUE 3): host golden-mirror failover -------

    def _degraded(self, entry) -> bool:
        """True when ``entry`` must serve from its host mirror.  Healthy
        fast path is two attribute reads and a branch — no lock, no dict
        probe — until the first breaker ever opens.

        Seeding a missing mirror runs OUTSIDE the mirror lock: the seed's
        drain barrier can wait out parked-segment backoffs and its
        read_row retries traverse the failing dispatch path (seconds),
        and every degraded op of every kind serializes on the one mirror
        lock — seeding under it turned a single-op-path failure into an
        engine-wide stall.  The install re-checks under the lock: a
        racing seeder's mirror wins, a reconcile that cleared the kind
        mid-seed routes back to the device, and a reconcile that WROTE
        mirrors back mid-seed (epoch bump) discards the possibly-stale
        row and retries — installing it would resurrect pre-reconcile
        state and lose acked writes on the next write-back.

        Residency ladder (ISSUE 14): the same boundary serves DEMOTED
        sketches — a HOST-resident entry's mirror answers here (no
        breaker, no degraded flag), a DISK-resident or born-cold entry
        loads its mirror first.  The membership probe is lock-free
        (dict probe, GIL-atomic): a stale True is re-checked by
        _mirror_call under the lock, and a promote racing a stale
        False repoints entry.row to a fully-written device row BEFORE
        dropping the mirror."""
        if entry.row < 0 and entry.name not in self._mirrors:
            self._ensure_resident(entry)
        if entry.name in self._mirrors:
            return True
        if not self.health.any_degraded:
            return False
        for _ in range(4):
            with self._mirror_lock:
                if entry.name in self._mirrors:
                    return True
                if not self.health.degraded_kind(entry.kind):
                    return False
                epoch = self._mirror_epoch
            row = self._seed_row(entry)
            with self._mirror_lock:
                if entry.name in self._mirrors:
                    return True
                if not self.health.degraded_kind(entry.kind):
                    return False
                if self._mirror_epoch != epoch:
                    continue  # reconciled mid-seed: row may be stale
                if row is None:
                    return False
                self._install_mirror(entry, row)
                return True
        return False  # flapping hard: let the device surface the failure

    def _ensure_resident(self, entry) -> None:
        """Row-less entry (DISK-resident, or born cold past the device
        budget): install its HOST mirror — from the CRC-checked blob,
        or from zeros for a never-touched tenant.  A corrupt/missing
        blob raises (the op fails typed; serving garbage state is the
        one thing a tier must never do)."""
        self.residency.load(entry.name)

    def _tier_row(self, entry, row0: int) -> int:
        """Resolve the device row for a READ dispatch that captured
        ``row0`` BEFORE its residency check and then got no mirror
        result.  Readers do not hold the journal gate, so a transition
        can interleave with their check→dispatch window:

        - a PROMOTE racing the check leaves row0 at -1 while entry.row
          is already live (promote repoints the row before dropping
          the mirror) — re-read it;
        - a DEMOTE racing it leaves row0 pointing at the QUARANTINED
          row, whose contents stay bit-identical to the pre-demotion
          state until a later maintenance cycle's post-drain reclaim —
          dispatching against it is linearizable (the read began
          before the demotion completed).

        Every read site must capture entry.row before its
        _serve_degraded/_degraded check and resolve through this
        helper — reading entry.row AFTER the check races the demote's
        row retirement."""
        return entry.row if row0 < 0 else row0

    def _install_residency_mirror(self, entry, row=None, mirror=None):
        """Install ``entry`` as HOST-resident from a row array or a
        ready-made mirror — the snapshot-restore / journal-writeback
        install path (engine init, or under the journal gate).
        Delegates to the residency manager, which owns the mirror
        install + host-bytes accounting in one place."""
        self.residency.install_host(entry, row=row, mirror=mirror)

    def _seed_row(self, entry):
        """Fetch the entry's device row for mirror seeding (no lock
        held).  Seeding itself needs a working read dispatch; under a
        partial fault schedule a few retries ride it out — if the device
        is truly unreachable, returns None and the op proceeds to the
        device (surfacing the typed failure instead of silently serving
        empty state)."""
        try:
            self._drain()
        except Exception:
            pass  # queued segments fail typed on their own futures
        for _ in range(4):
            try:
                return self.executor.read_row(entry.pool, entry.row)
            except Exception:
                continue
        return None

    def _install_mirror(self, entry, row):
        """Install ``entry``'s mirror from ``row`` (under the mirror
        lock) and register the kind's recovery probe: a real read
        dispatch against the degraded pool (exercises the full _locked
        path, chaos points included), driven by the health monitor while
        the breaker is open."""
        from redisson_tpu.objects.degraded import mirror_for_entry

        self._mirrors[entry.name] = mirror_for_entry(entry, row)
        pool, prow = entry.pool, entry.row
        self.health.ensure_probe(
            entry.kind,
            lambda: self.executor.read_row(pool, prow),
        )

    def _mirror_call(self, entry, nops: int, fn):
        """Apply a degraded-mode or demoted-tier op to the entry's
        mirror (serialized by the mirror lock) and account it; returns
        an ImmediateResult.  Demoted is NOT degraded: a residency
        mirror's serves count to the host tier, never to
        rtpu_degraded_ops."""
        with self._mirror_lock:
            mirror = self._mirrors.get(entry.name)
            if mirror is None:  # reconciled/promoted between check+apply
                return None
            out = fn(mirror)
            demoted = getattr(mirror, "residency", None) is not None
            if demoted:
                # Under the mirror lock: += is a read-modify-write and
                # every demoted serve already holds this lock.
                self.residency.host_serves += nops
        if not demoted:
            self.obs.degraded_ops.inc((entry.kind,), nops)
        return ImmediateResult(out)

    def _serve_degraded(self, entry, nops: int, fn):
        """The failover boundary every engine method crosses: the
        mirror's ImmediateResult when ``entry`` serves degraded, else
        None (the op proceeds to the device).  One helper, so a missing
        failover is a greppable hole, not a silent one — every method
        that touches ``entry``'s row must call this (or _host_row) first
        or acked state diverges from what reconcile writes back."""
        if self._degraded(entry):
            return self._mirror_call(entry, nops, fn)
        return None

    def _host_row(self, entry) -> np.ndarray:
        """``entry``'s current truth in device-row layout: its mirror's
        encoding while one is live (the device row is stale during
        degradation), else the device row itself.  Serves merge sources
        and DUMP during degradation (and the demoted/spilled tiers —
        a DISK-resident entry loads its mirror first)."""
        row0 = entry.row  # BEFORE the residency check (see _tier_row)
        if row0 < 0 and entry.name not in self._mirrors:
            self._ensure_resident(entry)
        if self._mirrors:
            with self._mirror_lock:
                mirror = self._mirrors.get(entry.name)
                if mirror is not None:
                    return np.asarray(mirror.encode(entry.pool.row_units))
        self._drain()
        return np.asarray(
            self.executor.read_row(entry.pool, self._tier_row(entry, row0))
        )

    def _reconcile_kind(self, kind: str) -> bool:
        """Breaker-close hook (health.reconcile_cb): write every mirrored
        row of ``kind`` back to the device, then drop the mirrors — the
        device resumes from exactly the state the mirror served.  False
        (stay degraded, breaker re-opens) if any write fails."""
        t0 = time.monotonic()
        try:
            return self._reconcile_kind_inner(kind)
        finally:
            # LATENCY "reconcile" event (ISSUE 13): the write-back stall
            # every op of this kind rode out, visible next to
            # fsync-stall/breaker-open in LATENCY LATEST.
            lat = self.obs.latency
            if lat.threshold_ms > 0:
                lat.record(
                    "reconcile", (time.monotonic() - t0) * 1e3
                )

    def _reconcile_kind_inner(self, kind: str) -> bool:
        with self._mirror_lock:
            # Residency mirrors (ISSUE 14) are NOT breaker state: a
            # demoted sketch has no device row to write back to, and
            # its mirror stays the truth after the breaker closes.
            names = [
                n for n, m in self._mirrors.items()
                if m.kind == kind
                and getattr(m, "residency", None) is None
            ]
            for n in names:
                mirror = self._mirrors[n]
                entry = self.registry.lookup(n)
                if entry is None:  # deleted while degraded
                    del self._mirrors[n]
                    continue
                # Size to the entry's CURRENT pool: a degraded-window
                # bitset grow may have migrated it to a larger class.
                row = mirror.encode(entry.pool.row_units)
                try:
                    for r in self._entry_rows(entry):
                        # rtpulint: disable=RT001 write-back MUST hold the mirror lock: a degraded op interleaving between write-back and mirror drop would apply to a mirror about to be discarded (lost acked write); the degraded flag clears atomically with the mirrors below
                        self.executor.write_row(entry.pool, r, row)
                except Exception:
                    return False
                del self._mirrors[n]
            # Device rows changed under any in-flight seeder: its row
            # snapshot may predate the write-backs above (see _degraded).
            self._mirror_epoch += 1
            # Still under the mirror lock: drop the degraded flag
            # atomically with the mirrors, so no serving thread can see
            # "kind degraded, mirror missing" and seed an orphan mirror
            # that outlives the recovery (permanent split-brain).
            self.health.clear_degraded(kind)
        return True

    def _submit(self, key, dispatch, arrays, nops, pool_key=None, meta=None,
                tenant=None):
        from redisson_tpu.executor.coalescer import HintedFuture, _op_label

        # ``tenant`` rides the segment as an appended (tenant, nops)
        # tuple; the coalescer's COMPLETER thread turns it into the
        # per-tenant counters, so this producer path pays no counter
        # lock (the ≤10% submit-overhead guard in test_observability.py).
        #
        # Overload control plane (ISSUE 7): the ambient deadline (RESP
        # ingress stamp or client.op_deadline scope) rides the op into
        # the coalescer — admission control + queue shedding there, the
        # residual budget on the returned future's .result().  The
        # tenant governor sheds over-quota tenants HERE, before the op
        # can cost anyone else queue wait.
        deadline = _ovl.current_deadline()
        gov = self.governor
        governed = (
            gov is not None and tenant is not None and gov.active
        )
        if governed:
            gov.admit(tenant, nops)  # raises TenantThrottledError
        try:
            fut = self.coalescer.submit(
                key, dispatch, arrays, nops, pool_key=pool_key, meta=meta,
                tenant=tenant, deadline=deadline,
            )
        except BaseException:
            if governed:
                gov.release(tenant, nops)
            raise
        if governed and gov.max_inflight > 0:
            fut.add_done_callback(lambda _f: gov.release(tenant, nops))
        return HintedFuture(
            fut, self.coalescer, deadline=deadline, op=_op_label(key),
            nops=nops,
        )

    def _prewarm_keyed(self, pool, k: int, L: int, blocks, lengths) -> None:
        """Register device-hash warm ladders for an observed codec
        signature (lane count L + trim depth Lt + const-length flag are
        jit-key components only real key bytes reveal).  Called once per
        coarse (pool, k, L) signature — the caller's seen-set gate keeps
        the trim/const scans below off the per-submit hot path."""
        from redisson_tpu.executor import prewarm

        Lt = self.executor._trim_lanes(blocks)[0].shape[1]
        const = lengths.ndim == 0 or bool(np.all(lengths == lengths[0]))
        if getattr(self.executor, "supports_runs_metadata", False):
            self.prewarmer.register(
                pool, ("bloom_mixkr", k, L, Lt, const),
                prewarm.warm_bloom_mixed_keys_runs(k, L, Lt, const),
            )
        self.prewarmer.register(
            pool, ("bloom_mixk", k, L, Lt),
            prewarm.warm_bloom_mixed_keys(k, L, Lt),
        )

    # -- generic -----------------------------------------------------------

    def exists(self, name: str) -> bool:
        return self._live_lookup(name) is not None

    def delete(self, name: str) -> bool:
        import time as _time

        # detach-then-zero-then-free: only one concurrent deleter (user
        # call, expiry sweeper, or lazy-expiry reader) wins the pop, and
        # the row is reusable only after it is zeroed — a stale deleter
        # can never zero a row already reallocated to a new object.
        # Epoch BEFORE detach: a change_topology completing between
        # detach and the epoch read would return this entry's rows to the
        # rebuilt free list AND bump the epoch — reading the bumped value
        # would defeat _reap_rows' stale-topology guard and double-free.
        with self._journal_gate:
            pre_pool = self.registry.lookup(name)
            pre_epoch = pre_pool.pool.topology_epoch if pre_pool else 0
            entry = self.registry.detach(name)
            if entry is None:
                return False
            seq = self._journal_rec("obj.del", name)
            # An expired-but-unswept entry is already logically gone: free
            # the row, but report False (Redis DEL on an expired key).
            # Checked inline — _live_lookup would recurse through
            # _expire_if_due.
            was_expired = (
                entry.expire_at is not None
                and _time.time() >= entry.expire_at
            )
            epoch = pre_epoch if pre_pool and pre_pool.pool is entry.pool \
                else entry.pool.topology_epoch
            self._drain()
            self._reap_rows(entry.pool, self._entry_rows(entry), epoch)
            self.topk.drop(name)
            # Structural epoch advance + entry drop: a successor object
            # under this name continues the epoch sequence, so an
            # in-flight read of the OLD object can never install as fresh.
            self.nearcache.drop_object(name)
            if self._mirrors:
                with self._mirror_lock:
                    self._mirrors.pop(name, None)
            # Residency state dies with the object: heat, host-bytes
            # accounting, and the disk blob (retired into blob GC).
            self.residency.drop(name)
            result = not was_expired
        # Durability fence OUTSIDE the gate: blocking on the fsync while
        # holding it would serialize every writer behind one barrier
        # (group commit amortizes exactly because waiters overlap).
        return self._ack(result, seq)

    def rename(self, old: str, new: str) -> bool:
        with self._journal_gate:
            if old == new or self._live_lookup(old) is None:
                return False
            self._guard_foreign(new)
            self._drain()
            # Atomic rename FIRST: if the source vanished since the check
            # (expiry race), the destination must be left untouched.  The
            # displaced dest is zeroed before its row becomes reusable.
            ok, dest = self.registry.rename_detach_dest(old, new)
            if not ok:
                return False
            seq = self._journal_rec("obj.rename", old, new=new)
            if dest is not None:
                self._reap_rows(
                    dest.pool, self._entry_rows(dest),
                    dest.pool.topology_epoch,
                )
            self.topk.rename(old, new)
            # Both names change identity: drop entries + structural bumps.
            self.nearcache.drop_object(old)
            self.nearcache.drop_object(new)
            if self._mirrors:
                with self._mirror_lock:
                    self._mirrors.pop(new, None)
                    m = self._mirrors.pop(old, None)
                    if m is not None:
                        self._mirrors[new] = m
            # Residency state follows the rename (heat, host-bytes,
            # disk-blob index; the displaced dest's blob retires).
            self.residency.rename(old, new)
        return self._ack(True, seq)  # fence outside the gate (see delete)

    def names(self, kind=None):
        for e in self.registry.entries():
            if e.expire_at is not None:
                self._expire_if_due(e)
        return self.registry.names(kind)

    def params(self, name: str) -> Optional[dict]:
        entry = self._live_lookup(name)
        return None if entry is None else entry.params

    def _require(self, name: str, kind: str):
        entry = self._lookup_kind(name, kind)
        if entry is None:
            raise RuntimeError(f"{kind} object {name!r} is not initialized")
        # Per-tenant call counter: covers every op path (coalesced or
        # direct) at one inc per API call.
        self.obs.tenant_calls.inc((name, kind))
        return entry

    def _lookup_kind(self, name: str, kind: str):
        """None if absent/expired; TypeError (WRONGTYPE analog) on kind
        mismatch."""
        entry = self._live_lookup(name)
        if entry is not None and entry.kind != kind:
            raise TypeError(f"object {name!r} holds a {entry.kind}, not a {kind}")
        if entry is not None:
            # Residency heat feed (ISSUE 14): every read and write path
            # resolves its entry here (or via the ensure paths, which
            # also touch) — one decayed-counter bump per API call, the
            # same choke points the near-cache epoch hooks mark.
            self.residency.touch(name)
        return entry

    def _guard_foreign(self, name: str) -> None:
        """Cross-backend WRONGTYPE: creating a sketch under a name the data
        grid holds is an error, not a shadow object.  ``foreign_exists``
        is the grid's lock-free probe (see client.py wiring)."""
        if (
            self.foreign_exists is not None
            and self.registry.lookup(name) is None
            and self.foreign_exists(name)
        ):
            raise TypeError(
                f"object {name!r} is held by the data grid (WRONGTYPE)"
            )

    def probe(self, name: str) -> bool:
        """Lock-free-ish existence probe for the grid's guard: takes only
        the registry's leaf lock, never engine/store locks, and never
        mutates (no expiry reap)."""
        import time as _time

        entry = self.registry.lookup(name)
        return entry is not None and (
            entry.expire_at is None or _time.time() < entry.expire_at
        )

    # -- bloom read replication (SURVEY §2.4 replication row / the
    # ReadMode.SLAVE analog): a hot tenant's row copies to every shard;
    # reads spread round-robin across copies, writes broadcast to all ----

    def bloom_replicate(self, name: str) -> bool:
        """Replicate a bloom filter's row to every mesh shard.  No-op
        (False) on the single-device executor — there is nothing to
        spread reads across.

        Ordering vs concurrent writers (bloom bits only ever turn ON, so
        OR-merge makes this safe): the replica rows are published FIRST
        (new writers broadcast from then on, landing bits in the fresh
        rows), THEN queued primary-only writes drain, THEN the primary is
        OR-merged into each replica — a broadcast bit is never erased and
        a drained primary bit always reaches every copy.  The drain+merge
        runs twice, closing writers that captured the pre-publish state
        but had not yet submitted at the first drain."""
        S = getattr(self.executor, "S", 1)
        if S <= 1:
            return False
        entry = self._lookup_kind(name, PoolKind.BLOOM)
        if entry is None:
            raise RuntimeError(f"bloom filter {name!r} is not initialized")
        if entry.row < 0:
            # Replication spreads DEVICE rows across shards; promote
            # the demoted/spilled filter back to the fast tier first.
            if not self.residency.promote(name):
                raise RuntimeError(
                    f"bloom filter {name!r} could not promote to the "
                    f"device tier for replication"
                )
        # Topology change for this object's reads: defensively retire
        # every cached entry (structural bump) while replicas publish.
        self.nearcache.note_structural(name)
        with self.registry._lock:
            if entry.replica_rows:
                return True
            replicas = [None] * S
            replicas[entry.row % S] = entry.row
            for s in range(S):
                if replicas[s] is None:
                    replicas[s] = entry.pool.alloc_row_with_residue(s, S)
            entry.replica_rows = replicas  # published: writers broadcast now
        for _ in range(2):
            self._drain()
            for r in replicas:
                if r != entry.row:
                    # replica |= primary (device-side, serialized with all
                    # dispatches by the executor lock; rows are uint32
                    # bitmaps, so the bitset OR kernel applies verbatim).
                    self.executor.bitset_bitop(
                        entry.pool, r, [r, entry.row], "or"
                    )
        return True

    def bloom_is_replicated(self, name: str) -> bool:
        entry = self._lookup_kind(name, PoolKind.BLOOM)
        return bool(entry is not None and entry.replica_rows)

    def _bloom_expand_ops(self, entry, B: int, is_add):
        """(rows[B'], expand_idx[B'], primary_pos[B]) for a replicated
        entry: writes fan out to every replica (results identical on all
        copies — every write reaches every copy, so any one stands in);
        reads rotate across replicas.  ``expand_idx`` maps each expanded
        op back to its source op (for gathering the other columns)."""
        replicas = np.asarray(entry.replica_rows, np.int32)
        S = len(replicas)
        base = getattr(self, "_rr_counter", 0)
        self._rr_counter = base + B  # benign race: balance, not correctness
        is_add = np.asarray(is_add, bool)
        # Vectorized expansion (this is the dispatch hot path): each add
        # becomes S consecutive slots (replica 0..S-1), each read one slot.
        counts = np.where(is_add, S, 1)
        primary_pos = np.zeros(B, np.int64)
        np.cumsum(counts[:-1], out=primary_pos[1:])
        expand_idx = np.repeat(np.arange(B, dtype=np.int64), counts)
        ranks = np.arange(len(expand_idx), dtype=np.int64) - primary_pos[expand_idx]
        rows = np.where(
            is_add[expand_idx],
            replicas[ranks % S],
            replicas[(base + expand_idx) % S],
        ).astype(np.int32)
        return rows, expand_idx, primary_pos

    # -- bloom -------------------------------------------------------------

    def bloom_try_init(self, name, expected_insertions, false_probability) -> bool:
        m = golden.optimal_num_of_bits(
            expected_insertions, false_probability,
            max_bits=getattr(self.config.tpu_sketch, "max_bloom_bits",
                             golden.MAX_BLOOM_BITS),
        )
        k = golden.optimal_num_of_hash_functions(expected_insertions, m)
        params = {
            "size": m,
            "hash_iterations": k,
            "expected_insertions": expected_insertions,
            "false_probability": false_probability,
        }
        with self._journal_gate:
            self._live_lookup(name)  # reap an expired holder before tryInit
            self._guard_foreign(name)
            entry, created = self.registry.try_create(
                name, PoolKind.BLOOM, (class_words_for_bits(m),), params
            )
            # Journaled only when the create WON (replay of a lost race
            # must not re-parameterize the incumbent).
            seq = self._journal_rec(
                "bloom.init", name,
                ei=int(expected_insertions), fp=float(false_probability),
            ) if created else None
        if self.prewarmer is not None:
            from redisson_tpu.executor import prewarm

            # Pool attach → compile the hashed mixed-kernel ladder in the
            # background (the keyed/device-hash ladders register on first
            # sight of a codec signature, _bloom_submit_mixed_keys).
            self.prewarmer.register(
                entry.pool, ("bloom_mixed", k), prewarm.warm_bloom_mixed(k)
            )
        return self._ack(created, seq)

    def _bloom_reduce(self, entry, H1, H2):
        m = entry.params["size"]
        return hashing.km_reduce_mod(H1, H2, m)

    def _replication_fence(self, entry, saw_replicas, redispatch) -> None:
        """Close the writer-vs-set_replicated race: a writer that read
        ``replica_rows`` as unset and SUBMITTED before the publish is
        reached by bloom_replicate's drain+merge; a writer whose submit
        lands after the merge re-checks here (post-submit) and, seeing
        the publish, re-dispatches the same ops as a broadcast.  Bloom
        bits only turn ON, so the redundant re-write is idempotent and
        the original future's results stay valid."""
        if not saw_replicas and entry.replica_rows:
            # The primary write already applied: this broadcast
            # COMPLETES an acked write, so it must never shed on the
            # caller's deadline (neither the direct _locked shed nor
            # the coalescer's submit/queue shed) — a shed here leaves
            # replicas diverged from the primary and rotating reads
            # flapping.  The explicit None frame shadows any ambient
            # deadline for exactly this redispatch.
            with _ovl.deadline_scope(None):
                redispatch()

    def _bloom_dispatch_hashed(self, entry, h1m, h2m, is_add) -> LazyResult:
        """One mixed-kernel dispatch for hashed ops, honoring replication:
        replicated entries expand (writes fan to every copy, reads rotate)
        and results gather back to per-source-op shape."""
        m, k = entry.params["size"], entry.params["hash_iterations"]
        B = len(h1m)
        is_add = np.asarray(is_add, bool)
        row0 = entry.row  # BEFORE the residency check (see _tier_row)
        res = self._serve_degraded(
            entry, B, lambda mir: mir.mixed(h1m, h2m, is_add)
        )
        if res is not None:
            return res
        orig = (h1m, h2m, is_add)
        saw_replicas = bool(entry.replica_rows)
        if saw_replicas:
            rows, eidx, ppos = self._bloom_expand_ops(entry, B, is_add)
            h1m, h2m, is_add = h1m[eidx], h2m[eidx], is_add[eidx]
            gather = lambda v: v[ppos]  # noqa: E731
        else:
            rows = np.full(B, self._tier_row(entry, row0), np.int32)
            gather = None
        m_arr = np.full(len(rows), m, np.uint32)
        pool = entry.pool
        if self.coalescer is not None:
            # Adds and contains share ONE segment per (pool, k) — the
            # combined kernel keeps exact arrival-order semantics while
            # mixed traffic coalesces instead of fragmenting (config 4).
            fut = self._submit(
                ("bloom_mix", id(pool), k),
                lambda cols: self.executor.bloom_mixed(
                    pool, cols[0], cols[1], k, cols[2], cols[3], cols[4]
                ),
                (rows, m_arr, h1m, h2m, is_add),
                len(rows),
                pool_key=id(pool),
                tenant=entry.name,
            )
        else:
            fut = self.executor.bloom_mixed(
                pool, rows, m_arr, k, h1m, h2m, is_add
            )
        if bool(np.any(orig[2])):
            self._replication_fence(
                entry,
                saw_replicas,
                lambda: self._bloom_dispatch_hashed(entry, *orig),
            )
        return fut if gather is None else _MappedFuture(fut, gather)

    def bloom_add(self, name, H1, H2) -> LazyResult:
        with self._nc_mutate(name), self._journal_gate:
            entry = self._require(name, PoolKind.BLOOM)
            h1m, h2m = self._bloom_reduce(entry, H1, H2)
            m, k = entry.params["size"], entry.params["hash_iterations"]
            if (
                not self.config.tpu_sketch.exact_add_semantics
                and not entry.replica_rows
                # Degraded: route through the hashed path's mirror
                # failover instead of hitting the dead device via the
                # fast-add st dispatch.
                and not self._degraded(entry)
            ):
                # Fast single-tenant bulk path dispatches immediately —
                # but only after queued coalesced ops flush, so a
                # contains submitted *before* this add can never observe
                # its writes (arrival-order contract of the coalescer
                # docstring).
                self._drain()
                res = self.executor.bloom_add_fast_st(
                    entry.pool, entry.row, m, k, h1m, h2m
                )
                self._replication_fence(
                    entry,
                    False,
                    lambda: self._bloom_dispatch_hashed(
                        entry, h1m, h2m, np.ones(len(H1), bool)
                    ),
                )
            else:
                res = self._bloom_dispatch_hashed(
                    entry, h1m, h2m, np.ones(len(H1), bool)
                )
            # Journaled PRE-reduce (raw twins): replay re-reduces against
            # the entry's params, same as the live path.
            return self._commit(
                res, "bloom.add", name,
                h1=np.asarray(H1), h2=np.asarray(H2),
            )

    def bloom_contains(self, name, H1, H2) -> LazyResult:
        # Epoch capture BEFORE entry resolution: a delete racing the
        # lookup bumps epochs in between, and a late capture would tag
        # the old object's results as fresh for its successor.
        nc = self.nearcache
        captured = nc.epochs(name)
        entry = self._require(name, PoolKind.BLOOM)
        if nc.active(len(H1)):
            H1a, H2a = np.asarray(H1), np.asarray(H2)
            return nc.lookup_batch(
                "bloom", name, nc.hashed_keys(H1a, H2a), np.bool_,
                lambda idx: self._bloom_contains_dispatch(
                    entry,
                    H1a if idx is None else H1a[idx],
                    H2a if idx is None else H2a[idx],
                ),
                monotone=True, captured=captured,
            )
        return self._bloom_contains_dispatch(entry, H1, H2)

    def _bloom_contains_dispatch(self, entry, H1, H2) -> LazyResult:
        h1m, h2m = self._bloom_reduce(entry, H1, H2)
        m, k = entry.params["size"], entry.params["hash_iterations"]
        row0 = entry.row  # BEFORE the residency check (see _tier_row)
        if (
            self.coalescer is not None
            or entry.replica_rows
            or self._degraded(entry)  # hashed path serves the mirror
        ):
            return self._bloom_dispatch_hashed(
                entry, h1m, h2m, np.zeros(len(H1), bool)
            )
        return self.executor.bloom_contains_st(
            entry.pool, self._tier_row(entry, row0), m, k, h1m, h2m
        )

    def bloom_count(self, name) -> LazyResult:
        nc = self.nearcache
        captured = nc.epochs(name)  # before entry resolution, see contains
        entry = self._require(name, PoolKind.BLOOM)
        if nc.active(1):
            return nc.lookup_scalar(
                "bloom", name, ("count",),
                lambda: self._bloom_count_dispatch(entry),
                captured=captured,
            )
        return self._bloom_count_dispatch(entry)

    def _bloom_count_dispatch(self, entry) -> LazyResult:
        row0 = entry.row  # BEFORE the residency check (see _tier_row)
        res = self._serve_degraded(entry, 1, lambda mir: mir.count())
        if res is not None:
            return res
        self._drain()
        return self.executor.bloom_count(
            entry.pool, self._tier_row(entry, row0),
            entry.params["size"], entry.params["hash_iterations"]
        )

    # Encoded entry points: the object layer hands down raw codec lanes and
    # each engine decides where to hash.  On the direct single-device path
    # the hash + 64-bit mod run in-kernel (ops/fastpath.py device-hash
    # path, bit-identical to the host pipeline); coalesced/sharded paths
    # hash on the host as before.

    def _runs_dispatch(self, pool, k):
        """Flush-time dispatch for the run-length mixed path: folds the
        segment's per-chunk metas into per-RUN metadata arrays (row, m,
        is_add once per chunk + cumulative starts) and ships them with the
        concatenated key blocks (executor.bloom_mixed_keys_runs).  Key
        lengths collapse to one scalar when every chunk is const-length."""

        def dispatch(cols, metas):
            C = len(metas)
            run_rows = np.empty(C, np.int32)
            run_m = np.empty(C, np.uint32)
            run_flags = np.empty(C, np.bool_)
            starts = np.zeros(C + 1, np.int32)
            const_val = None
            all_const = True
            for i, (nops, (row, m, flag, ln)) in enumerate(metas):
                run_rows[i] = row
                run_m[i] = m
                run_flags[i] = flag
                starts[i + 1] = starts[i] + nops
                if isinstance(ln, (int, np.integer)):
                    if const_val is None:
                        const_val = int(ln)
                    elif const_val != int(ln):
                        all_const = False
                else:
                    all_const = False
            if all_const:
                lengths = np.uint32(0 if const_val is None else const_val)
            else:
                lengths = np.concatenate(
                    [
                        np.full(nops, ln, np.uint32)
                        if isinstance(ln, (int, np.integer))
                        else np.asarray(ln, np.uint32)
                        for nops, (_, _, _, ln) in metas
                    ]
                )
            if (
                not getattr(self.executor, "supports_runs_metadata", False)
                or C > 1024
            ):
                # Two reasons to expand the runs host-side and take the
                # per-op-array path: (1) the executor changed under a
                # queued segment (live change_topology swaps in a
                # sharded executor, which has no runs kernel) — rows are
                # topology-stable, so the queued ops stay valid
                # verbatim; (2) a degenerate many-tiny-chunk segment
                # with >1024 runs — capping C here pins the runs
                # kernel's compiled Cp space to exactly {1024}, which is
                # what the AOT pre-warmer compiles (a bigger Cp would be
                # a first-touch compile ON the serving path after
                # prewarm_wait reported a warmed cache).
                B = int(starts[-1])
                rows = np.repeat(run_rows, np.diff(starts))
                m_arr = np.repeat(run_m, np.diff(starts))
                flags = np.repeat(run_flags, np.diff(starts))
                if np.ndim(lengths) == 0:
                    lengths = np.full(B, lengths, np.uint32)
                return self.executor.bloom_mixed_keys(
                    pool, rows, m_arr, k, cols[0], lengths, flags
                )
            return self.executor.bloom_mixed_keys_runs(
                pool, k, cols[0], lengths, run_rows, run_m, run_flags, starts
            )

        return dispatch

    def _bloom_submit_mixed_keys(self, entry, blocks, lengths, is_add):
        """Device-hash path: raw codec lanes ride the mixed kernel;
        producer threads never hash (GIL relief under offered load).
        Replicated entries expand writes to every copy and rotate reads.
        Lane count is part of the segment key so concatenated chunks
        always agree on shape.

        ``is_add`` is a scalar for uniform batches or a per-op bool array
        for an ordered add/contains mix (the front-door fused runs of
        ISSUE 6) — the mixed kernel honors intra-batch order either way;
        only the runs-metadata compression requires a uniform flag."""
        m, k = entry.params["size"], entry.params["hash_iterations"]
        pool = entry.pool
        B = blocks.shape[0]
        L = blocks.shape[1]
        lengths = np.asarray(lengths, np.uint32)
        uniform = np.ndim(is_add) == 0
        orig_flags = (
            np.full(B, bool(is_add), bool)
            if uniform else np.asarray(is_add, bool)
        )
        any_add = bool(orig_flags.any())
        row0 = entry.row  # BEFORE the residency check (see _tier_row)
        if self._degraded(entry):
            # Degraded: hash host-side (the mirror consumes reduced
            # hashes) and serve from the golden mirror.
            lens = (
                np.full(B, lengths, np.uint32)
                if lengths.ndim == 0 else lengths
            )
            h1m, h2m = self._bloom_reduce(
                entry, *hashing.hash128_np(blocks, lens)
            )
            res = self._mirror_call(
                entry, B, lambda mir: mir.mixed(h1m, h2m, orig_flags)
            )
            if res is not None:
                return res
            # mirror reconciled mid-call: fall through to the device
        saw_replicas = bool(entry.replica_rows)
        if self.prewarmer is not None and B:
            # Keyed (codec-shaped) signatures can't be known at pool
            # attach — the lane count and trim depth come from real key
            # bytes.  First sight of a COARSE (pool, k, L) signature
            # schedules the whole bucket ladder in the background; the
            # coarse gate keeps the O(B) trim/const scans off every
            # subsequent submit (this producer path is the hot path).
            coarse = (id(pool), k, L)
            if coarse not in self._prewarm_seen:
                self._prewarm_seen.add(coarse)
                self._prewarm_keyed(pool, k, L, blocks, lengths)
        if (
            self.coalescer is not None
            and not saw_replicas
            and uniform
            and getattr(self.executor, "supports_runs_metadata", False)
        ):
            # Run-length path: row/m/is_add are constant across this call,
            # so they ride the segment as ONE meta tuple instead of B-long
            # arrays — ~22→~8 bytes/op on the wire (PROFILE.md lever 1) and
            # no np.full per submit on the producer thread.
            if lengths.ndim == 0:
                len_meta = int(lengths)
            else:
                const = B > 0 and bool(np.all(lengths == lengths[0]))
                len_meta = int(lengths[0]) if const else lengths
            fut = self._submit(
                ("bloom_mixkr", id(pool), k, L),
                self._runs_dispatch(pool, k),
                (blocks,),
                B,
                pool_key=id(pool),
                meta=(self._tier_row(entry, row0), m, is_add, len_meta),
                tenant=entry.name,
            )
            if any_add:
                self._replication_fence(
                    entry,
                    saw_replicas,
                    # _bloom_submit_mixed_keys accepts scalar lengths, so
                    # the original (blocks, lengths) pair re-submits as-is.
                    lambda: self._bloom_submit_mixed_keys(
                        entry, blocks, lengths, True
                    ),
                )
            return fut
        if lengths.ndim == 0:
            lengths = np.full(B, lengths, np.uint32)
        flags = orig_flags
        orig = (blocks, lengths)
        if saw_replicas:
            rows, eidx, ppos = self._bloom_expand_ops(entry, B, flags)
            blocks, lengths, flags = blocks[eidx], lengths[eidx], flags[eidx]
            gather = lambda v: v[ppos]  # noqa: E731
        else:
            rows = np.full(B, self._tier_row(entry, row0), np.int32)
            gather = None
        if self.coalescer is not None:
            m_arr = np.full(len(rows), m, np.uint32)
            fut = self._submit(
                ("bloom_mixk", id(pool), k, L),
                lambda cols: self.executor.bloom_mixed_keys(
                    pool, cols[0], cols[1], k, cols[2], cols[3], cols[4]
                ),
                (rows, m_arr, blocks, lengths, flags),
                len(rows),
                pool_key=id(pool),
                tenant=entry.name,
            )
        else:
            m_arr = np.full(len(rows), m, np.uint32)
            fut = self.executor.bloom_mixed_keys(
                pool, rows, m_arr, k, blocks, lengths, flags
            )
        if any_add:
            # Fence re-applies WRITES only: for a mixed batch the add
            # subset re-broadcasts (contains ops have nothing to re-apply
            # and re-running them would waste a launch).
            if uniform:
                redo = lambda: self._bloom_submit_mixed_keys(  # noqa: E731
                    entry, *orig, True
                )
            else:
                sel = orig_flags
                redo = lambda: self._bloom_submit_mixed_keys(  # noqa: E731
                    entry, orig[0][sel], orig[1][sel], True
                )
            self._replication_fence(entry, saw_replicas, redo)
        return fut if gather is None else _MappedFuture(fut, gather)

    def bloom_add_encoded(self, name, blocks, lengths) -> LazyResult:
        if self.executor.supports_device_hash:
            with self._nc_mutate(name), self._journal_gate:
                entry = self._require(name, PoolKind.BLOOM)
                if (
                    self.coalescer is not None
                    and self.config.tpu_sketch.exact_add_semantics
                ) or entry.replica_rows or self._degraded(entry):
                    # The mixed-keys path owns the degraded-mirror failover.
                    res = self._bloom_submit_mixed_keys(
                        entry, blocks, lengths, True
                    )
                    # Journaled as raw key material (replay hashes
                    # host-side — bit-identical to the device hash).
                    return self._commit(
                        res, "bloom.addk", name,
                        blocks=np.asarray(blocks),
                        lengths=np.asarray(lengths),
                    )
                if not self.config.tpu_sketch.exact_add_semantics:
                    m, k = entry.params["size"], entry.params["hash_iterations"]
                    self._drain()
                    res = self.executor.bloom_add_keys_st(
                        entry.pool, entry.row, m, k, blocks, lengths
                    )
                    self._replication_fence(
                        entry,
                        False,
                        lambda: self._bloom_submit_mixed_keys(
                            entry, blocks, lengths, True
                        ),
                    )
                    return self._commit(
                        res, "bloom.addk", name,
                        blocks=np.asarray(blocks),
                        lengths=np.asarray(lengths),
                    )
        # Host-hash fallback journals inside bloom_add (one record per
        # accepted op — never two).
        return self.bloom_add(name, *hashing.hash128_np(blocks, lengths))

    def collect_results(self, lazies) -> None:
        """Engine-level mailbox collect (policy gate for the bulk APIs):
        honors ``mailbox_collect`` and never raises — a failed group
        fetch degrades to per-item ``.result()``, which recovers or
        attributes each launch individually."""
        if not self.config.tpu_sketch.mailbox_collect:
            return
        try:
            self.executor.collect_group(lazies)
        except Exception:
            pass

    def bloom_contains_encoded(self, name, blocks, lengths) -> LazyResult:
        if not self.executor.supports_device_hash:
            return self.bloom_contains(name, *hashing.hash128_np(blocks, lengths))
        nc = self.nearcache
        captured = nc.epochs(name)  # before entry resolution, see contains
        entry = self._require(name, PoolKind.BLOOM)
        B = blocks.shape[0]
        if nc.active(B):
            lengths_arr = np.asarray(lengths)

            def fetch(idx):
                if idx is None:
                    return self._bloom_contains_encoded_dispatch(
                        entry, blocks, lengths
                    )
                sub_l = (
                    lengths if lengths_arr.ndim == 0 else lengths_arr[idx]
                )
                return self._bloom_contains_encoded_dispatch(
                    entry, blocks[idx], sub_l
                )

            return nc.lookup_batch(
                "bloom", name, nc.encoded_keys(blocks, lengths), np.bool_,
                fetch, monotone=True, captured=captured,
            )
        return self._bloom_contains_encoded_dispatch(entry, blocks, lengths)

    def _bloom_contains_encoded_dispatch(self, entry, blocks, lengths):
        row0 = entry.row  # BEFORE the residency check (see _tier_row)
        if (
            self.coalescer is not None
            or entry.replica_rows
            or self._degraded(entry)  # mixed-keys path serves mirror
        ):
            return self._bloom_submit_mixed_keys(entry, blocks, lengths, False)
        m, k = entry.params["size"], entry.params["hash_iterations"]
        return self.executor.bloom_contains_keys_st(
            entry.pool, self._tier_row(entry, row0), m, k, blocks, lengths
        )

    def bloom_mixed_encoded(self, name, blocks, lengths, flags) -> LazyResult:
        """Front-door fused run (ISSUE 6): one ordered add/contains mix on
        one filter as ONE engine call — per-op results (newly-added for
        add ops, membership for contains ops) come back in command order.
        The mixed kernel already honors intra-batch sequencing (adds and
        contains of one pool share a coalescer segment today), so a run
        of 500 pipelined BF.ADD/BF.EXISTS costs one launch, not 500."""
        flags = np.asarray(flags, bool)
        if not flags.any():
            return self.bloom_contains_encoded(name, blocks, lengths)
        if flags.all():
            return self.bloom_add_encoded(name, blocks, lengths)
        with self._nc_mutate(name), self._journal_gate:
            entry = self._require(name, PoolKind.BLOOM)
            # Journal the ADD subset only (contains ops have no state
            # effect to recover); replay order within the batch is
            # preserved — adds of one call are order-independent.
            lens_arr = np.asarray(lengths, np.uint32)
            if lens_arr.ndim == 0:
                lens_arr = np.full(blocks.shape[0], lens_arr, np.uint32)
            if not self.executor.supports_device_hash:
                h1m, h2m = self._bloom_reduce(
                    entry, *hashing.hash128_np(blocks, lens_arr)
                )
                res = self._bloom_dispatch_hashed(entry, h1m, h2m, flags)
            else:
                res = self._bloom_submit_mixed_keys(
                    entry, blocks, lengths, flags
                )
            return self._commit(
                res, "bloom.addk", name,
                blocks=np.asarray(blocks)[flags],
                lengths=lens_arr[flags],
            )

    # -- hll ---------------------------------------------------------------

    def hll_ensure(self, name):
        self._live_lookup(name)  # reap an expired holder first
        self._guard_foreign(name)
        entry, _ = self.registry.try_create(name, PoolKind.HLL, (), {})
        self.residency.touch(name)  # heat feed (see _lookup_kind)
        if self.prewarmer is not None:
            # Seen-set gate: hll_ensure runs on EVERY op — the closure
            # build + prewarmer lock belong off the hot path (register
            # itself dedupes, but not for free).
            coarse = (id(entry.pool), "hll")
            if coarse not in self._prewarm_seen:
                self._prewarm_seen.add(coarse)
                from redisson_tpu.executor import prewarm

                self.prewarmer.register(
                    entry.pool, ("hll_add",), prewarm.warm_hll_add_changed()
                )
        return entry

    def hll_add(self, name, c0, c1, c2) -> LazyResult:
        with self._nc_mutate(name), self._journal_gate:
            res = self._hll_add_impl(name, c0, c1, c2)
            return self._commit(
                res, "hll.add", name,
                c0=np.asarray(c0, np.uint32),
                c1=np.asarray(c1, np.uint32),
                c2=np.asarray(c2, np.uint32),
            )

    def _hll_add_impl(self, name, c0, c1, c2) -> LazyResult:
        entry = self.hll_ensure(name)
        res = self._serve_degraded(
            entry, len(c0),
            lambda mir: bool(np.any(mir.add_changed(c0, c1, c2))),
        )
        if res is not None:
            return res
        if self.coalescer is not None:
            pool = entry.pool
            rows = np.full(len(c0), entry.row, np.int32)
            fut = self._submit(
                ("hll_add", id(pool)),
                lambda cols: self.executor.hll_add_changed(
                    pool, cols[0], cols[1], cols[2], cols[3]
                ),
                (rows, c0, c1, c2),
                len(c0),
                pool_key=id(pool),
                tenant=entry.name,
            )
            # addAll boolean: did anything change?
            return _MappedFuture(fut, lambda v: bool(np.any(v)))
        return self.executor.hll_add_single(entry.pool, entry.row, c0, c1, c2)

    def hll_add_encoded(self, name, blocks, lengths) -> LazyResult:
        if self.coalescer is None and self.executor.supports_device_hash:
            with self._nc_mutate(name), self._journal_gate:
                entry = self.hll_ensure(name)
                if not self._degraded(entry):
                    res = self.executor.hll_add_keys_single(
                        entry.pool, entry.row, blocks, lengths
                    )
                    # Raw key material; replay hashes host-side.
                    return self._commit(
                        res, "hll.addk", name,
                        blocks=np.asarray(blocks),
                        lengths=np.asarray(lengths),
                    )
        # Host-hash fallback journals inside hll_add.
        c0, c1, c2, _ = hashing.murmur3_x86_128(blocks, lengths)
        return self.hll_add(name, c0, c1, c2)

    def hll_count(self, name) -> LazyResult:
        nc = self.nearcache
        captured = nc.epochs(name)  # before entry resolution
        entry = self._lookup_kind(name, PoolKind.HLL)
        if entry is None:
            return ImmediateResult(0)
        if nc.active(1):
            return nc.lookup_scalar(
                "hll", name, ("count",),
                lambda: self._hll_count_dispatch(entry),
                captured=captured,
            )
        return self._hll_count_dispatch(entry)

    def _hll_count_dispatch(self, entry) -> LazyResult:
        row0 = entry.row  # BEFORE the residency check (see _tier_row)
        res = self._serve_degraded(entry, 1, lambda mir: mir.count())
        if res is not None:
            return res
        self._drain()
        return self.executor.hll_count(
            entry.pool, self._tier_row(entry, row0)
        )

    def hll_count_with(self, name, other_names) -> int:
        """PFCOUNT over several keys = cardinality of the union: merge
        histogram-side via max of registers without mutating state."""
        entries = [self._lookup_kind(n, PoolKind.HLL) for n in (name, *other_names)]
        entries = [e for e in entries if e is not None]
        if not entries:
            return 0
        self._drain()
        # All HLL tenants share one pool; union via host max of rows is
        # small (16KB/row) — fine for a count call.  Degraded entries
        # contribute their MIRROR registers (the device row is stale
        # while a breaker is open).
        regs = None
        for e in entries:
            r = None
            row0 = e.row  # BEFORE the residency check (see _tier_row)
            if e.row < 0 and e.name not in self._mirrors:
                self._ensure_resident(e)  # DISK/born-cold union source
            if self._mirrors:
                # Snapshot under the mirror lock (degraded.py's
                # external-synchronization contract): a concurrent
                # add_changed or reconcile must not tear the read.
                with self._mirror_lock:
                    mir = self._mirrors.get(e.name)
                    if mir is not None and mir.kind == PoolKind.HLL:
                        r = mir.regs.copy()
            if r is None:
                r = self.executor.read_row(e.pool, self._tier_row(e, row0))
            regs = r if regs is None else np.maximum(regs, r)
        hist = np.bincount(regs, minlength=golden.HLL_Q + 2)
        return int(round(golden.ertl_estimate(hist)))

    def hll_merge_with(self, name, other_names) -> None:
        with self._nc_mutate(name), self._journal_gate:
            self._hll_merge_with_impl(name, other_names)
            seq = self._journal_rec(
                "hll.merge", name, srcs=[str(n) for n in other_names]
            )
        return self._ack(None, seq)  # fence outside the gate (see delete)

    def _hll_merge_with_impl(self, name, other_names) -> None:
        entry = self.hll_ensure(name)
        src_entries = [
            e
            for e in (self._lookup_kind(n, PoolKind.HLL) for n in other_names)
            if e is not None
        ]
        if not src_entries:
            return
        if self._degraded(entry):
            # Merge golden-side: each source contributes its CURRENT
            # truth (its own mirror if degraded, else its device row) —
            # source rows gathered before the dest's mirror lock is
            # taken (lock order: one _mirror_lock acquisition at a time).
            rows = [self._host_row(e) for e in src_entries]
            res = self._mirror_call(
                entry, 1, lambda mir: mir.merge_rows(rows)
            )
            if res is not None:
                return
        self._drain()
        self.executor.hll_merge(
            entry.pool, entry.row, [e.row for e in src_entries]
        )

    # -- bitset ------------------------------------------------------------

    def _bitset_entry_with_capacity(self, name, min_bits: int):
        """Physical placement only — create/migrate so the row can hold
        ``min_bits``, WITHOUT extending the logical bit length (bitop
        operands must keep their true lengths)."""
        self._live_lookup(name)  # reap an expired holder first
        self._guard_foreign(name)
        entry, created = self.registry.try_create(
            name, PoolKind.BITSET, (class_words_for_bits(min_bits),), {"nbits": 0}
        )
        self.residency.touch(name)  # heat feed (see _lookup_kind)
        if not created:
            self._bitset_grow(entry, min_bits)
        return entry

    def bitset_ensure(self, name, min_bits: int = 1):
        entry = self._bitset_entry_with_capacity(name, min_bits)
        # Logical size tracking = Redis string-length semantics (SETBIT
        # grows the value to cover the highest index ever touched).
        entry.params["nbits"] = max(entry.params.get("nbits", 0), int(min_bits))
        if self.prewarmer is not None:
            # Seen-set gate: bitset_ensure runs on EVERY op (see
            # hll_ensure) — register once per pool, off the hot path.
            coarse = (id(entry.pool), "bitset")
            if coarse not in self._prewarm_seen:
                self._prewarm_seen.add(coarse)
                from redisson_tpu.executor import prewarm

                if getattr(self.executor, "supports_runs_metadata", False):
                    self.prewarmer.register(
                        entry.pool, ("bs_mixed_runs",),
                        prewarm.warm_bitset_mixed_runs(),
                    )
                self.prewarmer.register(
                    entry.pool, ("bs_mixed",), prewarm.warm_bitset_mixed()
                )
        return entry

    def _bitset_grow(self, entry, min_bits: int) -> None:
        """Auto-grow semantics of Redis bitmaps: migrate the tenant to a
        larger size class, copying the row through the host (rare path).

        The commit (write new row, zero+free old, repoint the entry) runs
        under the dispatch lock with a topology-epoch check: if a live
        change_topology swapped layouts mid-migration, the swap's free-
        list rebuild already reclaimed the not-yet-attached new row — we
        retry against the fresh layout instead of committing stale state."""
        cur_words = entry.pool.row_units
        need_words = class_words_for_bits(min_bits)
        if need_words <= cur_words:
            return
        # Size-class migration is STRUCTURAL for the near cache (ISSUE
        # 4: clear/resize/migration bump unconditionally) — entry+exit
        # bumps bracket the whole commit so no read captured mid-
        # migration can install.
        with self._nc_mutate(entry.name, structural=True):
            if entry.row < 0:
                # HOST/DISK residency (ISSUE 14): no device row to
                # migrate — repoint the entry to the larger size class.
                # The mirror's golden model grows on demand, the blob
                # loader zero-pads, and promote/encode size to the
                # entry's CURRENT pool.  Mutating callers hold the
                # journal gate, so no transition can interleave.
                entry.pool = self.registry.pool_for(
                    PoolKind.BITSET, (need_words,)
                )
                return
            self._bitset_migrate(entry, need_words)

    def _bitset_migrate(self, entry, need_words: int) -> None:
        # Shrink the queue first (optional — flush-time row resolution in
        # _bitset_submit_mixed makes queued ops follow the repoint, so
        # correctness doesn't depend on this drain).
        self._drain()
        while True:
            old_pool, old_row = entry.pool, entry.row
            epoch_old = old_pool.topology_epoch
            new_pool = self.registry.pool_for(PoolKind.BITSET, (need_words,))
            with old_pool._dispatch_lock:
                if (
                    old_pool.topology_epoch != epoch_old
                    or entry.pool is not old_pool
                    or entry.row != old_row
                ):
                    # Stale view: a topology swap rebuilt layouts, or a
                    # CONCURRENT grow already migrated this entry (same
                    # destination class → committing here would copy the
                    # zeroed old row over live data and double-free it).
                    # Nothing allocated yet — safe to re-evaluate.
                    if entry.pool.row_units >= need_words:
                        return  # the other grow already got us there
                    continue
                # Allocate INSIDE the dispatch lock: change_topology holds
                # this lock for its swap, so no free-list rebuild can
                # interleave between this alloc and the commit below (the
                # old alloc-before-lock ordering leaked or double-freed
                # the new row depending on which side of the swap the
                # alloc landed).
                new_row = new_pool.alloc_row()
                # Read INSIDE the lock: the copy and the commit are atomic
                # vs concurrent flushes applying ops to the old row.
                # rtpulint: disable=RT001 migration copy-and-commit must be atomic vs concurrent flushes on the old row — releasing the dispatch lock between read and write would lose ops applied in the gap
                data = self.executor.read_row(old_pool, old_row)
                padded = np.zeros(need_words, dtype=np.uint32)
                padded[: len(data)] = data
                # rtpulint: disable=RT001 same atomic migration window as the read above
                self.executor.write_row(new_pool, new_row, padded)
                # rtpulint: disable=RT001 zero-then-free must be atomic vs reallocation (the _reap_rows discipline): releasing between would hand out a dirty row
                self.executor.zero_row(old_pool, old_row)
                old_pool.free_row(old_row)
                entry.pool, entry.row = new_pool, new_row
                return

    def bitset_capacity_bits(self, name) -> int:
        entry = self._lookup_kind(name, PoolKind.BITSET)
        return 0 if entry is None else entry.pool.row_units * 32

    def _bitset_dispatch_group(self, pool, gidx, runs):
        """One resolved-placement group of a mixed-bit segment → one
        device launch (runs-metadata form when the executor supports it;
        >1024 runs expand to per-op arrays so the runs kernel's Cp
        compile space stays the single pre-warmed 1024 bucket)."""
        if (
            getattr(self.executor, "supports_runs_metadata", False)
            and len(runs) <= 1024
        ):
            run_rows = np.array([r for _, r, _ in runs], np.int32)
            run_ops = np.array([o for _, _, o in runs], np.uint32)
            starts = np.zeros(len(runs) + 1, np.int32)
            starts[1:] = np.cumsum([n for n, _, _ in runs])
            return self.executor.bitset_mixed_runs(
                pool, gidx, run_rows, run_ops, starts
            )
        rows = np.concatenate(
            [np.full(n, r, np.int32) for n, r, _ in runs]
        )
        ops_col = np.concatenate(
            [np.full(n, o, np.uint32) for n, _, o in runs]
        )
        return self.executor.bitset_mixed(pool, rows, gidx, ops_col)

    def _bitset_submit_mixed(self, entry, idx, opcode: int):
        """Coalesced path: every single-bit opcode rides ONE segment per
        pool through the unified affine kernel (exact sequential
        semantics), so interleaved set/clear/flip/get never fragment.

        Placement (entry.pool/row) resolves at FLUSH time, under the
        dispatch lock, from per-chunk metas — not at submit: a size-class
        migration (_bitset_grow) or live change_topology committing while
        ops sit queued repoints the entry, and baked-at-submit rows would
        land writes in the old, freed row (lost updates).  Flush-time
        resolution linearizes queued ops AFTER the commit, onto the row
        that now holds the data."""

        def dispatch(cols, metas):
            offs = [0]
            for nops, _m in metas:
                offs.append(offs[-1] + nops)
            # Residency stragglers (ISSUE 14): a chunk whose entry
            # DEMOTED between submit and flush serves from the mirror —
            # flush-time residency resolution, the same discipline as
            # the flush-time row resolution below.  Applied OUTSIDE the
            # dispatch lock: mirror→dispatch is the engine-wide lock
            # order (snapshot capture, reconcile, promote); inverting
            # it here would be an AB-BA.  A None from _mirror_call
            # means the entry promoted mid-flight — its row is live
            # again and the group pass re-reads it under the lock.
            mirror_parts = {}
            for mi, (nops, (e, op)) in enumerate(metas):
                if e.row >= 0:
                    continue
                gidx = np.asarray(cols[0][offs[mi]:offs[mi + 1]])
                ops_col = np.full(nops, op, np.uint32)
                res = None
                for _ in range(4):
                    res = self._mirror_call(
                        e, nops,
                        lambda mir, g=gidx, o=ops_col: mir.mixed(g, o),
                    )
                    if res is not None or e.row >= 0:
                        break
                    # Row-less with no mirror: the entry SPILLED
                    # between this chunk queueing and the flush (spill
                    # drains first, but readers enqueue gate-free) —
                    # reload the mirror and re-apply.  Falling through
                    # to the device branch would dispatch row -1 into
                    # another tenant's row.  load_nowait, never load:
                    # the gate holder may be draining on THIS flush
                    # (blocking would be flush→gate vs gate→drain).
                    if not self.residency.load_nowait(e):
                        time.sleep(0.001)
                if res is not None:
                    mirror_parts[mi] = res
                elif e.row < 0:  # pragma: no cover — load kept failing
                    from redisson_tpu.executor.failures import (
                        NonRetryableDispatchError,
                    )

                    raise NonRetryableDispatchError(
                        f"bitset chunk for {e.name!r} has neither a "
                        f"device row nor a loadable mirror"
                    )
            with self.executor._dispatch_lock:  # atomic vs migration commit
                # Group CONSECUTIVE device chunks by their resolved pool
                # (op order is preserved — groups split at chunk
                # boundaries and at mirror-served chunks).  More than one
                # group only when a migration or demotion committed
                # mid-segment.
                groups = []  # ("dev", pool, runs, lo, hi) | ("mir", res,...)
                off = 0
                for mi, (nops, (e, op)) in enumerate(metas):
                    part = mirror_parts.get(mi)
                    if part is not None:
                        groups.append(("mir", part, None, off, off + nops))
                    else:
                        pool, row = e.pool, e.row
                        if (
                            groups and groups[-1][0] == "dev"
                            and groups[-1][1] is pool
                        ):
                            groups[-1][2].append((nops, row, op))
                            groups[-1][4] = off + nops
                        else:
                            groups.append(
                                ["dev", pool, [(nops, row, op)],
                                 off, off + nops]
                            )
                    off += nops
                results = []
                # Mirror parts already applied: any later failure must
                # not blind-retry the whole segment (re-applying them).
                applied = bool(mirror_parts)
                for tag, pool, runs, lo, hi in groups:
                    if tag == "mir":
                        results.append(pool)  # the ImmediateResult
                        continue
                    gidx = cols[0][lo:hi]
                    if applied:
                        # Earlier groups/mirror parts already mutated
                        # state: a failure from here on must NOT be
                        # blind-retried (double-applying OP_FLIP/OP_SET).
                        try:
                            results.append(
                                self._bitset_dispatch_group(
                                    pool, gidx, runs
                                )
                            )
                        except Exception as exc:
                            from redisson_tpu.executor.failures import (
                                NonRetryableDispatchError,
                            )

                            raise NonRetryableDispatchError(
                                "a later group of a split mixed-bit "
                                "launch failed after earlier groups "
                                "applied"
                            ) from exc
                        continue
                    results.append(self._bitset_dispatch_group(pool, gidx, runs))
                    applied = True
                return results[0] if len(results) == 1 else _ConcatLazy(results)

        return self._submit(
            ("bs_mix", id(entry.pool)),
            dispatch,
            (np.asarray(idx, np.uint32),),
            len(idx),
            pool_key=id(entry.pool),
            meta=(entry, opcode),
            tenant=entry.name,
        )

    def _bitset_rw(self, opcode: int, method, entry, idx):
        res = self._serve_degraded(
            entry, len(idx), lambda mir: mir.mixed(
                idx, np.full(len(idx), opcode, np.uint32)
            )
        )
        if res is not None:
            return res
        if self.coalescer is not None:
            return self._bitset_submit_mixed(entry, idx, opcode)
        # Resolve placement and dispatch atomically vs a concurrent
        # size-class migration (same lock its commit holds).
        with self.executor._dispatch_lock:
            rows = np.full(len(idx), entry.row, np.int32)
            return method(entry.pool, rows, idx)

    def bitset_set(self, name, idx, value: bool) -> LazyResult:
        from redisson_tpu.ops import bitset as bitset_ops

        idx = np.asarray(idx, np.uint32)
        # Clearing bits retires monotone positives → structural bump;
        # setting bits is an ordinary (monotone-safe) write.
        with self._nc_mutate(name, structural=not value), \
                self._journal_gate:
            entry = self.bitset_ensure(
                name, int(idx.max()) + 1 if idx.size else 1
            )
            if value:
                res = self._bitset_rw(
                    bitset_ops.OP_SET, self.executor.bitset_set, entry, idx
                )
            else:
                res = self._bitset_rw(
                    bitset_ops.OP_CLEAR, self.executor.bitset_clear_bits,
                    entry, idx,
                )
            return self._commit(
                res, "bitset.set", name, idx=idx, value=bool(value)
            )

    def bitset_flip(self, name, idx) -> LazyResult:
        from redisson_tpu.ops import bitset as bitset_ops

        idx = np.asarray(idx, np.uint32)
        with self._nc_mutate(name, structural=True), \
                self._journal_gate:  # flips clear bits
            entry = self.bitset_ensure(
                name, int(idx.max()) + 1 if idx.size else 1
            )
            res = self._bitset_rw(
                bitset_ops.OP_FLIP, self.executor.bitset_flip, entry, idx
            )
            return self._commit(res, "bitset.flip", name, idx=idx)

    def bitset_get(self, name, idx) -> LazyResult:
        idx = np.asarray(idx, np.uint32)
        nc = self.nearcache
        captured = nc.epochs(name)  # before entry resolution
        entry = self._lookup_kind(name, PoolKind.BITSET)
        if entry is None:
            return ImmediateResult(np.zeros(len(idx), bool))
        if nc.active(len(idx)):
            return nc.lookup_batch(
                "bitset", name, [int(i) for i in idx], np.bool_,
                lambda midx: self._bitset_get_dispatch(
                    entry, idx if midx is None else idx[midx]
                ),
                monotone=True,  # OP_CLEAR/OP_FLIP/replace are structural
                captured=captured,
            )
        return self._bitset_get_dispatch(entry, idx)

    def _bitset_get_dispatch(self, entry, idx) -> LazyResult:
        from redisson_tpu.ops import bitset as bitset_ops

        cap = entry.pool.row_units * 32
        in_range = idx < cap
        safe_idx = np.where(in_range, idx, 0).astype(np.uint32)
        row0 = entry.row  # BEFORE the residency check (see _tier_row)
        res = self._serve_degraded(
            entry, len(idx), lambda mir: mir.mixed(
                safe_idx, np.full(len(idx), bitset_ops.OP_GET, np.uint32)
            ) & in_range
        )
        if res is not None:
            return res
        if self.coalescer is not None:
            fut = self._bitset_submit_mixed(entry, safe_idx, bitset_ops.OP_GET)
            return _MappedFuture(fut, lambda v: v & in_range)
        rows = np.full(len(idx), self._tier_row(entry, row0), np.int32)
        res = self.executor.bitset_get(entry.pool, rows, safe_idx)
        return _MappedFuture(res, lambda v: v & in_range)

    def bitset_set_range(self, name, from_bit, to_bit, value: bool) -> LazyResult:
        with self._nc_mutate(name, structural=not value), \
                self._journal_gate:
            entry = self.bitset_ensure(name, int(to_bit))
            res = self._serve_degraded(
                entry, 1,
                lambda mir: mir.set_range(int(from_bit), int(to_bit), bool(value)),
            )
            if res is None:
                self._drain()
                res = self.executor.bitset_set_range(
                    entry.pool, entry.row, int(from_bit), int(to_bit), value
                )
            return self._commit(
                res, "bitset.range", name,
                frm=int(from_bit), to=int(to_bit), value=bool(value),
            )

    def _nc_scalar(self, kind, name, key, dispatch, captured):
        """Near-cache plumbing shared by every scalar read-through
        (bitset cardinality/length/bitpos, CMS total): epoch-tagged,
        single host int.  ``captured``: epoch pair sampled before entry
        resolution."""
        nc = self.nearcache
        if nc.active(1):
            return int(
                nc.lookup_scalar(
                    kind, name, key, dispatch, captured=captured
                ).result()
            )
        return int(dispatch().result())

    def bitset_cardinality(self, name) -> int:
        captured = self.nearcache.epochs(name)
        entry = self._lookup_kind(name, PoolKind.BITSET)
        if entry is None:
            return 0

        def dispatch():
            row0 = entry.row  # BEFORE the residency check
            res = self._serve_degraded(entry, 1, lambda mir: mir.cardinality())
            if res is not None:
                return res
            self._drain()
            return self.executor.bitset_cardinality(
                entry.pool, self._tier_row(entry, row0)
            )

        return self._nc_scalar("bitset", name, ("card",), dispatch, captured)

    def bitset_length(self, name) -> int:
        captured = self.nearcache.epochs(name)
        entry = self._lookup_kind(name, PoolKind.BITSET)
        if entry is None:
            return 0

        def dispatch():
            row0 = entry.row  # BEFORE the residency check
            res = self._serve_degraded(entry, 1, lambda mir: mir.length())
            if res is not None:
                return res
            self._drain()
            return self.executor.bitset_length(
                entry.pool, self._tier_row(entry, row0)
            )

        return self._nc_scalar("bitset", name, ("len",), dispatch, captured)

    def bitset_bitpos(self, name, target_bit: int) -> int:
        captured = self.nearcache.epochs(name)
        entry = self._lookup_kind(name, PoolKind.BITSET)
        if entry is None:
            return -1 if target_bit else 0

        def dispatch():
            row0 = entry.row  # BEFORE the residency check
            res = self._serve_degraded(
                entry, 1, lambda mir: mir.bitpos(int(target_bit))
            )
            if res is not None:
                return res
            self._drain()
            return self.executor.bitset_bitpos(
                entry.pool, self._tier_row(entry, row0), target_bit
            )

        return self._nc_scalar(
            "bitset", name, ("bitpos", int(target_bit)), dispatch, captured
        )

    def bitset_bitop(self, dest: str, src_names, op: str) -> None:
        """BITOP dest = op(srcs).  All operands (dest included) are grown
        into one size class first so their rows co-reside in a single pool
        (the TPU answer to the reference's same-slot requirement for
        cross-key BITOP, SURVEY.md §2.2).

        Redis semantics: dest is *replaced* (its prior value never leaks
        into the result), and the result length is the max source length.
        Unary NOT complements the source's full *byte-aligned* string
        (Redis values are byte strings, so BITOP NOT flips padding bits up
        to the byte boundary too) and is masked there so tail bits of the
        size-class row stay 0.
        """
        with self._nc_mutate(dest, structural=True), \
                self._journal_gate:  # dest is REPLACED
            self._bitset_bitop_impl(dest, src_names, op)
            seq = self._journal_rec(
                "bitset.bitop", dest,
                srcs=[str(n) for n in src_names], bop=str(op),
            )
        return self._ack(None, seq)  # fence outside the gate (see delete)

    def _bitset_bitop_impl(self, dest: str, src_names, op: str) -> None:
        max_bits = max(
            (self.bitset_capacity_bits(n) for n in (dest, *src_names)),
            default=0,
        ) or 32 * 32
        dst = self._bitset_entry_with_capacity(dest, max_bits)
        srcs, src_nbits, src_entries = [], [], []
        for n in src_names:
            e = self._bitset_entry_with_capacity(n, max_bits)
            srcs.append(e.row)
            src_nbits.append(e.params.get("nbits", 0))
            src_entries.append(e)
        nbits = (
            -(-src_nbits[0] // 8) * 8 if op == "not" else max(src_nbits, default=0)
        )
        if self._degraded(dst):
            # Golden-side BITOP: decode every source's current truth
            # (mirror or device row — all operands were grown into one
            # size class above, so rows share one physical width),
            # combine host-side, and REPLACE the dest mirror (Redis
            # semantics: dest's prior value never leaks into the result).
            from redisson_tpu.objects.degraded import _bits_from_words

            nb_phys = dst.pool.row_units * 32
            srcs_bits = [
                _bits_from_words(self._host_row(e), nb_phys)
                for e in src_entries
            ]
            if op == "not":
                out = np.zeros(nb_phys, bool)
                out[:nbits] = ~srcs_bits[0][:nbits]
            else:
                fn = {
                    "and": np.logical_and,
                    "or": np.logical_or,
                    "xor": np.logical_xor,
                }[op]
                out = srcs_bits[0].copy()
                for b in srcs_bits[1:]:
                    out = fn(out, b)
            res = self._mirror_call(
                dst, 1, lambda mir: mir.replace_bits(out)
            )
            if res is not None:
                dst.params["nbits"] = nbits
                return
        self._drain()
        self.executor.bitset_bitop(
            dst.pool, dst.row, srcs, op,
            limit_bits=nbits if op == "not" else None,
        )
        dst.params["nbits"] = nbits

    def bitset_to_bytes(self, name) -> bytes:
        """Dump trimmed to the logical length (Redis STRLEN semantics) so
        both engines return identical bytes for the same object."""
        entry = self._lookup_kind(name, PoolKind.BITSET)
        if entry is None:
            return b""
        nbytes = -(-entry.params.get("nbits", 0) // 8)
        row0 = entry.row  # BEFORE the residency check (see _tier_row)
        res = self._serve_degraded(
            entry, 1,
            lambda mir: np.packbits(
                mir.bits, bitorder="little"
            ).tobytes()[:nbytes],
        )
        if res is not None:
            return res.result()
        self._drain()
        return self.executor.read_row(
            entry.pool, self._tier_row(entry, row0)
        ).tobytes()[:nbytes]

    # -- cms ---------------------------------------------------------------

    def cms_try_init(self, name, depth: int, width: int) -> bool:
        params = {"depth": depth, "width": width}
        with self._journal_gate:
            self._live_lookup(name)  # reap an expired holder before tryInit
            self._guard_foreign(name)
            entry, created = self.registry.try_create(
                name, PoolKind.CMS, (depth, width), params
            )
            seq = self._journal_rec(
                "cms.init", name, depth=int(depth), width=int(width)
            ) if created else None
        if self.prewarmer is not None:
            from redisson_tpu.executor import prewarm

            self.prewarmer.register(
                entry.pool, ("cms_updest", depth, width),
                prewarm.warm_cms_update_estimate(depth, width),
            )
        return self._ack(created, seq)

    def cms_total(self, name) -> int:
        """Total inserted weight (CMS.INFO 'count'): every increment adds
        its weight to exactly one cell per depth row, so row 0's sum is
        the total."""
        captured = self.nearcache.epochs(name)  # before entry resolution
        entry = self._require(name, PoolKind.CMS)
        w = entry.params["width"]

        def dispatch():
            row0 = entry.row  # BEFORE the residency check
            res = self._serve_degraded(entry, 1, lambda mir: mir.total())
            if res is not None:
                return res
            self._drain()
            row = self.executor.read_row(
                entry.pool, self._tier_row(entry, row0)
            )
            return ImmediateResult(int(np.asarray(row[:w], np.uint64).sum()))

        return self._nc_scalar("cms", name, ("total",), dispatch, captured)

    def cms_reset(self, name) -> None:
        """Zero a CMS's counters in place (CMS.MERGE overwrite semantics)
        — the registry entry and any top-K configuration survive."""
        with self._nc_mutate(name, structural=True), \
                self._journal_gate:  # counters REPLACED
            entry = self._require(name, PoolKind.CMS)
            res = self._serve_degraded(entry, 1, lambda mir: mir.reset())
            if res is None:
                self._drain()
                self.executor.zero_row(entry.pool, entry.row)
            seq = self._journal_rec("cms.reset", name)
        self._ack(None, seq)  # fence outside the gate (see delete)

    def cms_add(self, name, H1, H2, weights) -> LazyResult:
        with self._nc_mutate(name), self._journal_gate:
            res = self._cms_add_impl(name, H1, H2, weights)
            return self._commit(
                res, "cms.add", name,
                h1=np.asarray(H1), h2=np.asarray(H2),
                w=np.asarray(weights, np.uint32),
            )

    def _cms_add_impl(self, name, H1, H2, weights) -> LazyResult:
        entry = self._require(name, PoolKind.CMS)
        d, w = entry.params["depth"], entry.params["width"]
        h1w, h2w = hashing.km_reduce_mod(H1, H2, w)
        rows = np.full(len(H1), entry.row, np.int32)
        wts = np.asarray(weights, np.uint32)
        res = self._serve_degraded(
            entry, len(H1),
            lambda mir: mir.update_estimate(h1w, h2w, wts),
        )
        if res is not None:
            return res
        if self.coalescer is not None:
            # Updates and estimates share one segment per (pool, d, w):
            # estimate ops ride with weight 0 (the scatter-add identity).
            # Estimates in a flush window may observe adds coalesced into
            # the same batch — CMS stays an upper bound either way.
            pool = entry.pool
            return self._submit(
                ("cms_mix", id(pool), d, w),
                lambda cols: self.executor.cms_update_estimate(
                    pool, cols[0], cols[1], cols[2], cols[3], d, w
                ),
                (rows, h1w, h2w, wts),
                len(H1),
                pool_key=id(pool),
                tenant=entry.name,
            )
        return self.executor.cms_update_estimate(
            entry.pool, rows, h1w, h2w, wts, d, w
        )

    def cms_estimate(self, name, H1, H2) -> LazyResult:
        nc = self.nearcache
        captured = nc.epochs(name)  # before entry resolution
        entry = self._require(name, PoolKind.CMS)
        if nc.active(len(H1)):
            H1a, H2a = np.asarray(H1), np.asarray(H2)
            return nc.lookup_batch(
                "cms", name, nc.hashed_keys(H1a, H2a), np.uint32,
                lambda idx: self._cms_estimate_dispatch(
                    entry,
                    H1a if idx is None else H1a[idx],
                    H2a if idx is None else H2a[idx],
                ),
                monotone=False,  # any add can raise an estimate
                captured=captured,
            )
        return self._cms_estimate_dispatch(entry, H1, H2)

    def _cms_estimate_dispatch(self, entry, H1, H2) -> LazyResult:
        d, w = entry.params["depth"], entry.params["width"]
        h1w, h2w = hashing.km_reduce_mod(H1, H2, w)
        row0 = entry.row  # BEFORE the residency check (see _tier_row)
        res = self._serve_degraded(
            entry, len(H1),
            lambda mir: mir.update_estimate(
                h1w, h2w, np.zeros(len(H1), np.uint32)
            ),
        )
        if res is not None:
            return res
        rows = np.full(len(H1), self._tier_row(entry, row0), np.int32)
        if self.coalescer is not None:
            pool = entry.pool
            zeros = np.zeros(len(H1), np.uint32)
            return self._submit(
                ("cms_mix", id(pool), d, w),
                lambda cols: self.executor.cms_update_estimate(
                    pool, cols[0], cols[1], cols[2], cols[3], d, w
                ),
                (rows, h1w, h2w, zeros),
                len(H1),
                pool_key=id(pool),
                tenant=entry.name,
            )
        return self.executor.cms_estimate(entry.pool, rows, h1w, h2w, d, w)

    # Per-launch op cap for the Pallas path: 4 uint32[B] operands must
    # share VMEM with the table; bigger batches chunk (state carries
    # across chunks, so sequential semantics are preserved exactly).
    _SEQ_CHUNK = 1 << 15

    def cms_add_seq(self, name, H1, H2, weights) -> LazyResult:
        """Streaming add+estimate via the Pallas heavy-hitter kernel
        (BASELINE config 5): op j's estimate is its AT-SEQUENCE-POINT
        value — ops ≤ j applied (its own update included), later ops
        excluded.  Falls back to the vectorized XLA path where the kernel
        isn't available (sharded mode) or the geometry doesn't fit VMEM
        lane blocks; the fallback's estimates include the whole batch."""
        with self._nc_mutate(name), self._journal_gate:
            res = self._cms_add_seq_impl(name, H1, H2, weights)
            # Same record as cms_add: the STATE effect of seq vs
            # vectorized add is identical (only the returned estimates'
            # sequence point differs), so replay shares one path.
            return self._commit(
                res, "cms.add", name,
                h1=np.asarray(H1), h2=np.asarray(H2),
                w=np.asarray(weights, np.uint32),
            )

    def _cms_add_seq_impl(self, name, H1, H2, weights) -> LazyResult:
        entry = self._require(name, PoolKind.CMS)
        d, w = entry.params["depth"], entry.params["width"]
        if self._degraded(entry):
            # Mirror fallback has whole-batch (vectorized) semantics,
            # like the non-Pallas fallback below.
            # _cms_add_impl, not cms_add: the public wrapper already
            # journals this call once (one record per accepted op).
            return self._cms_add_impl(name, H1, H2, weights)
        if (
            not getattr(self.executor, "supports_pallas_cms", False)
            or (d * w) % 128 != 0  # VMEM lane-block geometry
            or d * w * 4 > (8 << 20)  # table must fit VMEM
            or len(H1) == 0
        ):
            # _cms_add_impl, not cms_add: the public wrapper already
            # journals this call once (one record per accepted op).
            return self._cms_add_impl(name, H1, H2, weights)
        h1w, h2w = hashing.km_reduce_mod(H1, H2, w)
        weights = np.asarray(weights, np.uint32)
        self._drain()  # sequential semantics: all queued ops land first
        B = len(h1w)
        if B <= self._SEQ_CHUNK:
            return self.executor.cms_update_estimate_seq(
                entry.pool, entry.row, h1w, h2w, weights, d, w
            )
        parts = [
            self.executor.cms_update_estimate_seq(
                entry.pool, entry.row,
                h1w[i : i + self._SEQ_CHUNK],
                h2w[i : i + self._SEQ_CHUNK],
                weights[i : i + self._SEQ_CHUNK],
                d, w,
            )
            for i in range(0, B, self._SEQ_CHUNK)
        ]
        return ImmediateResult(
            np.concatenate([np.asarray(p.result()) for p in parts])
        )

    def cms_merge(self, name, other_names) -> None:
        with self._nc_mutate(name), self._journal_gate:
            self._cms_merge_impl(name, other_names)
            seq = self._journal_rec(
                "cms.merge", name, srcs=[str(n) for n in other_names]
            )
        return self._ack(None, seq)  # fence outside the gate (see delete)

    def _cms_merge_impl(self, name, other_names) -> None:
        entry = self._require(name, PoolKind.CMS)
        src_entries = []
        for n in other_names:
            e = self._require(n, PoolKind.CMS)
            if (
                e.params["depth"] != entry.params["depth"]
                or e.params["width"] != entry.params["width"]
            ):
                raise ValueError("cannot merge CMS with different geometry")
            src_entries.append(e)
        if not src_entries:
            return
        if self._degraded(entry):
            # Golden-side CMS.MERGE: sum each source's current truth
            # (its mirror if degraded, else its device row) into the
            # dest mirror — see hll_merge_with.
            rows = [self._host_row(e) for e in src_entries]
            res = self._mirror_call(
                entry, 1, lambda mir: mir.merge_rows(rows)
            )
            if res is not None:
                return
        self._drain()
        self.executor.cms_merge(
            entry.pool, entry.row, [e.row for e in src_entries]
        )


class HostSketchEngine:
    """Golden-model backend — the 'Redis server on the host' analog and the
    benchmark baseline.  Same hash material, same formulas; same
    TTL/dump/restore surface as the TPU engine."""

    def __init__(self, config):
        from redisson_tpu.obs import Observability

        self.config = config
        self._lock = _witness.named(threading.RLock(), "engine.host")
        self._objects: dict[str, dict] = {}
        # Same observability surface as the TPU engine (so a RESP server
        # or client fronting either backend finds one bundle to record
        # into); the host engine has no coalescer/executor to instrument.
        self.obs = Observability(
            trace_sample_rate=getattr(config, "trace_sample_rate", 0.0),
            trace_max_spans=getattr(config, "trace_max_spans", 2048),
            latency_threshold_ms=getattr(
                config, "latency_monitor_threshold_ms", 0
            ),
        )
        self.topk = TopKStore()
        # Wired by the client to the grid store's lock-free ``probe`` (one
        # logical keyspace — same contract as TpuSketchEngine).  Called
        # while holding self._lock, so it MUST NOT take the grid's lock.
        self.foreign_exists = None

    def _guard_foreign(self, name: str) -> None:
        if (
            self.foreign_exists is not None
            and name not in self._objects
            and self.foreign_exists(name)
        ):
            raise TypeError(
                f"object {name!r} is held by the data grid (WRONGTYPE)"
            )

    def probe(self, name: str) -> bool:
        """Lock-free existence probe for the grid's guard."""
        import time as _time

        o = self._objects.get(name)
        if o is None:
            return False
        exp = o.get("expire_at")
        return exp is None or _time.time() < exp

    def shutdown(self) -> None:
        pass

    # -- generic -----------------------------------------------------------

    def _live(self, name):
        """Lazy expiry (Redis-style): an overdue object vanishes on touch."""
        import time as _time

        o = self._objects.get(name)
        if o is not None and o.get("expire_at") is not None:
            if _time.time() >= o["expire_at"]:
                del self._objects[name]
                self.topk.drop(name)
                return None
        return o

    def exists(self, name) -> bool:
        with self._lock:
            return self._live(name) is not None

    def delete(self, name) -> bool:
        with self._lock:
            live = self._live(name) is not None
            self._objects.pop(name, None)
            self.topk.drop(name)
            return live

    def rename(self, old, new) -> bool:
        with self._lock:
            if old == new or self._live(old) is None:
                return False
            self._guard_foreign(new)  # one keyspace: RENAME can't shadow grid
            self._objects[new] = self._objects.pop(old)
            self.topk.rename(old, new)
            return True

    def names(self, kind=None):
        with self._lock:
            return [
                n
                for n in list(self._objects)
                if self._live(n) is not None
                and (kind is None or self._objects[n]["kind"] == kind)
            ]

    def params(self, name):
        with self._lock:
            o = self._live(name)
            return None if o is None else o["params"]

    def _require(self, name, kind):
        o = self._lookup_kind(name, kind)
        if o is None:
            raise RuntimeError(f"{kind} object {name!r} is not initialized")
        return o

    def _lookup_kind(self, name, kind):
        with self._lock:
            o = self._live(name)
            if o is not None and o["kind"] != kind:
                raise TypeError(f"object {name!r} holds a {o['kind']}, not a {kind}")
            return o

    # -- TTL / dump parity with the TPU engine -----------------------------

    def expire(self, name, ttl_s: float) -> bool:
        import time as _time

        return self.expire_at(name, _time.time() + ttl_s)

    def expire_at(self, name, ts: float) -> bool:
        with self._lock:
            o = self._live(name)
            if o is None:
                return False
            o["expire_at"] = float(ts)
            return True

    def clear_expire(self, name) -> bool:
        with self._lock:
            o = self._live(name)
            if o is None or o.get("expire_at") is None:
                return False
            o["expire_at"] = None
            return True

    def remain_ttl_ms(self, name) -> int:
        import time as _time

        with self._lock:
            o = self._live(name)
            if o is None:
                return -2
            if o.get("expire_at") is None:
                return -1
            return max(0, int((o["expire_at"] - _time.time()) * 1000))

    # Data-only dump wire format (no pickle — dump blobs may cross trust
    # boundaries; the reference's DUMP/RESTORE payload is data-only,
    # ADVICE r3): RTPH | u32 header_len | json header | npy arrays.
    # The header records the golden-model class by NAME and its int
    # scalars; arrays ride as concatenated .npy blobs in header order.
    _DUMP_MAGIC = b"RTPH"

    def dump(self, name):
        import io
        import json
        import struct

        with self._lock:
            o = self._live(name)
            if o is None:
                return None
            m = o["model"]
            scalars, arrays = {}, []
            for k_, v_ in vars(m).items():
                if isinstance(v_, np.ndarray):
                    arrays.append(k_)
                elif isinstance(v_, (int, np.integer)):
                    scalars[k_] = int(v_)
                else:  # pragma: no cover — golden models hold ints+arrays
                    raise TypeError(f"non-serializable model field {k_!r}")
            header = json.dumps(
                {
                    "v": 2,
                    "kind": o["kind"],
                    "params": dict(o["params"]),
                    "model_cls": type(m).__name__,
                    "scalars": scalars,
                    "arrays": arrays,
                    "topk": self.topk.export_state(name),
                }
            ).encode("utf-8")
            buf = io.BytesIO()
            for k_ in arrays:
                np.save(buf, getattr(m, k_), allow_pickle=False)
            return (
                self._DUMP_MAGIC
                + struct.pack("<I", len(header))
                + header
                + buf.getvalue()
            )

    # Per-class schemas for restore-time validation: dumps cross trust
    # boundaries, so field names, dtypes, shapes, and bounds are all
    # checked before a model is built (a forged blob must not create a
    # corrupt object or a giant allocation).
    _RESTORE_SCHEMAS = {
        "GoldenBloomFilter": {
            "scalars": {"size": (1, 1 << 33), "hash_iterations": (1, 64)},
            "arrays": {"bits": (np.bool_, lambda s: (s["size"],))},
        },
        "GoldenHyperLogLog": {
            "scalars": {},
            "arrays": {"regs": (np.uint8, lambda s: (golden.HLL_M,))},
        },
        "GoldenCountMinSketch": {
            "scalars": {"depth": (1, 64), "width": (1, 1 << 27)},
            "arrays": {
                "counts": (np.uint32, lambda s: (s["depth"], s["width"]))
            },
        },
        "GoldenBitSet": {
            "scalars": {},
            "arrays": {"bits": (np.bool_, None)},  # any 1-D length ≤ cap
        },
    }

    def restore(self, name, data: bytes, replace: bool = False) -> None:
        import io
        import json
        import struct

        from redisson_tpu.objects.durability import safe_load_npy

        if len(data) < 8 or data[:4] != self._DUMP_MAGIC:
            raise ValueError("not a host-sketch dump (bad magic)")
        (hlen,) = struct.unpack("<I", data[4:8])
        if hlen > 1 << 16:
            raise ValueError("dump header too large")
        d = json.loads(data[8 : 8 + hlen].decode("utf-8"))
        if d.get("v") != 2:
            raise ValueError(f"unsupported dump version: {d.get('v')}")
        cls_name = d.get("model_cls")
        schema = self._RESTORE_SCHEMAS.get(cls_name)
        if schema is None:
            raise ValueError(f"unknown model class {cls_name!r}")
        # kind must agree with the model class — a forged blob pairing
        # kind='cms' with a bloom model would create an object whose every
        # later op feeds the wrong model the wrong arguments.
        expected_kind = {
            "GoldenBloomFilter": PoolKind.BLOOM,
            "GoldenHyperLogLog": PoolKind.HLL,
            "GoldenCountMinSketch": PoolKind.CMS,
            "GoldenBitSet": PoolKind.BITSET,
        }[cls_name]
        if d.get("kind") != expected_kind:
            raise ValueError(
                f"dump kind {d.get('kind')!r} does not match {cls_name}"
            )
        if not isinstance(d.get("params"), dict):
            raise ValueError("dump params must be a dict")
        # Untrusted candidate table: validate BEFORE any mutation.
        topk_decoded = TopKStore.decode_state(d.get("topk"), name)
        cls = getattr(golden, cls_name)
        scalars = d.get("scalars", {})
        if set(scalars) != set(schema["scalars"]):
            raise ValueError(f"dump scalar fields {sorted(scalars)} do not "
                             f"match {cls_name}")
        for k_, (lo, hi) in schema["scalars"].items():
            v_ = int(scalars[k_])
            if not lo <= v_ <= hi:
                raise ValueError(f"dump field {k_}={v_} out of range")
            scalars[k_] = v_
        if list(d.get("arrays", [])) != list(schema["arrays"]):
            raise ValueError(f"dump array fields {d.get('arrays')} do not "
                             f"match {cls_name}")
        model = object.__new__(cls)
        for k_, v_ in scalars.items():
            setattr(model, k_, v_)
        buf = io.BytesIO(data[8 + hlen :])
        for k_, (want_dtype, want_shape) in schema["arrays"].items():
            arr = safe_load_npy(buf)
            if arr.dtype != want_dtype:
                raise ValueError(f"dump array {k_} has dtype {arr.dtype}")
            if want_shape is not None and arr.shape != want_shape(scalars):
                raise ValueError(f"dump array {k_} has shape {arr.shape}")
            if want_shape is None and (arr.ndim != 1 or arr.size > 1 << 33):
                raise ValueError(f"dump array {k_} has bad geometry")
            setattr(model, k_, arr.copy())  # writable (frombuffer is RO)
        with self._lock:
            if self._live(name) is not None:
                if not replace:
                    raise ValueError(f"BUSYKEY: {name!r} already exists")
                del self._objects[name]
            self._guard_foreign(name)
            self._objects[name] = {
                "kind": d["kind"],
                "model": model,
                "params": d["params"],
            }
        # Unconditional: replaces (or clears) any previous object's table
        # so a ghost heavy-hitter set never survives a replace.
        self.topk.import_decoded(topk_decoded, name)

    # -- bloom -------------------------------------------------------------

    def bloom_try_init(self, name, expected_insertions, false_probability) -> bool:
        m = golden.optimal_num_of_bits(
            expected_insertions, false_probability,
            max_bits=getattr(self.config.tpu_sketch, "max_bloom_bits",
                             golden.MAX_BLOOM_BITS),
        )
        k = golden.optimal_num_of_hash_functions(expected_insertions, m)
        with self._lock:
            if self._lookup_kind(name, PoolKind.BLOOM) is not None:
                return False
            self._guard_foreign(name)
            self._objects[name] = {
                "kind": PoolKind.BLOOM,
                "model": golden.GoldenBloomFilter(m, k),
                "params": {
                    "size": m,
                    "hash_iterations": k,
                    "expected_insertions": expected_insertions,
                    "false_probability": false_probability,
                },
            }
            return True

    def bloom_add(self, name, H1, H2):
        o = self._require(name, PoolKind.BLOOM)
        model: golden.GoldenBloomFilter = o["model"]
        h1m, h2m = hashing.km_reduce_mod(H1, H2, model.size)
        with self._lock:
            return ImmediateResult(model.add_hashed(h1m, h2m))

    def bloom_contains(self, name, H1, H2):
        o = self._require(name, PoolKind.BLOOM)
        model = o["model"]
        h1m, h2m = hashing.km_reduce_mod(H1, H2, model.size)
        with self._lock:
            return ImmediateResult(model.contains_hashed(h1m, h2m))

    def bloom_count(self, name):
        o = self._require(name, PoolKind.BLOOM)
        with self._lock:
            return ImmediateResult(o["model"].cardinality_estimate())

    def bloom_add_encoded(self, name, blocks, lengths):
        return self.bloom_add(name, *hashing.hash128_np(blocks, lengths))

    def bloom_contains_encoded(self, name, blocks, lengths):
        return self.bloom_contains(name, *hashing.hash128_np(blocks, lengths))

    def bloom_mixed_encoded(self, name, blocks, lengths, flags):
        """Ordered add/contains mix on one filter (front-door fused runs):
        consecutive same-flag spans apply in order under one lock hold,
        so results are bit-identical to the sequential command stream."""
        o = self._require(name, PoolKind.BLOOM)
        model = o["model"]
        H1, H2 = hashing.hash128_np(blocks, lengths)
        h1m, h2m = hashing.km_reduce_mod(H1, H2, model.size)
        flags = np.asarray(flags, bool)
        n = len(flags)
        out = np.empty(n, bool)
        with self._lock:
            i = 0
            while i < n:
                j = i + 1
                while j < n and flags[j] == flags[i]:
                    j += 1
                if flags[i]:
                    out[i:j] = model.add_hashed(h1m[i:j], h2m[i:j])
                else:
                    out[i:j] = model.contains_hashed(h1m[i:j], h2m[i:j])
                i = j
        return ImmediateResult(out)

    def bloom_replicate(self, name) -> bool:
        return False  # one host copy; nothing to spread reads across

    def bloom_is_replicated(self, name) -> bool:
        return False

    # -- hll ---------------------------------------------------------------

    def _hll(self, name):
        with self._lock:
            o = self._lookup_kind(name, PoolKind.HLL)
            if o is None:
                self._guard_foreign(name)
                o = {
                    "kind": PoolKind.HLL,
                    "model": golden.GoldenHyperLogLog(),
                    "params": {},
                }
                self._objects[name] = o
            return o

    def hll_add(self, name, c0, c1, c2):
        o = self._hll(name)
        with self._lock:
            model = o["model"]
            before = int(model.regs.sum())
            model.add_hashed(c0, c1, c2)
            return ImmediateResult(int(model.regs.sum()) != before)

    def hll_add_encoded(self, name, blocks, lengths):
        c0, c1, c2, _ = hashing.murmur3_x86_128(blocks, lengths)
        return self.hll_add(name, c0, c1, c2)

    def hll_count(self, name):
        o = self._lookup_kind(name, PoolKind.HLL)
        with self._lock:
            return ImmediateResult(0 if o is None else o["model"].count())

    def hll_count_with(self, name, other_names) -> int:
        with self._lock:
            regs = None
            for n in (name, *other_names):
                o = self._lookup_kind(n, PoolKind.HLL)
                if o is not None:
                    r = o["model"].regs
                    regs = r.copy() if regs is None else np.maximum(regs, r)
            if regs is None:
                return 0
            hist = np.bincount(regs, minlength=golden.HLL_Q + 2)
            return int(round(golden.ertl_estimate(hist)))

    def hll_merge_with(self, name, other_names) -> None:
        o = self._hll(name)
        with self._lock:
            for n in other_names:
                src = self._lookup_kind(n, PoolKind.HLL)
                if src is not None:
                    o["model"].merge(src["model"])

    # -- bitset ------------------------------------------------------------

    def _bitset(self, name):
        with self._lock:
            o = self._lookup_kind(name, PoolKind.BITSET)
            if o is None:
                self._guard_foreign(name)
                o = {
                    "kind": PoolKind.BITSET,
                    "model": golden.GoldenBitSet(),
                    "params": {},
                }
                self._objects[name] = o
            return o

    def bitset_capacity_bits(self, name) -> int:
        with self._lock:
            o = self._lookup_kind(name, PoolKind.BITSET)
            return 0 if o is None else o["model"].bits.size

    def bitset_set(self, name, idx, value: bool):
        o = self._bitset(name)
        with self._lock:
            return ImmediateResult(o["model"].set(np.asarray(idx, np.int64), value))

    def bitset_flip(self, name, idx):
        o = self._bitset(name)
        with self._lock:
            model = o["model"]
            idx = np.asarray(idx, np.int64)
            model._grow(int(idx.max()) + 1 if idx.size else 1)
            prev = np.empty(len(idx), bool)
            for j, ix in enumerate(idx):
                prev[j] = model.bits[ix]
                model.bits[ix] = not model.bits[ix]
            return ImmediateResult(prev)

    def bitset_get(self, name, idx):
        with self._lock:
            o = self._lookup_kind(name, PoolKind.BITSET)
            if o is None:
                return ImmediateResult(np.zeros(len(idx), bool))
            return ImmediateResult(o["model"].get(np.asarray(idx, np.int64)))

    def bitset_set_range(self, name, from_bit, to_bit, value: bool):
        o = self._bitset(name)
        with self._lock:
            model = o["model"]
            model._grow(int(to_bit))
            model.bits[int(from_bit) : int(to_bit)] = value
            return ImmediateResult(None)

    def bitset_cardinality(self, name) -> int:
        with self._lock:
            o = self._lookup_kind(name, PoolKind.BITSET)
            return 0 if o is None else o["model"].cardinality()

    def bitset_length(self, name) -> int:
        with self._lock:
            o = self._lookup_kind(name, PoolKind.BITSET)
            return 0 if o is None else o["model"].length()

    def bitset_bitpos(self, name, target_bit: int) -> int:
        with self._lock:
            o = self._lookup_kind(name, PoolKind.BITSET)
            if o is None:
                return -1 if target_bit else 0
            bits = o["model"].bits
            matches = np.nonzero(bits == bool(target_bit))[0]
            return int(matches[0]) if matches.size else (-1 if target_bit else bits.size)

    def bitset_bitop(self, dest, src_names, op: str) -> None:
        """Redis BITOP: sources are zero-padded to the max source length
        (without mutating them), dest is replaced entirely; NOT complements
        its single source's byte-aligned string (padding bits up to the
        byte boundary flip to 1, as on a real Redis value) — mirrors
        TpuSketchEngine."""
        with self._lock:
            srcs = [self._bitset(n)["model"] for n in src_names]
            if op == "not":
                size = -(-srcs[0].bits.size // 8) * 8
                res = np.ones(size, dtype=bool)
                res[: srcs[0].bits.size] = ~srcs[0].bits
            else:
                size = max((s.bits.size for s in srcs), default=0)

                def padded(b):
                    if b.size == size:
                        return b
                    p = np.zeros(size, dtype=bool)
                    p[: b.size] = b
                    return p

                fn = {"and": np.logical_and, "or": np.logical_or, "xor": np.logical_xor}[op]
                res = padded(srcs[0].bits).copy()
                for s in srcs[1:]:
                    res = fn(res, padded(s.bits))
            d = self._bitset(dest)["model"]
            d.bits = np.array(res, dtype=bool)

    def bitset_to_bytes(self, name) -> bytes:
        with self._lock:
            o = self._lookup_kind(name, PoolKind.BITSET)
            if o is None:
                return b""
            return np.packbits(o["model"].bits, bitorder="little").tobytes()

    # -- cms ---------------------------------------------------------------

    def cms_try_init(self, name, depth, width) -> bool:
        with self._lock:
            if self._lookup_kind(name, PoolKind.CMS) is not None:
                return False
            self._guard_foreign(name)
            self._objects[name] = {
                "kind": PoolKind.CMS,
                "model": golden.GoldenCountMinSketch(depth, width),
                "params": {"depth": depth, "width": width},
            }
            return True

    def cms_total(self, name) -> int:
        o = self._require(name, PoolKind.CMS)
        with self._lock:
            return int(np.asarray(o["model"].counts[0], np.uint64).sum())

    def cms_reset(self, name) -> None:
        o = self._require(name, PoolKind.CMS)
        with self._lock:
            o["model"].counts[:] = 0

    def cms_add(self, name, H1, H2, weights):
        o = self._require(name, PoolKind.CMS)
        model: golden.GoldenCountMinSketch = o["model"]
        h1w, h2w = hashing.km_reduce_mod(H1, H2, model.width)
        with self._lock:
            model.add_hashed(h1w, h2w, weights)
            return ImmediateResult(
                model.estimate_hashed(h1w, h2w).astype(np.uint32)
            )

    def cms_estimate(self, name, H1, H2):
        o = self._require(name, PoolKind.CMS)
        model = o["model"]
        h1w, h2w = hashing.km_reduce_mod(H1, H2, model.width)
        with self._lock:
            return ImmediateResult(model.estimate_hashed(h1w, h2w).astype(np.uint32))

    def cms_add_seq(self, name, H1, H2, weights):
        """Exact-streaming semantics (parity with the TPU Pallas path):
        one-op-at-a-time through the golden model."""
        o = self._require(name, PoolKind.CMS)
        model = o["model"]
        h1w, h2w = hashing.km_reduce_mod(H1, H2, model.width)
        weights = np.asarray(weights, np.uint32)
        with self._lock:
            est = np.zeros(len(h1w), np.uint32)
            for j in range(len(h1w)):
                model.add_hashed(h1w[j : j + 1], h2w[j : j + 1], weights[j : j + 1])
                est[j] = model.estimate_hashed(h1w[j : j + 1], h2w[j : j + 1])[0]
            return ImmediateResult(est)

    def cms_merge(self, name, other_names) -> None:
        o = self._require(name, PoolKind.CMS)
        with self._lock:
            for n in other_names:
                src = self._require(n, PoolKind.CMS)
                if (
                    src["params"]["depth"] != o["params"]["depth"]
                    or src["params"]["width"] != o["params"]["width"]
                ):
                    raise ValueError("cannot merge CMS with different geometry")
                o["model"].merge(src["model"])
