"""HyperLogLog — parity with org/redisson/api/RHyperLogLog.java /
org/redisson/RedissonHyperLogLog.java.

The reference is a thin PFADD/PFCOUNT/PFMERGE wrapper (SURVEY.md §2.2);
here the register math runs on device (ops/hll.py) with Redis geometry
(p=14, registers 0..51) and the Ertl estimator.
"""

from __future__ import annotations

from redisson_tpu.objects.base import RObject
from redisson_tpu.tenancy import PoolKind


class HyperLogLog(RObject):
    KIND = PoolKind.HLL

    # Batch pipelining (SURVEY.md §3.4): sync-named adds coalesce.
    _DEFERRED = {
        "add": "add_deferred",
        "add_all": "add_deferred_all",
    }

    def add_deferred(self, obj):
        from redisson_tpu.objects.base import MappedFuture

        return MappedFuture(self.add_async(obj), bool)

    def add_deferred_all(self, objs):
        from redisson_tpu.objects.base import MappedFuture

        return MappedFuture(self.add_all_async(objs), bool)

    def add(self, obj) -> bool:
        """→ RHyperLogLog#add: True iff the estimate changed (a register
        grew).  ``obj`` is ONE key, wrapped explicitly — a tuple/list
        argument is a legal single key under pickle-style codecs (the
        batch form would hash its ELEMENTS as separate keys)."""
        return bool(self.add_all_async([obj]).result())

    def add_all(self, objs) -> bool:
        """→ RHyperLogLog#addAll(Collection)."""
        return bool(self.add_all_async(objs).result())

    def add_all_async(self, objs):
        return self._engine.hll_add_encoded(self._name, *self._encode(objs))

    add_async = add_all_async

    def count(self) -> int:
        """→ RHyperLogLog#count (PFCOUNT)."""
        return int(self._engine.hll_count(self._name).result())

    def count_with(self, *other_names: str) -> int:
        """→ RHyperLogLog#countWith (PFCOUNT key [key ...]): union
        cardinality without mutating any operand."""
        return self._engine.hll_count_with(self._name, other_names)

    def merge_with(self, *other_names: str) -> None:
        """→ RHyperLogLog#mergeWith (PFMERGE)."""
        self._engine.hll_merge_with(self._name, other_names)
