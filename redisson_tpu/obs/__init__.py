"""Unified observability subsystem (ISSUE 1 tentpole).

One ``Observability`` bundle per engine/client ties together:

- ``registry`` — labeled counters / gauges / log2 histograms
  (obs/registry.py) rendered as typed Prometheus text;
- ``spans`` — per-launch lifecycle spans feeding phase histograms
  (obs/spans.py);
- ``slowlog`` — the SLOWLOG-compatible slow-op ring (obs/slowlog.py),
  surfaced over RESP by serve/resp.py.

The pre-built families below are the instrumentation points the rest of
the codebase uses; everything is lazy-cheap when nothing reads it.
"""

from __future__ import annotations

from redisson_tpu.obs.events import EventRing
from redisson_tpu.obs.latency import LatencyMonitor
from redisson_tpu.obs.loadmap import LoadMap
from redisson_tpu.obs.registry import Family, MetricsRegistry
from redisson_tpu.obs.slowlog import SlowLog, SlowLogEntry
from redisson_tpu.obs.spans import OpSpan, SpanRecorder
from redisson_tpu.obs.trace import Tracer


class Observability:
    def __init__(self, slowlog_max_len: int = 128,
                 slowlog_threshold_us: int = 10_000,
                 trace_sample_rate: float = 0.0,
                 trace_max_spans: int = 2048,
                 latency_threshold_ms: int = 0):
        r = MetricsRegistry()
        self.registry = r
        # Fleet telemetry plane (ISSUE 13): latency monitor + tracer
        # volume counters come FIRST so the recorders below can ride
        # them.
        self.latency_events = r.counter(
            "rtpu_latency_events",
            "latency-monitor samples recorded, by event "
            "(command | slow-launch | fsync-stall | breaker-open | "
            "migration | reconcile | election | rebalance-wave | "
            "full-resync)", ("event",))
        self.trace_sampled = r.counter(
            "rtpu_trace_sampled",
            "requests head-sampled into a distributed trace")
        self.trace_spans = r.counter(
            "rtpu_trace_spans",
            "trace spans recorded into the bounded per-process ring")
        self.latency = LatencyMonitor(
            latency_threshold_ms, counter=self.latency_events)
        self.trace = Tracer(
            trace_sample_rate, max_spans=trace_max_spans,
            sampled_counter=self.trace_sampled,
            span_counter=self.trace_spans,
        )
        self.spans = SpanRecorder(r, latency=self.latency)
        self.slowlog = SlowLog(slowlog_max_len, slowlog_threshold_us)
        # RESP front door (per-command dimension).
        self.resp_commands = r.counter(
            "rtpu_resp_commands", "RESP commands processed", ("cmd",))
        self.resp_errors = r.counter(
            "rtpu_resp_errors", "RESP commands that returned an error",
            ("cmd",))
        self.resp_latency = r.histogram(
            "rtpu_resp_command_seconds", "RESP command execution time",
            ("cmd",))
        # Engine submit (per-tenant / per-object-type dimensions).
        self.tenant_ops = r.counter(
            "rtpu_tenant_ops", "sketch ops submitted, by tenant and op",
            ("tenant", "op"), max_children=2048)
        self.tenant_calls = r.counter(
            "rtpu_tenant_calls", "sketch API calls, by tenant and kind",
            ("tenant", "kind"), max_children=2048)
        # Executor dispatch (per-method; per-shard in sharded mode).
        self.dispatches = r.counter(
            "rtpu_dispatches", "executor dispatches, by method", ("method",))
        self.dispatch_ops = r.counter(
            "rtpu_dispatch_ops", "ops dispatched, by executor method",
            ("method",))
        self.dispatch_seconds = r.histogram(
            "rtpu_dispatch_enqueue_seconds",
            "host-side dispatch enqueue time, by method", ("method",))
        self.shard_ops = r.counter(
            "rtpu_shard_ops", "ops routed to each mesh shard", ("shard",))
        # Robustness (ISSUE 3): degraded-mode serving + chaos injection.
        # Breaker state itself is a render-time gauge (rtpu_breaker_state,
        # registered by the engine's health-gauge wiring).
        self.degraded_ops = r.counter(
            "rtpu_degraded_ops",
            "ops served from the host golden mirror while a breaker was "
            "open, by op kind", ("op",))
        self.faults_injected = r.counter(
            "rtpu_faults_injected",
            "chaos faults injected, by fault point and kind",
            ("point", "kind"))
        # Overload control plane (ISSUE 7): pre-dispatch shedding by
        # reason (deadline | admission | tenant | ingress), deadline
        # failures by stage (submit | admission | queue | fetch_wait),
        # per-tenant throttles, fetch timeouts (the breaker-feeding
        # kind), and slow-client disconnects.  The admission wait
        # estimate itself is a render-time gauge the engine registers
        # (rtpu_admission_est_wait_us).
        self.shed_ops = r.counter(
            "rtpu_shed_ops",
            "ops shed pre-dispatch by the overload control plane, "
            "by reason", ("reason",))
        self.deadline_exceeded = r.counter(
            "rtpu_deadline_exceeded",
            "ops that failed with DeadlineExceededError, by stage",
            ("stage",))
        self.tenant_throttled = r.counter(
            "rtpu_tenant_throttled",
            "ops shed by per-tenant quotas, by tenant",
            ("tenant",), max_children=2048)
        self.fetch_timeouts = r.counter(
            "rtpu_fetch_timeouts",
            "blocking result waits that hit the fetch timeout, by op",
            ("op",))
        self.slow_client_disconnects = r.counter(
            "rtpu_slow_client_disconnects",
            "connections dropped by the output-buffer limits, by cause",
            ("cause",))
        self.resp_ingress_shed = r.counter(
            "rtpu_resp_ingress_shed",
            "RESP commands (or transactions) refused at ingress, by "
            "reason (pressure = admission watermark, tenant = over-quota "
            "tenant peek) — COMMAND-denominated, unlike the "
            "ops-denominated rtpu_shed_ops", ("reason",))
        # Durability tier (ISSUE 10): the op journal's append volume,
        # group-commit fsync latency, and recovery replay count.  Lag
        # (appended-but-unfsynced records) and live segment count are
        # render-time gauges the engine registers
        # (rtpu_journal_lag_ops / rtpu_journal_segments).
        self.journal_records = r.counter(
            "rtpu_journal_records",
            "op records appended to the durability journal")
        self.journal_bytes = r.counter(
            "rtpu_journal_bytes",
            "bytes appended to the durability journal")
        self.journal_fsync_us = r.histogram(
            "rtpu_journal_fsync_us",
            "journal group-commit fsync latency")
        self.journal_replayed = r.counter(
            "rtpu_journal_replayed",
            "journal records replayed through the golden engine at "
            "recovery")
        # Near cache (ISSUE 4): hit/miss by result kind; evictions and
        # live byte occupancy are store-side (evictions inc'd via the
        # store's on_evict hook, bytes a render-time gauge registered by
        # the engine).
        self.nearcache_hits = r.counter(
            "rtpu_nearcache_hits",
            "reads answered from the host near cache, by object kind",
            ("kind",))
        self.nearcache_misses = r.counter(
            "rtpu_nearcache_misses",
            "near-cache probes that went to the device, by object kind",
            ("kind",))
        self.nearcache_evictions = r.counter(
            "rtpu_nearcache_evictions",
            "near-cache entries evicted (quota or budget pressure)")
        # Tiered residency (ISSUE 14): SWAPIN/SWAPOUT-style transition
        # volume for the heat-based ladder (storage/residency.py);
        # tier occupancy (device rows in use, host/disk bytes) is a
        # set of render-time gauges the engine registers.
        self.residency_promotions = r.counter(
            "rtpu_residency_promotions",
            "sketches promoted back to a device row (host/disk tier "
            "→ device, through the prewarmed size-class pools)")
        self.residency_demotions = r.counter(
            "rtpu_residency_demotions",
            "sketches demoted from a device row to an exact host "
            "golden mirror (demoted is NOT degraded)")
        self.residency_spills = r.counter(
            "rtpu_residency_spills",
            "host mirrors spilled to CRC-framed per-object disk blobs")
        self.residency_loads = r.counter(
            "rtpu_residency_loads",
            "disk blobs loaded back into host mirrors (first touch of "
            "a DISK-resident sketch)")
        # Front door vectorization (ISSUE 6): pipelined command runs fused
        # into single engine launches, plus the per-connection response
        # cache for repeated identical reads inside one pipeline window.
        self.resp_fused_cmds = r.counter(
            "rtpu_resp_fused_cmds",
            "RESP commands absorbed into fused front-door runs, by family",
            ("family",))
        self.resp_fused_ops = r.counter(
            "rtpu_resp_fused_ops",
            "engine ops carried by fused front-door runs, by family",
            ("family",))
        self.resp_fused_runs = r.counter(
            "rtpu_resp_fused_runs",
            "fused front-door runs dispatched, by family", ("family",))
        self.resp_cache_hits = r.counter(
            "rtpu_resp_response_cache_hits",
            "pipelined replies served from the per-connection response "
            "cache")
        self.resp_cache_misses = r.counter(
            "rtpu_resp_response_cache_misses",
            "response-cache probes that executed the command")
        # Reactor front door (ISSUE 11): epoll event-loop ticks, how many
        # connections each tick found ready, and ops that fused into an
        # engine launch TOGETHER WITH ops from other connections (the
        # cross-connection batch-economics headline — within-connection
        # fusion is already counted by rtpu_resp_fused_ops).
        self.reactor_ticks = r.counter(
            "rtpu_reactor_ticks",
            "reactor event-loop ticks that processed at least one event")
        self.reactor_ready_conns = r.counter(
            "rtpu_reactor_ready_conns",
            "connections found ready across reactor ticks (avg per tick "
            "= this / rtpu_reactor_ticks)")
        self.cross_conn_fused_ops = r.counter(
            "rtpu_cross_conn_fused_ops",
            "engine ops fused into a launch together with ops from OTHER "
            "connections, by family", ("family",))
        # Per-core front door (ISSUE 17): K SO_REUSEPORT reactor
        # processes per node, an in-node slot→process map, and loopback
        # handoff legs over unix-domain sockets.  Per-worker series
        # federate through the existing plane — each worker process
        # serves its own /metrics, the parent's federation endpoint
        # labels them.
        self.frontdoor_processes = r.gauge(
            "rtpu_frontdoor_processes",
            "front-door worker processes sharing this node's listen "
            "port (1 = single-process door, incl. the no-SO_REUSEPORT "
            "fallback)")
        self.frontdoor_worker_index = r.gauge(
            "rtpu_frontdoor_worker_index",
            "this worker's index in the node's in-node slot->process "
            "map (0 in single-process mode)")
        self.frontdoor_handoffs = r.counter(
            "rtpu_frontdoor_handoffs",
            "commands routed across the in-node worker boundary, by "
            "kind (forward = whole command to one sibling, split = "
            "per-key multi-key split, fanout = broadcast-and-merge)",
            ("kind",))
        self.frontdoor_handoff_errors = r.counter(
            "rtpu_frontdoor_handoff_errors",
            "in-node handoff legs that failed (peer gone / corrupt "
            "stream / injected fault) and surfaced -HANDOFFBROKEN",
            ("kind",))
        self.frontdoor_peer_accepts = r.counter(
            "rtpu_frontdoor_peer_accepts",
            "handoff legs accepted from sibling workers on the in-node "
            "unix-domain listener")
        # Cluster mode (ISSUE 12): redirect volume by kind (the door
        # counts moved/ask/tryagain/crossslot/asking_served as it emits
        # or honors them; the slot-aware client counts
        # client_moved/client_ask as it follows them), slot-ownership
        # handoffs this process finalized, and the scatter/gather
        # client's fan-out (legs / batches = average nodes touched per
        # multi-slot batch).
        self.cluster_redirects = r.counter(
            "rtpu_cluster_redirects",
            "cluster redirects emitted by the door or followed by the "
            "slot-aware client, by kind", ("kind",))
        self.cluster_slot_migrations = r.counter(
            "rtpu_cluster_slot_migrations",
            "slot ownership handoffs finalized on this node (SETSLOT "
            "NODE closing an IMPORTING/MIGRATING state)")
        self.cluster_scatter_fanout = r.counter(
            "rtpu_cluster_scatter_fanout",
            "scatter/gather batches and the per-node pipeline legs they "
            "fanned out to, by unit", ("unit",))
        # Load-attribution plane (ISSUE 16): billing-grade device-time
        # split per (tenant, op) — label cardinality is bounded TWICE
        # (the loadmap folds cold tenants into "other" before the bump,
        # max_children backstops it), and the per-slot planes export as
        # render-time gauges over the top-N busiest slots only (a
        # 16384-label family would melt any scrape).
        self.tenant_device_us = r.counter(
            "rtpu_tenant_device_us",
            "device-side launch microseconds attributed by tenant and "
            "op (top-N tenants, cold ones fold into 'other')",
            ("tenant", "op"), max_children=256)
        self.loadmap = LoadMap()
        self.loadmap.tenant_device_us_family = self.tenant_device_us
        self.spans.loadmap = self.loadmap
        _lm = self.loadmap
        r.gauge_callback(
            "rtpu_loadmap_slot_ops",
            "commands accounted to the busiest slots (top-8 by ops — "
            "bounded export of the 16384-slot load vector)",
            lambda: {(str(s),): float(v) for s, v in _lm.top_slots(8)},
            labelnames=("slot",))
        r.gauge_callback(
            "rtpu_loadmap_sampled_keys",
            "keys sampled into the hot-key sketches at RESP ingress",
            _lm.sampled_keys)
        r.gauge_callback(
            "rtpu_loadmap_tracked_keys",
            "candidate keys currently monitored by the space-saving "
            "top-k",
            _lm.tracked_keys)
        # Replication + failover plane (ISSUE 18): stream/ack volume
        # counters plus offset/lag gauges.  The gauges read through
        # source callables the RESP door wires once the replication hub
        # or replica link exists — 0.0 until then, so the families are
        # present (and doc-tabled) on every process regardless of role.
        self.repl_acks = r.counter(
            "rtpu_repl_acks",
            "REPLCONF ACK frames accepted from replicas (primary side)")
        self.repl_fullresyncs = r.counter(
            "rtpu_repl_fullresyncs",
            "full resynchronizations served (snapshot + stream tail "
            "bootstrap) or performed (replica side)")
        self.repl_partial_resyncs = r.counter(
            "rtpu_repl_partial_resyncs",
            "partial resynchronizations (PSYNC CONTINUE on a matching "
            "replication id + backlog-covered offset)")
        self.repl_stream_records = r.counter(
            "rtpu_repl_stream_records",
            "journal records applied from the replication stream "
            "(replica side)")
        self.failover_elections = r.counter(
            "rtpu_failover_elections",
            "failover elections this node started as a candidate")
        self.failover_takeovers = r.counter(
            "rtpu_failover_takeovers",
            "slot takeovers this node performed after winning an "
            "election (or via manual FAILOVER promotion)")
        # Autonomous rebalancer (ISSUE 19).  `decisions` kinds: planned
        # (moves a wave scheduled), moved, failed, and the last-moment
        # vetoes skip_busy / skip_stale / skip_failover.
        self.rebalancer_decisions = r.counter(
            "rtpu_rebalancer_decisions",
            "rebalancer planning/execution decisions by kind",
            ("kind",))
        self.rebalancer_keys_moved = r.counter(
            "rtpu_rebalancer_keys_moved",
            "keys migrated by rebalancer-driven slot moves")
        self.rebalancer_migration_seconds = r.histogram(
            "rtpu_rebalancer_migration_seconds",
            "wall seconds per rebalancer-driven slot migration")
        self.rebalancer_imbalance_source = None  # wired by the agent
        r.gauge_callback(
            "rtpu_rebalancer_imbalance_ratio",
            "fleet imbalance (max node load / mean) from the planner's "
            "smoothed heat model; 1.0 = perfectly level",
            lambda: float(self.rebalancer_imbalance_source())
            if self.rebalancer_imbalance_source is not None else 1.0)
        # Fleet flight recorder + invariant doctor (ISSUE 20): the
        # control planes' causal event record (obs/events.py) and the
        # continuous protocol auditor (obs/doctor.py).  Kind label
        # cardinality is bounded by the events.KINDS catalog (rtpulint
        # RT015 rejects unregistered kind literals at lint time).
        self.events_emitted = r.counter(
            "rtpu_events_emitted",
            "flight-recorder events emitted, by kind (bounded by the "
            "events.KINDS catalog)", ("kind",))
        self.events_evicted = r.counter(
            "rtpu_events_evicted",
            "flight-recorder events evicted from the bounded ring "
            "(visible downstream as per-node seq gaps)")
        self.events = EventRing(
            counter=self.events_emitted,
            evicted_counter=self.events_evicted)
        self.doctor_sweeps = r.counter(
            "rtpu_doctor_sweeps",
            "invariant-doctor sweeps completed on this node (only the "
            "elected coordinator sweeps)")
        self.doctor_findings = r.counter(
            "rtpu_doctor_findings",
            "invariant findings raised by the doctor, by kind",
            ("kind",))
        self.doctor_canary_rtt_us = r.histogram(
            "rtpu_doctor_canary_rtt_us",
            "black-box canary round trip (WAIT-fenced write-then-read "
            "through the real client path)")
        self.repl_offset_source = None  # wired by the RESP door
        self.repl_lag_source = None
        r.gauge_callback(
            "rtpu_repl_offset",
            "replication offset: journal head seq on a primary, last "
            "applied stream seq on a replica",
            lambda: float(self.repl_offset_source())
            if self.repl_offset_source is not None else 0.0)
        r.gauge_callback(
            "rtpu_repl_lag_ops",
            "replica staleness in journal records (master_offset - "
            "applied; 0 on a primary)",
            lambda: float(self.repl_lag_source())
            if self.repl_lag_source is not None else 0.0)

    # -- instrumentation helpers (one call per batch, never per op) --------

    def record_resp_command(self, cmd: str, duration_s: float,
                            error: bool) -> None:
        self.resp_commands.inc((cmd,))
        if error:
            self.resp_errors.inc((cmd,))
        self.resp_latency.observe((cmd,), duration_s)

    def record_dispatch(self, method: str, nops: int, dur_s: float) -> None:
        self.dispatches.inc((method,))
        self.dispatch_ops.inc((method,), nops)
        self.dispatch_seconds.observe((method,), dur_s)

    def record_shard_counts(self, counts) -> None:
        for s, c in enumerate(counts):
            if c:
                self.shard_ops.inc((str(s),), int(c))

    def reset_command_stats(self) -> None:
        """CONFIG RESETSTAT: zero the RESP per-command families."""
        self.resp_commands.reset()
        self.resp_errors.reset()
        self.resp_latency.reset()

    def reset_op_stats(self) -> None:
        """Zero the span-derived families — benches call this after
        warmup so compile-era samples don't pollute the warm-path
        evidence view (op_stats / phase_stats).  Delegates to the
        recorder's PUBLIC reset() (ISSUE 13 satellite: reaching into
        ``spans._phase_hist`` etc. from here coupled the bench lifecycle
        to SpanRecorder privates); the trace ring shares the same
        lifecycle call."""
        self.spans.reset()
        self.trace.reset()

    # -- snapshot views ----------------------------------------------------

    def command_stats(self) -> dict:
        """{cmd: {calls, errors, usec, usec_per_call}} for INFO
        commandstats and client.get_metrics()."""
        out = {}
        errs = {lv: c.value for lv, c in self.resp_errors.items()}
        lat = dict(self.resp_latency.items())
        for (cmd,), c in self.resp_commands.items():
            calls = int(c.value)
            h = lat.get((cmd,))
            usec = int((h.sum if h is not None else 0.0) * 1e6)
            out[cmd] = {
                "calls": calls,
                "errors": int(errs.get((cmd,), 0)),
                "usec": usec,
                "usec_per_call": round(usec / calls, 2) if calls else 0.0,
            }
        return out

    def latency_stats(self) -> dict:
        """{cmd: {p50_us, p99_us, p999_us}} for INFO latencystats."""
        out = {}
        for (cmd,), c in self.resp_latency.items():
            if c.count == 0:
                continue
            p50, p99, p999 = self.resp_latency.percentiles(
                (cmd,), (50, 99, 99.9))
            out[cmd] = {
                "p50_us": p50 * 1e6,
                "p99_us": p99 * 1e6,
                "p999_us": p999 * 1e6,
            }
        return out

    def op_stats(self) -> dict:
        """{op: {ops, launches, p50_ms, p99_ms}} from the span
        histograms — the per-command latency view of the ENGINE (bench
        snapshots report this one)."""
        out = {}
        ops = {lv: c.value for lv, c in self.spans._ops.items()}
        for (op,), c in self.spans._total_hist.items():
            if c.count == 0:
                continue
            p50, p99 = self.spans._total_hist.percentiles((op,), (50, 99))
            out[op] = {
                "ops": int(ops.get((op,), 0)),
                "launches": int(c.count),
                "p50_ms": p50 * 1e3,
                "p99_ms": p99 * 1e3,
            }
        return out

    def phase_stats(self) -> dict:
        """{op: {phase: {launches, p50_ms, p99_ms}}} from the
        lifecycle-span phase histograms (coalesce_wait / host_stage /
        device_dispatch / d2h_fetch) — the warm-path evidence view:
        BENCH snapshots embed it so a latency regression is attributable
        to a specific phase from the JSON alone."""
        out: dict = {}
        h = self.spans._phase_hist
        for (op, phase), c in h.items():
            if c.count == 0:
                continue
            p50, p99 = h.percentiles((op, phase), (50, 99))
            out.setdefault(op, {})[phase] = {
                "launches": int(c.count),
                "p50_ms": round(p50 * 1e3, 3),
                "p99_ms": round(p99 * 1e3, 3),
            }
        return out

    def tenant_stats(self) -> dict:
        """{tenant: ops} aggregated over op types."""
        out: dict = {}
        for (tenant, _op), c in self.tenant_ops.items():
            out[tenant] = out.get(tenant, 0) + int(c.value)
        return out


__all__ = [
    "EventRing",
    "Family",
    "LatencyMonitor",
    "LoadMap",
    "MetricsRegistry",
    "Observability",
    "OpSpan",
    "SlowLog",
    "SlowLogEntry",
    "SpanRecorder",
    "Tracer",
]
