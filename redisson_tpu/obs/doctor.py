"""Continuous invariant doctor (ISSUE 20 tentpole part 2): the netsim
models' offline invariants — no dual primary, offsets converge, no
stuck migration, epochs only grow — re-run continuously against the
LIVE fleet, Slicer-style (PAPERS.md §3 ships its assigner with exactly
this kind of production-time self-checking).

One :class:`FleetDoctor` daemon thread per armed node (``--doctor``),
coordinator-elected like the rebalancer (lowest-id alive primary) so
exactly one node audits at a time while every armed node stays warm
for takeover.  Each sweep:

- **liveness** — probe every node (``RTPU.CLUSTERPING``, one retry);
  a dead PRIMARY still owning slots is the ``dead-primary`` finding
  (the unavailability window the failover exists to close);
- **slot ownership** — every slot owned by exactly one alive primary
  in this node's map (``unassigned-slots``), and every reachable
  peer's ``CLUSTER SHARDS`` agrees with the coordinator's view
  (``topology-divergence``);
- **replication** — per-node offsets from the ping replies must be
  monotone sweep-over-sweep (``offset-regression``: acked history
  vanished) and replica lag within ``lag_bound_ops`` (``repl-lag``);
- **epochs** — a node reporting a SMALLER epoch than its last sweep
  lost coordination state (``epoch-regression``);
- **migrations** — a slot stuck MIGRATING/IMPORTING longer than
  ``stuck_slot_s`` (``stuck-migration``: an operator or pump died
  mid-reshard, the slot is serving redirects forever);
- **canary** — a black-box WAIT-fenced write-then-read probe per
  primary through the real client path, on a reserved hash-tag
  keyspace (``{__rtpu-doctor-N}``): true availability and acked-write
  durability measured from OUTSIDE the process (``canary``).

Findings are STATE, not edges: each sweep recomputes the active set,
newly-raised ones emit ``doctor.finding`` (+ the
``rtpu_doctor_findings`` counter by kind), resolved ones emit
``doctor.clear`` — so a chaos window reads as raise → (failover fixes
the fleet) → clear, and a clean fleet stays at zero findings (the
zero-false-positive bar in tests/test_doctor.py).

``CLUSTER DOCTOR`` serves the human-readable report (the LATENCY
DOCTOR analog for the cluster plane); ``CLUSTER DOCTOR STATUS`` the
JSON; PAUSE/RESUME/NOW mirror the rebalancer's verbs.
"""

from __future__ import annotations

import socket
import threading
import time
from typing import Optional

from redisson_tpu.analysis import witness as _witness
from redisson_tpu.serve.wireutil import ReplyError, exchange

# Finding kinds (bounded: the rtpu_doctor_findings label dimension).
FINDING_KINDS = (
    "dead-primary",
    "unassigned-slots",
    "topology-divergence",
    "offset-regression",
    "repl-lag",
    "epoch-regression",
    "stuck-migration",
    "canary",
)

_SEVERITY = {
    "dead-primary": "error",
    "unassigned-slots": "error",
    "topology-divergence": "warn",
    "offset-regression": "error",
    "repl-lag": "warn",
    "epoch-regression": "error",
    "stuck-migration": "warn",
    "canary": "error",
}


def canary_key(node_id: str, slotmap, limit: int = 4096) -> Optional[str]:
    """A key in the reserved ``{__rtpu-doctor-N}`` hash-tag keyspace
    whose slot is owned by ``node_id`` — the per-node canary target.
    Deterministic scan so every doctor agrees on the key; None when the
    node owns no slots (nothing to probe)."""
    from redisson_tpu.cluster.slots import key_slot

    for i in range(limit):
        tag = f"__rtpu-doctor-{i}"
        if slotmap.owner(key_slot(tag.encode())) == node_id:
            return "{%s}:canary" % tag
    return None


class FleetDoctor(threading.Thread):
    """The sweep loop + finding ledger.  Construction registers the
    agent as ``server.doctor`` (the CLUSTER DOCTOR / INFO doctor
    surface); ``start()`` arms the loop."""

    def __init__(self, server, interval_s: float = 1.0,
                 stuck_slot_s: float = 30.0,
                 lag_bound_ops: int = 10_000,
                 canary: bool = True,
                 canary_timeout_ms: int = 500):
        super().__init__(name="rtpu-doctor", daemon=True)
        if server.cluster is None:
            raise ValueError("fleet doctor requires cluster mode")
        self.server = server
        self.myid = server.cluster.myid
        self.slotmap = server.cluster.slotmap
        self.obs = server.obs
        self.interval_s = float(interval_s)
        self.stuck_slot_s = float(stuck_slot_s)
        self.lag_bound_ops = int(lag_bound_ops)
        self.canary_enabled = bool(canary)
        self.canary_timeout_ms = int(canary_timeout_ms)
        self.paused = False
        self.sweeps = 0
        self.findings_total = 0
        self.canary_failures = 0
        self.last_sweep_ms = 0.0
        self.last_down: set = set()
        # finding key ("kind:subject") -> {"kind", "severity",
        # "subject", "detail", "since" (wall)} — the active ledger.
        self.active: dict = {}
        # Sweep-over-sweep memory for the monotonicity checks.
        self._last_seen: dict = {}  # node -> {"epoch","offset","role"}
        self._mig_first_seen: dict = {}  # (node, slot, state) -> mono
        self._canary_seq = 0
        self._tick_lock = _witness.named(threading.Lock(), "doctor.tick")
        self._kick = threading.Event()
        self._stop_evt = threading.Event()
        server.doctor = self

    def stop(self, join_timeout_s: float = 5.0) -> None:
        self._stop_evt.set()
        self._kick.set()
        if self.is_alive():
            self.join(timeout=join_timeout_s)

    # -- control surface (CLUSTER DOCTOR) ----------------------------------

    def pause(self) -> None:
        self.paused = True

    def resume(self) -> None:
        self.paused = False

    def status(self) -> dict:
        excluded = self.last_down | self._failover_failed()
        coord = self._coordinator(excluded)
        return {
            "enabled": True,
            "paused": self.paused,
            "coordinator": coord,
            "is_coordinator": coord == self.myid,
            "interval_ms": int(self.interval_s * 1000),
            "stuck_slot_ms": int(self.stuck_slot_s * 1000),
            "lag_bound_ops": self.lag_bound_ops,
            "canary_enabled": self.canary_enabled,
            "sweeps": self.sweeps,
            "findings_total": self.findings_total,
            "canary_failures": self.canary_failures,
            "last_sweep_ms": round(self.last_sweep_ms, 3),
            "down": sorted(self.last_down),
            "active_findings": [
                dict(f) for _, f in sorted(self.active.items())
            ],
        }

    def report(self, last_events: int = 12) -> str:
        """CLUSTER DOCTOR: the human diagnosis (the LATENCY DOCTOR
        analog) — fleet state, active findings, recent control-plane
        events from this node's flight recorder."""
        st = self.status()
        lines = [
            f"Fleet doctor on {self.myid} "
            f"(coordinator: {st['coordinator'] or 'none'}"
            f"{', me' if st['is_coordinator'] else ''}; "
            f"sweeps {st['sweeps']}, interval {st['interval_ms']} ms"
            f"{', PAUSED' if st['paused'] else ''}):"
        ]
        for nid in self.slotmap.node_ids():
            role = self.slotmap.role(nid)
            owned = sum(
                b - a + 1 for a, b in self.slotmap.ranges(nid)
            )
            state = "DOWN" if nid in self.last_down else "up"
            lines.append(
                f"- node {nid}: {role}, {owned} slots, {state}"
            )
        if not st["active_findings"]:
            lines.append(
                "No active findings. Every invariant I watch holds; "
                "keep it up!"
            )
        else:
            lines.append(
                f"{len(st['active_findings'])} ACTIVE finding(s):"
            )
            for f in st["active_findings"]:
                age = int(time.time() - f["since"])
                lines.append(
                    f"- [{f['severity']}] {f['kind']} ({f['subject']}): "
                    f"{f['detail']} — active {age}s"
                )
        events = getattr(self.obs, "events", None)
        if events is not None and last_events > 0:
            lines.append(f"Last {last_events} control-plane events:")
            for ev in events.snapshot(count=last_events):
                fields = ",".join(
                    f"{k}={v}" for k, v in sorted(ev["fields"].items())
                )
                lines.append(
                    f"- seq {ev['seq']} [{ev['severity']}] "
                    f"{ev['kind']} {fields}"
                )
        return "\n".join(lines)

    # -- bus I/O (the rebalancer's short-lived-connection idiom) -----------

    def _call(self, node_id: str, *cmds, timeout_s: float = 2.0):
        """Pipeline ``cmds`` (tuples) on a short-lived connection;
        None on any network failure — the sweep degrades, it never
        raises."""
        addr = self.slotmap.addr(node_id)
        if addr is None:
            return None
        try:
            sock = socket.create_connection(addr, timeout=1.0)
        except OSError:
            return None
        try:
            sock.settimeout(timeout_s)
            return exchange(sock, list(cmds))
        except (OSError, ValueError):
            return None
        finally:
            try:
                sock.close()
            except OSError:
                pass

    def _failover_failed(self) -> set:
        fo = getattr(self.server, "failover", None)
        if fo is None:
            return set()
        return set(fo.state.failed)

    def _coordinator(self, excluded) -> Optional[str]:
        alive = [
            p for p in self.slotmap.primary_ids() if p not in excluded
        ]
        return min(alive) if alive else None

    # -- the loop ----------------------------------------------------------

    def run(self) -> None:
        while not self._stop_evt.is_set():
            self._kick.wait(self.interval_s)
            self._kick.clear()
            if self._stop_evt.is_set():
                break
            try:
                self.tick()
            except Exception:  # pragma: no cover — the loop must not die
                pass

    def tick(self, force: bool = False) -> int:
        """One sweep; returns the active-finding count.  ``force``
        (CLUSTER DOCTOR NOW) sweeps even while paused and even
        off-coordinator — an explicit operator override."""
        if self.paused and not force:
            return len(self.active)
        with self._tick_lock:
            return self._sweep(force)

    def _sweep(self, force: bool) -> int:
        t0 = time.monotonic()
        # 1. Probe every node: liveness + (epoch, offset, role).
        probes: dict = {}
        down: set = set()
        for nid in self.slotmap.node_ids():
            if nid == self.myid:
                probes[nid] = self._self_probe()
                continue
            got = self._probe(nid)
            if got is None:
                down.add(nid)
            else:
                probes[nid] = got
        self.last_down = down
        excluded = down | self._failover_failed()
        coord = self._coordinator(excluded)
        if not force and coord != self.myid:
            # Observer: keep the monotonicity memory warm so a takeover
            # audits from history, but raise/clear nothing.
            for nid, p in probes.items():
                self._last_seen[nid] = p
            self.last_sweep_ms = (time.monotonic() - t0) * 1e3
            return len(self.active)
        findings: dict = {}

        def raise_finding(kind: str, subject: str, detail: str) -> None:
            findings[f"{kind}:{subject}"] = {
                "kind": kind,
                "severity": _SEVERITY[kind],
                "subject": subject,
                "detail": detail,
                "since": time.time(),
            }

        # 2. Dead primaries still owning slots + slot coverage.
        for nid in down:
            if (self.slotmap.role(nid) == "master"
                    and self.slotmap.ranges(nid)):
                raise_finding(
                    "dead-primary", nid,
                    f"primary unreachable but still owns "
                    f"{sum(b - a + 1 for a, b in self.slotmap.ranges(nid))}"
                    f" slots",
                )
        unassigned = 16384 - self.slotmap.assigned_count()
        if unassigned:
            raise_finding(
                "unassigned-slots", "fleet",
                f"{unassigned} slots have no owner",
            )
        # 3. Cross-node CLUSTER SHARDS compare against my view.
        my_view = self._owner_view(self.slotmap)
        for nid in self.slotmap.node_ids():
            if nid == self.myid or nid in down:
                continue
            peer_view = self._peer_owner_view(nid)
            if peer_view is not None and peer_view != my_view:
                raise_finding(
                    "topology-divergence", nid,
                    "peer's CLUSTER SHARDS disagrees with the "
                    "coordinator's slot map",
                )
        # 4. Offset/epoch monotonicity + replica lag.
        for nid, p in probes.items():
            prev = self._last_seen.get(nid)
            if prev is not None:
                if p["epoch"] < prev["epoch"]:
                    raise_finding(
                        "epoch-regression", nid,
                        f"epoch {p['epoch']} < last seen "
                        f"{prev['epoch']}",
                    )
                if p["role"] == prev["role"] and (
                        p["offset"] < prev["offset"]):
                    raise_finding(
                        "offset-regression", nid,
                        f"offset {p['offset']} < last seen "
                        f"{prev['offset']} (role unchanged: acked "
                        f"history vanished)",
                    )
            primary = self.slotmap.replica_of(nid)
            if primary is not None and primary in probes:
                lag = probes[primary]["offset"] - p["offset"]
                if lag > self.lag_bound_ops:
                    raise_finding(
                        "repl-lag", nid,
                        f"replica {lag} ops behind {primary} "
                        f"(bound {self.lag_bound_ops})",
                    )
        for nid, p in probes.items():
            self._last_seen[nid] = p
        # 5. Stuck MIGRATING/IMPORTING slots (age tracked here: first
        # sweep that SAW the state starts its clock).
        now = time.monotonic()
        live_states: set = set()
        for nid in self.slotmap.node_ids():
            if nid in down:
                continue
            migs = self._peer_migrations(nid)
            if migs is None:
                continue
            for state in ("importing", "migrating"):
                for slot in migs.get(state, {}):
                    k = (nid, int(slot), state)
                    live_states.add(k)
                    first = self._mig_first_seen.setdefault(k, now)
                    if now - first > self.stuck_slot_s:
                        raise_finding(
                            "stuck-migration",
                            f"{nid}/{slot}",
                            f"slot {slot} {state.upper()} on {nid} "
                            f"for {int(now - first)}s "
                            f"(threshold {int(self.stuck_slot_s)}s)",
                        )
        for k in list(self._mig_first_seen):
            if k not in live_states:
                del self._mig_first_seen[k]
        # 6. Black-box canary per alive primary.
        if self.canary_enabled:
            for nid in self.slotmap.primary_ids():
                if nid in down or not self.slotmap.ranges(nid):
                    continue
                err = self._canary_probe(nid)
                if err is not None:
                    self.canary_failures += 1
                    raise_finding("canary", nid, err)
        self._apply_findings(findings)
        self.sweeps += 1
        if self.obs is not None:
            try:
                self.obs.doctor_sweeps.inc((), 1)
            except AttributeError:
                pass
        self.last_sweep_ms = (time.monotonic() - t0) * 1e3
        return len(self.active)

    # -- probes ------------------------------------------------------------

    def _self_probe(self) -> dict:
        fo = getattr(self.server, "failover", None)
        epoch = fo.state.current_epoch if fo is not None else 0
        return {
            "epoch": int(epoch),
            "offset": int(self.server._repl_offset()),
            "role": ("slave" if self.server.replica_link is not None
                     else "master"),
        }

    def _probe(self, nid: str) -> Optional[dict]:
        """CLUSTERPING with ONE retry — a single timed-out connect must
        not read as a dead node (the zero-false-positive bar)."""
        for attempt in (0, 1):
            got = self._call(
                nid, ("RTPU.CLUSTERPING", self.myid, "0")
            )
            if got is not None and not isinstance(got[0], ReplyError):
                reply = got[0]
                if isinstance(reply, list) and len(reply) >= 5:
                    try:
                        return {
                            "epoch": int(reply[2]),
                            "offset": int(reply[3]),
                            "role": bytes(reply[4]).decode(),
                        }
                    except (TypeError, ValueError):
                        return None
            if attempt == 0 and not self._stop_evt.wait(0.1):
                continue
            break
        return None

    @staticmethod
    def _owner_view(slotmap) -> dict:
        """node -> tuple-of-ranges for every PRIMARY (the comparable
        ownership digest)."""
        return {
            nid: tuple(tuple(r) for r in slotmap.ranges(nid))
            for nid in slotmap.primary_ids()
        }

    def _peer_owner_view(self, nid: str) -> Optional[dict]:
        got = self._call(nid, ("CLUSTER", "SHARDS"))
        if got is None or isinstance(got[0], ReplyError):
            return None
        view: dict = {}
        try:
            for shard in got[0]:
                fields = {
                    bytes(shard[i]).decode(): shard[i + 1]
                    for i in range(0, len(shard), 2)
                }
                flat = [int(v) for v in fields["slots"]]
                node = fields["nodes"][0]
                nf = {
                    bytes(node[i]).decode(): node[i + 1]
                    for i in range(0, len(node), 2)
                }
                if bytes(nf["role"]).decode() != "master":
                    continue
                pid = bytes(nf["id"]).decode()
                view[pid] = tuple(
                    (flat[i], flat[i + 1])
                    for i in range(0, len(flat), 2)
                )
        except (TypeError, ValueError, KeyError, IndexError):
            return None
        return view

    def _peer_migrations(self, nid: str) -> Optional[dict]:
        if nid == self.myid:
            with self.slotmap._lock:
                return {
                    "importing": dict(self.slotmap.importing),
                    "migrating": dict(self.slotmap.migrating),
                }
        got = self._call(nid, ("CLUSTER", "MIGRATIONS"))
        if got is None or isinstance(got[0], ReplyError):
            return None
        import json

        try:
            return json.loads(bytes(got[0]))
        except (TypeError, ValueError):
            return None

    def _canary_probe(self, nid: str) -> Optional[str]:
        """WAIT-fenced write-then-read through the real client path;
        None on success, an error string on failure."""
        key = canary_key(nid, self.slotmap)
        if key is None:
            return None  # owns no slots: nothing to probe
        self._canary_seq += 1
        val = f"{self.myid}:{self._canary_seq}"
        t0 = time.monotonic()
        got = self._call(
            nid,
            ("SET", key, val),
            ("WAIT", "0", str(self.canary_timeout_ms)),
            ("GET", key),
            timeout_s=max(2.0, self.canary_timeout_ms / 1000.0 + 2.0),
        )
        rtt_s = time.monotonic() - t0
        if got is None:
            return "canary probe connection failed"
        set_r, _wait_r, get_r = got
        if isinstance(set_r, ReplyError):
            return f"canary SET refused: {set_r}"
        if isinstance(get_r, ReplyError):
            return f"canary GET refused: {get_r}"
        if bytes(get_r or b"") != val.encode():
            return (
                f"canary read-your-write failed: wrote {val!r}, "
                f"read {get_r!r}"
            )
        if self.obs is not None:
            try:
                self.obs.doctor_canary_rtt_us.observe((), rtt_s)
            except AttributeError:
                pass
        return None

    # -- the finding ledger ------------------------------------------------

    def _apply_findings(self, findings: dict) -> None:
        """Diff the freshly-computed set against the active ledger:
        raises emit doctor.finding (+ the kind counter), resolutions
        emit doctor.clear; persisting findings keep their original
        ``since`` stamp."""
        events = getattr(self.obs, "events", None)
        for key, f in findings.items():
            old = self.active.get(key)
            if old is not None:
                f["since"] = old["since"]  # keep the raise time
                continue
            self.findings_total += 1
            if self.obs is not None:
                try:
                    self.obs.doctor_findings.inc((f["kind"],))
                except AttributeError:
                    pass
            if events is not None:
                events.emit("doctor.finding", severity=f["severity"],
                            kind=f["kind"], subject=f["subject"],
                            detail=f["detail"])
                if f["kind"] == "canary":
                    events.emit("doctor.canary", severity="error",
                                node=f["subject"], detail=f["detail"])
        for key in list(self.active):
            if key not in findings:
                f = self.active[key]
                if events is not None:
                    events.emit("doctor.clear", kind=f["kind"],
                                subject=f["subject"],
                                active_s=round(
                                    time.time() - f["since"], 3))
        self.active = findings


__all__ = ["FleetDoctor", "FINDING_KINDS", "canary_key"]
