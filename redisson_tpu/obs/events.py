"""Fleet flight recorder (ISSUE 20 tentpole part 1) — a bounded,
lock-cheap structured event ring per process recording what the control
planes DECIDED and why: elections, takeovers, resyncs, rebalance waves,
breaker flips, tier transitions, worker deaths, config changes, doctor
findings.

Metrics say "how much", traces say "how slow" (PAPERS.md §2 Dapper);
the flight recorder says "what happened, in what order" — the causal
record an operator replays after a 3 a.m. failover.  Slicer (PAPERS.md
§3) ships its assigner with continuous self-checking; this ring is
where those checks (obs/doctor.py) and every other control plane write
their black-box log.

Event shape (one dict per event, JSON-safe by construction):

- ``node``      — emitting node's id (stamped by the owning server;
                  empty until a door claims the ring);
- ``seq``       — per-node monotonic sequence number.  Gaps in a
                  node's seq stream mean ring evictions, and
                  ``ClusterClient.fleet_events()`` reports them as
                  exactly that instead of pretending the record is
                  complete;
- ``wall``      — wall-clock seconds (time.time; the cross-node merge
                  key, ordered as (wall, node, seq));
- ``mono``      — monotonic stamp for intra-node interval math;
- ``kind``      — a literal from :data:`KINDS` (bounded cardinality —
                  the RT005 discipline applied to event kinds; rtpulint
                  RT015 enforces literal registered kinds at every call
                  site);
- ``severity``  — ``info`` | ``warn`` | ``error``;
- ``fields``    — small structured payload (slot, epoch, offsets, …);
- ``trace_id``  — present when a trace scope was ambient at emit time,
                  so a traced request's control-plane consequences join
                  its trace.

Cost discipline: emit points live on CONTROL-plane paths (ticks,
elections, breaker flips), never per-op hot paths, so the ring takes a
plain lock around a deque append — no sampling, no module guard.  The
ring is HARD-BOUNDED (``max_events``): recording can never become a
memory leak, only a recency window; evictions are counted and visible
as seq gaps.
"""

from __future__ import annotations

import threading
import time
from collections import deque

from redisson_tpu.obs import trace as _trace

# The event-kind catalog: every kind the fleet can ever emit, with the
# control plane it belongs to.  BOUNDED ON PURPOSE — kinds are a metric
# label dimension (rtpu_events_emitted) and the doctor's finding keys,
# so unbounded kinds would defeat the registry cardinality cap.  Adding
# an emit point means adding its kind HERE first (rtpulint RT015 fails
# any call site whose kind literal is not in this table, and
# tests/test_rtpulint.py pins the linter's mirror to this dict).
KINDS = {
    # cluster/failover.py — detection, votes, elections, takeovers.
    "failover.detected": "peer marked failed by the timeout detector",
    "failover.vote": "FAILOVER.AUTH vote granted to a candidate",
    "failover.election.won": "this node won an election (quorum)",
    "failover.election.lost": "this node's election fell short of quorum",
    "failover.takeover.sent": "takeover broadcast sent (per-slot-range epoch)",
    "failover.takeover.applied": "takeover broadcast applied to the slotmap",
    # cluster/rebalancer.py — coordinator changes + wave outcomes.
    "rebalance.coordinator": "rebalance coordinator changed",
    "rebalance.wave.planned": "wave planned (moves + imbalance ratio)",
    "rebalance.wave.executed": "wave executed (moved/failed counts)",
    "rebalance.wave.skipped": "planned move vetoed at the last moment",
    # durability/replication.py + replica.py — resyncs, link, fences.
    "repl.full_resync": "full resynchronization served or performed",
    "repl.partial_resync": "partial resync (PSYNC CONTINUE) served or ridden",
    "repl.link.down": "replica link to the primary broke",
    "repl.stale_read": "staleness gate refused a read (-STALEREAD)",
    "repl.wait.timeout": "WAIT fence timed out below the asked replica count",
    # executor/health.py — breaker transitions and mirror reconcile.
    "health.breaker.open": "circuit breaker opened (kind degraded)",
    "health.breaker.close": "breaker closed and the kind reconciled",
    "health.reconcile.failed": "reconcile write-back failed; breaker re-opened",
    # storage/residency.py — tier transitions.
    "residency.promote": "sketch promoted back to a device row",
    "residency.demote": "sketch demoted to its host golden mirror",
    "residency.spill": "host mirror spilled to a disk blob",
    # serve/multicore.py — worker lifecycle + in-node handoff legs.
    "multicore.worker.spawn": "front-door worker came up (self-announce)",
    "multicore.worker.death": "front-door worker observed dead by a "
                              "sibling (its peer listener is gone)",
    "multicore.handoff.broken": "in-node handoff leg broke (-HANDOFFBROKEN)",
    # serve/resp.py — the CONFIG SET audit trail.
    "config.set": "live CONFIG SET applied (key + new value)",
    # obs/doctor.py — invariant findings and the black-box canary.
    "doctor.finding": "doctor sweep raised an invariant finding",
    "doctor.clear": "a previously active finding cleared",
    "doctor.canary": "black-box canary probe failed",
}

SEVERITIES = ("info", "warn", "error")


class EventRing:
    """The per-process flight-recorder ring.

    One instance per :class:`~redisson_tpu.obs.Observability` bundle;
    the RESP door stamps ``node`` once the cluster identity is known
    (empty node = standalone process).  ``emit`` is thread-safe and
    cheap: one lock, one deque append, one counter bump."""

    def __init__(self, max_events: int = 1024, counter=None,
                 evicted_counter=None):
        self.max_events = int(max_events)
        self.node = ""
        self._lock = threading.Lock()
        self._ring: deque = deque()
        self._seq = 0
        self.evicted = 0
        self._counter = counter            # rtpu_events_emitted (kind)
        self._evicted_counter = evicted_counter  # rtpu_events_evicted

    # -- emit (control-plane paths only) -----------------------------------

    def emit(self, kind: str, /, severity: str = "info", **fields) -> dict:
        """Record one structured event; returns the event dict.

        ``kind`` must be a literal registered in :data:`KINDS` — an
        unknown kind raises (a programming error, caught by rtpulint
        RT015 before it ever runs).  ``fields`` must be JSON-safe
        scalars/lists (the EVENTS GET surface serializes them as-is).
        """
        if kind not in KINDS:
            raise ValueError(f"unregistered event kind {kind!r}")
        if severity not in SEVERITIES:
            raise ValueError(f"unknown severity {severity!r}")
        trace_id = None
        ctx = _trace.current()
        if ctx is not None:
            if isinstance(ctx, tuple):
                ctx = ctx[0]
            trace_id = getattr(ctx, "trace_id", None)
        ev = {
            "node": self.node,
            "wall": time.time(),
            "mono": time.monotonic(),
            "kind": kind,
            "severity": severity,
            "fields": fields,
        }
        if trace_id is not None:
            ev["trace_id"] = trace_id
        with self._lock:
            self._seq += 1
            ev["seq"] = self._seq
            if len(self._ring) >= self.max_events:
                self._ring.popleft()
                self.evicted += 1
                if self._evicted_counter is not None:
                    self._evicted_counter.inc((), 1)
            self._ring.append(ev)
        if self._counter is not None:
            self._counter.inc((kind,))
        return ev

    # -- read surface (EVENTS GET|LEN, INFO events, the doctor) ------------

    def snapshot(self, count: int = 0, kind: str = "") -> list:
        """Newest-last list of event dicts (copies); ``count`` > 0
        limits to the newest N, ``kind`` filters by exact kind (or a
        ``prefix.`` when it ends with a dot — ``doctor.`` selects the
        doctor's whole plane)."""
        with self._lock:
            evs = list(self._ring)
        if kind:
            if kind.endswith("."):
                evs = [e for e in evs if e["kind"].startswith(kind)]
            else:
                evs = [e for e in evs if e["kind"] == kind]
        if count > 0:
            evs = evs[-count:]
        return [dict(e) for e in evs]

    def __len__(self) -> int:
        with self._lock:
            return len(self._ring)

    def stats(self) -> dict:
        with self._lock:
            return {
                "events": len(self._ring),
                "seq": self._seq,
                "evicted": self.evicted,
                "max_events": self.max_events,
            }

    def reset(self) -> int:
        """EVENTS RESET: drop the ring (seq keeps counting — a reset
        must read as an eviction gap downstream, never as silence that
        looks like nothing happened)."""
        with self._lock:
            n = len(self._ring)
            self.evicted += n
            if n and self._evicted_counter is not None:
                self._evicted_counter.inc((), n)
            self._ring.clear()
            return n


def merge_timelines(per_node: dict) -> tuple[list, dict]:
    """Merge per-node event lists into ONE causally-ordered fleet
    timeline: ``(events, gaps)`` where events sort by
    ``(wall, node, seq)`` — wall clocks order across nodes (the best a
    multi-node merge can do without true causality tokens), per-node
    seq breaks ties and proves intra-node order — and ``gaps`` maps
    node -> evicted-event count inferred from seq discontinuities, so
    a reader knows where the record is incomplete instead of assuming
    the ring saw everything.  Node-disjoint merge, the fleet_loadmap
    discipline: a dead member contributes nothing, it never raises."""
    merged: list = []
    gaps: dict = {}
    for node, evs in per_node.items():
        prev_seq = None
        for ev in sorted(evs, key=lambda e: e.get("seq", 0)):
            seq = int(ev.get("seq", 0))
            if prev_seq is not None and seq > prev_seq + 1:
                gaps[node] = gaps.get(node, 0) + (seq - prev_seq - 1)
            prev_seq = seq
            merged.append(ev)
    merged.sort(
        key=lambda e: (e.get("wall", 0.0), e.get("node", ""),
                       e.get("seq", 0))
    )
    return merged, gaps


__all__ = ["EventRing", "KINDS", "SEVERITIES", "merge_timelines"]
