"""Metrics federation (ISSUE 13 tentpole part 2) — one merged
Prometheus exposition over a fleet of per-node ``/metrics`` endpoints,
every sample relabeled with a ``node="host:port"`` dimension (the
Scaling-Memcache aggregated-telemetry shape, PAPERS.md §1).

Two deployment forms share this module:

- ``ClusterSupervisor.start_federation()`` — the supervisor scrapes its
  member nodes' endpoints and serves the merge;
- ``python -m redisson_tpu --federate host:port,... --metrics-port N``
  — a standalone federation-only process (no engine, no RESP door) for
  fleets the supervisor does not own.

Scrapes happen per request (the promhttp discipline: no background
collection thread); an unreachable node contributes
``rtpu_federation_node_up{node=...} 0`` instead of failing the whole
exposition.  Families are regrouped so each name renders ONE
``# TYPE`` block with all nodes' samples under it — duplicate TYPE
lines are a Prometheus parse error, not a cosmetic issue.
"""

from __future__ import annotations

import threading
import urllib.request

from redisson_tpu.obs.promhttp import MetricsHTTPServer


def _inject_node_label(sample_line: str, node: str) -> str:
    """``name{a="b"} v`` → ``name{node="X",a="b"} v`` (node first so a
    reader scanning the merged page sees the owner immediately)."""
    esc = node.replace("\\", "\\\\").replace('"', '\\"')
    brace = sample_line.find("{")
    space = sample_line.find(" ")
    if brace != -1 and (space == -1 or brace < space):
        return (
            sample_line[: brace + 1]
            + f'node="{esc}",'
            + sample_line[brace + 1:]
        )
    if space == -1:
        return sample_line  # malformed; pass through untouched
    return (
        sample_line[:space] + f'{{node="{esc}"}}' + sample_line[space:]
    )


def merge_expositions(pages: "list[tuple[str, str]]") -> str:
    """Merge ``[(node_label, exposition_text)]`` into one valid page:
    per family, one HELP/TYPE (first seen) followed by every node's
    samples with the ``node`` label injected."""
    order: list = []  # family names in first-seen order
    meta: dict = {}   # family -> [comment lines]
    samples: dict = {}  # family -> [relabeled sample lines]
    for node, text in pages:
        family = None
        for line in text.splitlines():
            if not line.strip():
                continue
            if line.startswith("#"):
                parts = line.split(None, 3)
                # "# TYPE name kind" / "# HELP name text"
                if len(parts) >= 3 and parts[1] in ("TYPE", "HELP"):
                    family = parts[2]
                    if family not in meta:
                        meta[family] = []
                        samples[family] = []
                        order.append(family)
                    if line not in meta[family]:
                        # First node's wording wins; identical repeats
                        # (every node shares the codebase) dedupe here.
                        kind = parts[1]
                        if not any(
                            m.split(None, 2)[1] == kind
                            for m in meta[family]
                        ):
                            meta[family].append(line)
                continue
            if family is None:
                # Untyped sample (no preceding TYPE): its own family
                # keyed by the bare metric name.
                name = line.split("{", 1)[0].split(" ", 1)[0]
                family = name
                if family not in meta:
                    meta[family] = []
                    samples[family] = []
                    order.append(family)
            samples[family].append(_inject_node_label(line, node))
    out: list = []
    for fam in order:
        out.extend(meta[fam])
        out.extend(samples[fam])
    return "\n".join(out) + "\n"


class FederatedMetrics:
    """Scrape-and-merge renderer over N member ``/metrics`` targets."""

    def __init__(self, targets, timeout_s: float = 2.0):
        # targets: iterable of "host:port" strings or (host, port).
        self.targets = [
            t if isinstance(t, str) else "%s:%d" % tuple(t)
            for t in targets
        ]
        if not self.targets:
            raise ValueError("federation needs at least one target")
        self.timeout_s = timeout_s

    def _scrape(self, target: str) -> "tuple[str, str]":
        url = f"http://{target}/metrics"
        with urllib.request.urlopen(url, timeout=self.timeout_s) as r:
            return target, r.read().decode("utf-8", "replace")

    def render(self) -> str:
        pages: list = []
        up_lines = [
            "# HELP rtpu_federation_node_up member endpoint reachable "
            "at this scrape",
            "# TYPE rtpu_federation_node_up gauge",
        ]
        # Scrape members concurrently: a slow/unreachable node must not
        # serialize the whole fleet page behind its timeout.
        results: dict = {}

        def one(t):
            try:
                results[t] = self._scrape(t)[1]
            except Exception as e:
                results[t] = e

        threads = [
            threading.Thread(target=one, args=(t,), daemon=True)
            for t in self.targets
        ]
        for th in threads:
            th.start()
        for th in threads:
            th.join(self.timeout_s + 1.0)
        for t in self.targets:
            got = results.get(t)
            esc = t.replace("\\", "\\\\").replace('"', '\\"')
            if isinstance(got, str):
                pages.append((t, got))
                up_lines.append(f'rtpu_federation_node_up{{node="{esc}"}} 1')
            else:
                up_lines.append(f'rtpu_federation_node_up{{node="{esc}"}} 0')
        return merge_expositions(pages) + "\n".join(up_lines) + "\n"


def start_federation_endpoint(targets, host: str = "127.0.0.1",
                              port: int = 0, timeout_s: float = 2.0
                              ) -> MetricsHTTPServer:
    """Serve the merged fleet exposition at ``/metrics`` — the
    ``--federate`` mode of the metrics endpoint."""
    fm = FederatedMetrics(targets, timeout_s=timeout_s)
    srv = MetricsHTTPServer(fm.render, host=host, port=port)
    srv.federation = fm  # introspection / tests
    return srv


__all__ = [
    "FederatedMetrics",
    "merge_expositions",
    "start_federation_endpoint",
]
