"""LATENCY subsystem (ISSUE 13 parity surface) — redis-server's
latency monitor (latency.c): named latency events sampled into bounded
per-event histories once they meet ``latency-monitor-threshold``.

Event sources in this codebase:

- ``command``       — any RESP command whose execution time meets the
                      threshold (serve/resp.py _safe_dispatch);
- ``slow-launch``   — a coalesced engine launch whose end-to-end span
                      met the threshold (obs/spans.py);
- ``fsync-stall``   — a journal group-commit fsync that met the
                      threshold (durability/journal.py);
- ``breaker-open``  — a circuit breaker opening (executor/health.py;
                      the recorded latency is the open window, i.e. how
                      long dispatches will fail fast);
- ``migration``     — one key's MIGRATE dump→RESTORE→delete critical
                      section (cluster/door.py);
- ``reconcile``     — a degraded-kind mirror write-back at breaker
                      close (objects/engines.py);
- ``election``      — one failover election attempt, start to win/loss
                      (cluster/failover.py);
- ``rebalance-wave``— one executed rebalance wave, plan to last move
                      (cluster/rebalancer.py);
- ``full-resync``   — a replica-side full resynchronization, snapshot
                      load included (durability/replica.py).

Semantics follow Redis: threshold 0 disables monitoring entirely (the
hot-path guard is one attribute read + compare); each event keeps the
last ``MAX_SAMPLES`` (ts, ms) pairs plus an all-time max; ``LATENCY
LATEST|HISTORY|RESET|DOCTOR`` serve the data over RESP and ``CONFIG SET
latency-monitor-threshold`` arms it live.  The event-name space is
additionally capped (``MAX_EVENTS``) so a buggy caller can never grow
the dict without bound (the RT006 discipline).
"""

from __future__ import annotations

import threading
import time
from collections import deque

MAX_SAMPLES = 160  # per-event history bound (redis-server keeps 160)
MAX_EVENTS = 64    # event-name cardinality bound


class LatencyMonitor:
    def __init__(self, threshold_ms: int = 0, counter=None):
        # threshold_ms is read UNLOCKED on hot paths (single attribute,
        # GIL-atomic): 0 = disabled, the redis default.
        self.threshold_ms = int(threshold_ms)
        self._lock = threading.Lock()
        self._events: dict[str, deque] = {}  # name -> deque[(ts, ms)]
        self._max: dict[str, int] = {}       # name -> all-time max ms
        self._counter = counter  # optional rtpu_latency_events family

    # -- hot path ----------------------------------------------------------

    def record(self, event: str, ms: float) -> bool:
        """Sample ``event`` at ``ms`` when monitoring is armed and the
        value meets the threshold.  Cheap when disarmed: one compare."""
        thr = self.threshold_ms
        if thr <= 0 or ms < thr:
            return False
        ms_i = int(ms)
        with self._lock:
            ring = self._events.get(event)
            if ring is None:
                if len(self._events) >= MAX_EVENTS:
                    return False  # bounded event-name space
                ring = deque(maxlen=MAX_SAMPLES)
                self._events[event] = ring
            ring.append((int(time.time()), ms_i))
            if ms_i > self._max.get(event, 0):
                self._max[event] = ms_i
        if self._counter is not None:
            self._counter.inc((event,))
        return True

    # -- LATENCY command surface -------------------------------------------

    def latest(self) -> list:
        """[(event, last_ts, last_ms, max_ms)] — LATENCY LATEST."""
        with self._lock:
            out = []
            for name, ring in self._events.items():
                if not ring:
                    continue
                ts, ms = ring[-1]
                out.append((name, ts, ms, self._max.get(name, ms)))
        out.sort()
        return out

    def history(self, event: str) -> list:
        """[(ts, ms)] oldest first — LATENCY HISTORY <event>."""
        with self._lock:
            ring = self._events.get(event)
            return list(ring) if ring else []

    def reset(self, *events: str) -> int:
        """Clear the named events (all when none given); returns the
        number of event histories dropped — LATENCY RESET."""
        with self._lock:
            if not events:
                n = len(self._events)
                self._events.clear()
                self._max.clear()
                return n
            n = 0
            for e in events:
                if self._events.pop(e, None) is not None:
                    n += 1
                self._max.pop(e, None)
            return n

    def doctor(self) -> str:
        """LATENCY DOCTOR: a human diagnosis of the armed monitor."""
        if self.threshold_ms <= 0:
            return (
                "I'm sorry, Dave, I can't do that.  Latency monitoring "
                "is disabled in this instance.  Enable it with CONFIG "
                "SET latency-monitor-threshold <milliseconds>."
            )
        latest = self.latest()
        if not latest:
            return (
                f"Dave, I have observed the system, no worthy latency "
                f"event registered so far (threshold "
                f"{self.threshold_ms} ms), keep it up!"
            )
        lines = [
            f"Dave, I have a few latency spikes to report "
            f"(threshold {self.threshold_ms} ms):"
        ]
        advice = {
            "fsync-stall": "consider appendfsync everysec, a faster "
                           "disk, or a larger group-commit window",
            "slow-launch": "check rtpu_op_phase_seconds for the slow "
                           "phase (coalesce_wait vs device_dispatch vs "
                           "d2h_fetch) and the link-phase gauges",
            "breaker-open": "a device dispatch path is failing; see "
                            "rtpu_breaker_state and INFO stats "
                            "(degraded/breakers_open)",
            "command": "see SLOWLOG GET and INFO latencystats for the "
                       "offending commands",
            "migration": "per-key MIGRATE holds the move guard across "
                         "a network round trip; shrink keys or expect "
                         "this during resharding",
            "reconcile": "mirror write-back volume tracks the degraded "
                         "window length; close breakers sooner",
            "election": "slow elections lengthen the unavailability "
                        "window; check peer timeouts and EVENTS GET "
                        "failover. for the vote timeline",
            "rebalance-wave": "long waves hold slot move guards; lower "
                              "rebalance-max-moves or raise the "
                              "interval",
            "full-resync": "a replica fell off the backlog; grow "
                           "repl-backlog-size or check EVENTS GET "
                           "repl.link.down for flapping links",
        }
        for name, ts, ms, mx in latest:
            lines.append(
                f"- {name}: latest {ms} ms, all-time max {mx} ms"
            )
            if name in advice:
                lines.append(f"  advice: {advice[name]}")
        return "\n".join(lines)

    def stats(self) -> dict:
        with self._lock:
            return {
                "threshold_ms": self.threshold_ms,
                "events": len(self._events),
                "samples": sum(len(r) for r in self._events.values()),
            }

    # -- CONFIG SET hook ---------------------------------------------------

    def set_threshold_ms(self, ms: int) -> None:
        ms = int(ms)
        if ms < 0:
            raise ValueError(
                f"latency-monitor-threshold must be >= 0, got {ms}"
            )
        self.threshold_ms = ms


__all__ = ["LatencyMonitor", "MAX_EVENTS", "MAX_SAMPLES"]
