"""Load-attribution plane (ISSUE 16 tentpole): per-slot, per-key and
per-tenant heat telemetry — the sensing layer the future slot
rebalancer (ROADMAP direction 3, Slicer's assigner half) polls.

Slicer's core lesson is that slot load != key count: assignment must be
weighted by *observed work*.  One ``LoadMap`` per serving process
accumulates exactly that, in three planes:

- **Per-slot accounting** — fixed 16384-wide flat arrays (ops,
  read/write split, bytes in/out, shed ops, cumulative device-launch
  microseconds, live key count), bumped O(1) per command at the RESP
  dispatch point (slot stashed by the cluster door's route decision)
  and at span retirement.  Standalone mode degrades to slot 0, so the
  totals stay meaningful without a cluster.  The count/byte bumps are
  LOCK-FREE on purpose (the storage/heat.py discipline): an element
  ``+=`` is a read-modify-write that can lose a concurrent bump, which
  is benign for an advisory load signal — structural reads
  (``snapshot``/``top_slots``) and the EXACT key counters serialize on
  the leaf lock ``obs.loadmap`` instead.
- **Hot-key detection, dogfooding our own sketches** — a host-side
  *decayed* count-min sketch plus a space-saving top-k (the very
  structures this engine serves) fed by a sampled key stream at RESP
  ingress (``loadmap_key_sample_rate``).  Sampling keeps the hot path
  out of the sketches entirely at low rates; the CMS estimate feeds the
  top-k's counts so reported hotness survives candidate churn.  Both
  structures decay by the same half-life, so "hot" means *recently*
  hot, not hot-ever (redis-cli --hotkeys over LFU has the same
  recency shape).
- **Per-tenant device-time attribution** — the span recorder hands each
  retiring launch's device-side microseconds here together with the
  (tenant, nops) composition the coalescer stashed on the span; the
  time is split proportionally to each tenant's op share.  Tenant
  cardinality is bounded: past ``max_tenants`` the coldest entries fold
  into one ``"other"`` bucket (never evicted), and the exported
  ``rtpu_tenant_device_us`` series uses the folded label — top-N +
  other, never one series per tenant name.

The whole module is host-side stdlib + the pure slot math — no jax, no
I/O — so client processes and tests import it for free.
"""

from __future__ import annotations

import math
import threading
import time
from array import array

from redisson_tpu.analysis import witness as _witness
from redisson_tpu.cluster.slots import NSLOTS, key_slot

# Reserved fold bucket for the bounded tenant table — a real tenant
# named "other" would merge into it, which only blurs an advisory
# attribution signal.
OTHER_TENANT = "other"


def _as_text(key) -> str:
    if isinstance(key, bytes):
        return key.decode("utf-8", "replace")
    return str(key)


class DecayedCMS:
    """Count-min sketch over the sampled key stream with lazy
    exponential decay: every ``half_life_s`` of wall time halves every
    cell (applied in one vectorized-ish pass when the elapsed time
    crosses the half-life, so the amortized per-add cost stays O(depth)).

    NOT thread-safe on its own — the owning :class:`LoadMap` serializes
    all calls under its leaf lock (the sampled path is already off the
    per-command fast path).
    """

    def __init__(self, width: int = 1024, depth: int = 4,
                 half_life_s: float = 30.0, clock=time.monotonic):
        self.width = int(width)
        self.depth = int(depth)
        self.half_life_s = float(half_life_s)
        self._clock = clock
        self._rows = [array("d", bytes(8 * self.width))
                      for _ in range(self.depth)]
        self._last_decay = clock()

    def _indices(self, key: str):
        # In-process hashing only (never serialized): salting Python's
        # string hash per row gives depth independent functions.
        return [hash((d, key)) % self.width for d in range(self.depth)]

    def maybe_decay(self, now: float) -> float:
        """Apply pending decay; returns the factor applied (1.0 when
        none was due).  Shared by the owning LoadMap so the top-k decays
        in lockstep with the CMS (estimates must stay comparable)."""
        hl = self.half_life_s
        if hl <= 0.0:
            return 1.0
        dt = now - self._last_decay
        if dt < hl:
            return 1.0
        factor = math.pow(2.0, -dt / hl)
        for row in self._rows:
            for i in range(self.width):
                if row[i]:
                    row[i] *= factor
        self._last_decay = now
        return factor

    def add(self, key: str, n: float = 1.0) -> float:
        """Add ``n`` and return the post-add point estimate (min over
        rows — the classic CMS overestimate bound)."""
        est = float("inf")
        for d, i in enumerate(self._indices(key)):
            row = self._rows[d]
            row[i] += n
            if row[i] < est:
                est = row[i]
        return est

    def estimate(self, key: str) -> float:
        est = float("inf")
        for d, i in enumerate(self._indices(key)):
            v = self._rows[d][i]
            if v < est:
                est = v
        return est


class SpaceSavingTopK:
    """Metwally space-saving candidate table: bounded at ``capacity``
    monitored keys; a new key past capacity evicts the minimum-count
    entry and inherits its count (the algorithm's overestimate floor),
    so a genuinely hot newcomer climbs instead of thrashing.

    NOT thread-safe on its own — serialized by the owning LoadMap.
    """

    def __init__(self, capacity: int = 128):
        self.capacity = int(capacity)
        self._counts: dict[str, float] = {}

    def offer(self, key: str, n: float = 1.0) -> None:
        c = self._counts
        cur = c.get(key)
        if cur is not None:
            c[key] = cur + n
            return
        if len(c) < self.capacity:
            c[key] = n
            return
        # Evict the minimum; the newcomer inherits its count (bounded
        # table: this del is the RT006-visible shrink path).
        victim = min(c, key=c.get)
        floor = c[victim]
        del c[victim]
        c[key] = floor + n

    def scale(self, factor: float) -> None:
        for k in self._counts:
            self._counts[k] *= factor

    def top(self, count: int) -> list:
        return sorted(
            self._counts.items(), key=lambda kv: kv[1], reverse=True
        )[: max(0, int(count))]

    def __contains__(self, key) -> bool:
        return key in self._counts

    def __len__(self) -> int:
        return len(self._counts)


# Per-slot vector field order — the wire order of CLUSTER LOADMAP slot
# rows and the snapshot()/merge contract (cluster/client.py
# fleet_loadmap re-exposes it; keep docs/observability.md in sync).
SLOT_FIELDS = (
    "ops", "reads", "writes", "bytes_in", "bytes_out", "shed",
    "device_us", "keys",
)


class LoadMap:
    def __init__(self, *, sample_rate: float = 0.0, cluster: bool = False,
                 max_tenants: int = 32, topk_capacity: int = 128,
                 cms_width: int = 1024, cms_depth: int = 4,
                 half_life_s: float = 30.0, clock=time.monotonic):
        self.enabled = True
        self.sample_rate = float(sample_rate)
        # Slot attribution only means something under the cluster door;
        # standalone keeps everything in slot 0 (totals stay right).
        self.cluster = bool(cluster)
        self.max_tenants = int(max_tenants)
        self._clock = clock
        # LEAF lock by design: the keyspace hooks call note_key() under
        # grid-store / tenancy-registry locks, so nothing may be
        # acquired while this is held.
        self._lock = _witness.named(threading.Lock(), "obs.loadmap")
        # Per-slot planes.  'Q' = uint64 counters, 'd' = float
        # microseconds; bumped lock-free (see module doc).
        self.ops = array("Q", bytes(8 * NSLOTS))
        self.reads = array("Q", bytes(8 * NSLOTS))
        self.writes = array("Q", bytes(8 * NSLOTS))
        self.bytes_in = array("Q", bytes(8 * NSLOTS))
        self.bytes_out = array("Q", bytes(8 * NSLOTS))
        self.shed = array("Q", bytes(8 * NSLOTS))
        self.device_us = array("d", bytes(8 * NSLOTS))
        # EXACT live key count per slot ('q': a racing seed/hook pair
        # may transiently dip a slot below zero; clamped on read).
        self.key_count = array("q", bytes(8 * NSLOTS))
        # Hot-key sketches (dogfooded CMS + space-saving top-k).
        self._cms = DecayedCMS(cms_width, cms_depth, half_life_s, clock)
        self._topk = SpaceSavingTopK(topk_capacity)
        self._sampled = 0  # keys offered to the sketches, lifetime
        # Bounded tenant attribution table:
        # tenant -> [device_us, ops]; folds into OTHER_TENANT past
        # max_tenants (see _fold_tenants_locked).
        self._tenants: dict[str, list] = {}
        # Optional counter Family (created by Observability — RT005
        # keeps Family construction inside obs/) bumped with the
        # bounded tenant label at attribution time.
        self.tenant_device_us_family = None

    # -- per-slot accounting (lock-free hot path) --------------------------

    def note_command(self, slot, write: bool, bytes_in: int,
                     bytes_out: int, nops: int = 1) -> None:
        """One executed command (or one fused run): O(1) array bumps.
        ``slot`` is the door's routing decision (None = not served
        here — redirected/errored, nothing to attribute)."""
        if not self.enabled or slot is None:
            return
        self.ops[slot] += nops
        if write:
            self.writes[slot] += nops
        else:
            self.reads[slot] += nops
        if bytes_in:
            self.bytes_in[slot] += bytes_in
        if bytes_out:
            self.bytes_out[slot] += bytes_out

    def note_shed(self, slot) -> None:
        if not self.enabled or slot is None:
            return
        self.shed[slot] += 1

    # -- hot-key sampling ---------------------------------------------------

    def sample_keys(self, keys, n: int = 1) -> int:
        """Feed already-sampled keys into the sketches (the caller owns
        the sampling coin so the unsampled fast path never reaches this
        module).  Returns how many keys were offered."""
        if not self.enabled or not keys:
            return 0
        now = self._clock()
        offered = 0
        with self._lock:
            factor = self._cms.maybe_decay(now)
            if factor != 1.0:
                self._topk.scale(factor)
            for key in keys:
                k = _as_text(key)
                est = self._cms.add(k, n)
                # The CMS estimate (not the raw increment) feeds the
                # candidate table: a key re-entering after eviction
                # competes with its full observed weight.
                if k in self._topk:
                    self._topk.offer(k, n)
                else:
                    self._topk.offer(k, est)
                offered += 1
            self._sampled += offered
        return offered

    def hot_keys(self, count: int = 16) -> list:
        """[(key, estimated_decayed_count), ...] hottest first."""
        now = self._clock()
        with self._lock:
            factor = self._cms.maybe_decay(now)
            if factor != 1.0:
                self._topk.scale(factor)
            return [(k, c) for k, c in self._topk.top(count)]

    def sampled_keys(self) -> int:
        with self._lock:
            return self._sampled

    def tracked_keys(self) -> int:
        with self._lock:
            return len(self._topk)

    # -- exact per-slot key counters ---------------------------------------

    def note_key(self, name, delta: int) -> None:
        """Keyspace hook: ±1 per create/drop, called UNDER the store /
        registry lock — exact, so CLUSTER COUNTKEYSINSLOT is O(1)."""
        slot = key_slot(name) if self.cluster else 0
        with self._lock:
            self.key_count[slot] += delta

    def seed_keys(self, names) -> None:
        """Replace the key-count plane from one authoritative keyspace
        scan (server boot, after restore)."""
        counts = array("q", bytes(8 * NSLOTS))
        if self.cluster:
            for name in names:
                counts[key_slot(name)] += 1
        else:
            counts[0] = sum(1 for _ in names)
        with self._lock:
            self.key_count = counts

    def keys_in_slot(self, slot: int) -> int:
        with self._lock:
            return max(0, self.key_count[slot])

    # -- tenant device-time attribution ------------------------------------

    def attribute_launch(self, op: str, tenants, device_us: float) -> None:
        """Split one retired launch's device-side microseconds across
        the (tenant, nops) composition the coalescer recorded.  Called
        from the completer thread (span retirement) — off every
        client-facing path."""
        if not self.enabled or not tenants or device_us <= 0.0:
            return
        total = 0
        for _t, n in tenants:
            total += n
        if total <= 0:
            return
        fam = self.tenant_device_us_family
        bumps = []
        with self._lock:
            for tenant, n in tenants:
                us = device_us * (n / total)
                # Slot plane: the tenant label IS the sketch name, so
                # its slot is the key's slot (lock-free bump is fine,
                # we only hold the lock for the tenant table).
                slot = key_slot(tenant) if self.cluster else 0
                self.device_us[slot] += us
                ent = self._tenants.get(tenant)
                if ent is None:
                    self._tenants[tenant] = [us, n]
                else:
                    ent[0] += us
                    ent[1] += n
            if len(self._tenants) > self.max_tenants:
                self._fold_tenants_locked()
            if fam is not None:
                for tenant, n in tenants:
                    label = (tenant if tenant in self._tenants
                             else OTHER_TENANT)
                    bumps.append((label, device_us * (n / total)))
        if fam is not None:
            for label, us in bumps:
                fam.inc((label, op), us)

    def _fold_tenants_locked(self) -> None:
        """Bound the attribution table: keep the top ``max_tenants - 1``
        by device time, fold the rest into the OTHER_TENANT bucket
        (which itself is never evicted)."""
        t = self._tenants
        other = t.pop(OTHER_TENANT, None) or [0.0, 0]
        ranked = sorted(t.items(), key=lambda kv: kv[1][0], reverse=True)
        keep = ranked[: max(1, self.max_tenants - 1)]
        for _name, ent in ranked[len(keep):]:
            other[0] += ent[0]
            other[1] += ent[1]
        t.clear()
        t.update(keep)
        if other[0] or other[1]:
            t[OTHER_TENANT] = other

    def tenant_shares(self) -> dict:
        """{tenant: {device_us, ops, share}} — share of total attributed
        device time (INFO loadstats' billing view)."""
        with self._lock:
            items = [(k, v[0], v[1]) for k, v in self._tenants.items()]
        total = sum(us for _k, us, _n in items)
        out = {}
        for k, us, n in sorted(items, key=lambda e: e[1], reverse=True):
            out[k] = {
                "device_us": round(us, 1),
                "ops": int(n),
                "share": round(us / total, 4) if total > 0 else 0.0,
            }
        return out

    # -- aggregate views ----------------------------------------------------

    def top_slots(self, count: int = 8) -> list:
        """[(slot, ops), ...] busiest first, non-zero slots only."""
        ops = self.ops
        nz = [(s, ops[s]) for s in range(NSLOTS) if ops[s]]
        nz.sort(key=lambda e: e[1], reverse=True)
        return nz[: max(0, int(count))]

    def totals(self) -> dict:
        return {
            "ops": sum(self.ops),
            "reads": sum(self.reads),
            "writes": sum(self.writes),
            "bytes_in": sum(self.bytes_in),
            "bytes_out": sum(self.bytes_out),
            "shed": sum(self.shed),
            "device_us": round(sum(self.device_us), 1),
            "keys": sum(max(0, k) for k in self.key_count),
        }

    def snapshot(self) -> dict:
        """The CLUSTER LOADMAP payload: non-zero slot rows (slot ->
        SLOT_FIELDS-ordered vector), hottest keys, tenant shares.  Slot
        keys are strings because the payload travels as JSON."""
        slots = {}
        for s in range(NSLOTS):
            if (self.ops[s] or self.shed[s] or self.key_count[s]
                    or self.device_us[s]):
                slots[str(s)] = [
                    int(self.ops[s]), int(self.reads[s]),
                    int(self.writes[s]), int(self.bytes_in[s]),
                    int(self.bytes_out[s]), int(self.shed[s]),
                    round(self.device_us[s], 1),
                    max(0, self.key_count[s]),
                ]
        return {
            "fields": list(SLOT_FIELDS),
            "slots": slots,
            # 32, not the HOTKEYS-default 16: fleet merges re-rank
            # across nodes, and a per-node truncation at the final list
            # size would drop keys that are mid-tail locally but head
            # fleet-wide.
            "hot_keys": [[k, round(c, 2)] for k, c in self.hot_keys(32)],
            "tenants": self.tenant_shares(),
            "sample_rate": self.sample_rate,
            "sampled_keys": self.sampled_keys(),
            "totals": self.totals(),
        }

    def stats(self) -> dict:
        """Flat scalars for INFO loadstats (plus the shares/top views
        the section formats itself)."""
        t = self.totals()
        return {
            "loadmap_enabled": 1 if self.enabled else 0,
            "loadmap_key_sample_rate": self.sample_rate,
            "loadmap_ops": t["ops"],
            "loadmap_reads": t["reads"],
            "loadmap_writes": t["writes"],
            "loadmap_bytes_in": t["bytes_in"],
            "loadmap_bytes_out": t["bytes_out"],
            "loadmap_shed_ops": t["shed"],
            "loadmap_device_us": t["device_us"],
            "loadmap_keys": t["keys"],
            "loadmap_sampled_keys": self.sampled_keys(),
            "loadmap_tracked_keys": self.tracked_keys(),
            "loadmap_tracked_tenants": len(self._tenants),
        }

    def reset(self) -> None:
        """Zero every plane (bench warmup discipline, like
        Observability.reset_op_stats)."""
        with self._lock:
            for a in (self.ops, self.reads, self.writes, self.bytes_in,
                      self.bytes_out, self.shed):
                for i in range(NSLOTS):
                    a[i] = 0
            for i in range(NSLOTS):
                self.device_us[i] = 0.0
            self._cms = DecayedCMS(
                self._cms.width, self._cms.depth,
                self._cms.half_life_s, self._clock)
            self._topk = SpaceSavingTopK(self._topk.capacity)
            self._sampled = 0
            self._tenants.clear()


__all__ = [
    "DecayedCMS",
    "LoadMap",
    "OTHER_TENANT",
    "SLOT_FIELDS",
    "SpaceSavingTopK",
]
