"""Prometheus scrape endpoint (ISSUE 1 tentpole part 4).

A daemon ``ThreadingHTTPServer`` serving the text exposition at
``/metrics`` (anything else 404s).  Render happens per scrape from a
callable, so callback gauges (queue depth, device memory) are sampled
at scrape time — no background collection thread.
"""

from __future__ import annotations

import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable

CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"


class MetricsHTTPServer:
    def __init__(self, render: Callable[[], str], host: str = "127.0.0.1",
                 port: int = 0):
        outer = self

        class _Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"  # keep-alive between scrapes

            def do_GET(self):
                if self.path.split("?", 1)[0] not in ("/metrics", "/"):
                    self.send_response(404)
                    self.send_header("Content-Length", "0")
                    self.end_headers()
                    return
                try:
                    body = outer._render().encode()
                except Exception as e:  # a dying engine must not 500-loop
                    msg = str(e).encode()
                    self.send_response(500)
                    self.send_header("Content-Length", str(len(msg)))
                    self.end_headers()
                    self.wfile.write(msg)
                    return
                self.send_response(200)
                self.send_header("Content-Type", CONTENT_TYPE)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *a):  # scrapes must not spam stderr
                pass

        self._render = render
        self._httpd = ThreadingHTTPServer((host, port), _Handler)
        self._httpd.daemon_threads = True
        self.host, self.port = self._httpd.server_address[:2]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="rtpu-metrics-http",
            daemon=True,
        )
        self._thread.start()

    def close(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
