"""Labeled metrics registry — the measurement substrate every perf PR
reports against (ISSUE 1 tentpole part 1).

Monitoring is a Redisson PRO-only feature upstream (PAPER.md §5); this
registry is the built-in replacement: lock-cheap labeled counters,
gauges, and **log2-bucket histograms** (no per-sample sorting — the
bucket index is one ``int.bit_length()`` call) with per-command,
per-object-type, per-tenant, and per-shard dimensions.

Design constraints, in order:

- Hot-path observe/inc must stay a dict lookup + a tiny lock (the
  overhead guard in tests/test_observability.py bounds the instrumented
  submit path at ≤10% over a no-op stub).
- Prometheus exposition must be *typed correctly*: monotonic series are
  ``# TYPE ... counter`` with a ``_total`` suffix, distributions are
  real ``histogram`` families (``_bucket{le=}``/``_sum``/``_count``),
  point-in-time values are ``gauge`` — Prometheus rate() over a
  mis-typed gauge silently produces garbage.
- Label cardinality is bounded per family (``max_children``): past the
  cap, new label sets collapse into one ``"_overflow"`` child instead
  of growing without bound under per-tenant labels.
"""

from __future__ import annotations

import math
import threading
from typing import Callable, Optional, Sequence

# Log2 bucket boundaries in MICROSECONDS: 1us .. 2^25us (~33.5s), +Inf.
# Time histograms observe seconds; values are converted once at observe.
N_TIME_BUCKETS = 26


def bucket_index_us(v_us: float) -> int:
    """Index of the first bucket with upper bound >= v_us.

    Boundaries are ``le = 2**i`` microseconds: v=1 -> 0, v=2 -> 1,
    v=3 -> 2, v=4 -> 2, ...; values >= 2^25us land in the +Inf bucket.
    """
    n = int(math.ceil(v_us))
    if n <= 1:
        return 0
    idx = (n - 1).bit_length()
    return min(idx, N_TIME_BUCKETS)


def bucket_upper_bound_us(idx: int) -> float:
    return float("inf") if idx >= N_TIME_BUCKETS else float(1 << idx)


class _Child:
    """One label set's state.  ``kind`` decides which fields are live."""

    __slots__ = ("lock", "value", "buckets", "sum", "count")

    def __init__(self, kind: str):
        self.lock = threading.Lock()
        self.value = 0.0
        if kind == "histogram":
            self.buckets = [0] * (N_TIME_BUCKETS + 1)
            self.sum = 0.0
            self.count = 0


class Family:
    """One named metric family: children keyed by a label-value tuple."""

    OVERFLOW = "_overflow"

    def __init__(self, name: str, help: str, kind: str,
                 labelnames: Sequence[str] = (), max_children: int = 512):
        self.name = name
        self.help = help
        self.kind = kind  # "counter" | "gauge" | "histogram"
        self.labelnames = tuple(labelnames)
        self.max_children = max_children
        self._children: dict[tuple, _Child] = {}
        self._lock = threading.Lock()

    def child(self, labelvalues: tuple = ()) -> _Child:
        c = self._children.get(labelvalues)
        if c is None:
            with self._lock:
                c = self._children.get(labelvalues)
                if c is None:
                    if len(self._children) >= self.max_children:
                        # Bounded cardinality: spill into one sentinel
                        # child rather than growing per-tenant forever.
                        labelvalues = (self.OVERFLOW,) * len(self.labelnames)
                        c = self._children.get(labelvalues)
                        if c is not None:
                            return c
                    c = _Child(self.kind)
                    self._children[labelvalues] = c
        return c

    # -- hot-path updates --------------------------------------------------

    def inc(self, labelvalues: tuple = (), n: float = 1) -> None:
        c = self.child(labelvalues)
        with c.lock:
            c.value += n

    def set(self, labelvalues: tuple = (), v: float = 0.0) -> None:
        c = self.child(labelvalues)
        with c.lock:
            c.value = v

    def observe(self, labelvalues: tuple, seconds: float) -> None:
        c = self.child(labelvalues)
        idx = bucket_index_us(seconds * 1e6)
        with c.lock:
            c.buckets[idx] += 1
            c.sum += seconds
            c.count += 1

    # -- reads -------------------------------------------------------------

    def get(self, labelvalues: tuple = ()) -> float:
        c = self._children.get(labelvalues)
        return 0.0 if c is None else c.value

    def items(self):
        with self._lock:
            return list(self._children.items())

    def reset(self) -> None:
        with self._lock:
            self._children.clear()

    def percentiles(self, labelvalues: tuple, ps: Sequence[float]) -> list:
        """Percentile estimates (seconds) from the log2 buckets: the
        answer is the UPPER BOUND of the bucket holding the target rank
        (a ≤2x overestimate by construction — honest for SLO checks,
        no per-sample state).  n=1 and all-equal streams degenerate to
        that one bucket's bound for every p."""
        c = self._children.get(labelvalues)
        if c is None or c.count == 0:
            return [0.0 for _ in ps]
        with c.lock:
            buckets = list(c.buckets)
            n = c.count
        out = []
        for p in ps:
            rank = max(1, int(math.ceil(p / 100.0 * n)))
            acc = 0
            val = bucket_upper_bound_us(N_TIME_BUCKETS)
            for i, b in enumerate(buckets):
                acc += b
                if acc >= rank:
                    val = bucket_upper_bound_us(i)
                    break
            out.append(val / 1e6)
        return out


class MetricsRegistry:
    """Family registry + Prometheus text exposition.

    ``gauge_callback`` families are evaluated at render/snapshot time
    from a callable (point-in-time health: queue depth, device memory)
    so the hot path never pushes them.
    """

    def __init__(self):
        self._families: dict[str, Family] = {}
        self._callbacks: list[tuple[str, str, tuple, Callable]] = []
        self._lock = threading.Lock()

    def _register(self, name: str, help: str, kind: str, labelnames,
                  max_children: int) -> Family:
        with self._lock:
            fam = self._families.get(name)
            if fam is None:
                fam = Family(name, help, kind, labelnames, max_children)
                self._families[name] = fam
            return fam

    def counter(self, name: str, help: str = "", labelnames=(),
                max_children: int = 512) -> Family:
        # Prometheus counter naming contract: monotonic series end in
        # ``_total`` (satellite 1 of ISSUE 1 fixes the legacy renderer
        # for the same reason).
        if not name.endswith("_total"):
            name += "_total"
        return self._register(name, help, "counter", labelnames, max_children)

    def gauge(self, name: str, help: str = "", labelnames=(),
              max_children: int = 512) -> Family:
        return self._register(name, help, "gauge", labelnames, max_children)

    def histogram(self, name: str, help: str = "", labelnames=(),
                  max_children: int = 512) -> Family:
        return self._register(name, help, "histogram", labelnames, max_children)

    def gauge_callback(self, name: str, help: str, fn: Callable,
                       labelnames=()) -> None:
        """Register a render-time gauge: ``fn`` returns a scalar (no
        labels) or a dict {labelvalues_tuple: scalar}."""
        with self._lock:
            self._callbacks.append((name, help, tuple(labelnames), fn))

    def family(self, name: str) -> Optional[Family]:
        return self._families.get(name)

    # -- exposition --------------------------------------------------------

    @staticmethod
    def _fmt(v) -> str:
        """Integral values print as integers (counters are conceptually
        ints; '1.0' in the exposition is legal but noisy)."""
        if isinstance(v, float) and v.is_integer():
            return str(int(v))
        return str(v)

    @staticmethod
    def _labels(names: tuple, values: tuple) -> str:
        if not names:
            return ""
        pairs = ",".join(
            '%s="%s"' % (k, str(v).replace("\\", "\\\\").replace('"', '\\"'))
            for k, v in zip(names, values)
        )
        return "{" + pairs + "}"

    def render_prometheus(self) -> str:
        lines: list[str] = []
        with self._lock:
            families = sorted(self._families.items())
            callbacks = list(self._callbacks)
        for name, fam in families:
            items = fam.items()
            if not items:
                continue
            if fam.help:
                lines.append(f"# HELP {name} {fam.help}")
            lines.append(f"# TYPE {name} {fam.kind}")
            for labelvalues, c in sorted(items):
                lab = self._labels(fam.labelnames, labelvalues)
                if fam.kind == "histogram":
                    with c.lock:
                        buckets = list(c.buckets)
                        total, ssum = c.count, c.sum
                    acc = 0
                    for i, b in enumerate(buckets):
                        acc += b
                        le = bucket_upper_bound_us(i)
                        le_s = "+Inf" if le == float("inf") else repr(le / 1e6)
                        blab = self._labels(
                            fam.labelnames + ("le",), labelvalues + (le_s,)
                        )
                        lines.append(f"{name}_bucket{blab} {acc}")
                    lines.append(f"{name}_sum{lab} {ssum}")
                    lines.append(f"{name}_count{lab} {total}")
                else:
                    lines.append(f"{name}{lab} {self._fmt(c.value)}")
        for name, help, labelnames, fn in callbacks:
            try:
                v = fn()
            except Exception:
                continue  # a dead backend must not break exposition
            if help:
                lines.append(f"# HELP {name} {help}")
            lines.append(f"# TYPE {name} gauge")
            if isinstance(v, dict):
                for labelvalues, scalar in sorted(v.items()):
                    if scalar is None:
                        continue
                    lab = self._labels(labelnames, tuple(labelvalues))
                    lines.append(f"{name}{lab} {self._fmt(scalar)}")
            elif v is not None:
                lines.append(f"{name} {self._fmt(v)}")
        return "\n".join(lines) + "\n"
