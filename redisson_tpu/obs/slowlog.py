"""SLOWLOG-compatible slow-op ring buffer (ISSUE 1 tentpole part 3).

Semantics follow redis-server's slowlog.c: commands whose execution time
meets ``threshold_us`` are appended to a bounded ring (oldest evicted),
each entry carrying a monotonically increasing id, unix timestamp,
duration in microseconds, the (truncated) argument vector, and the
client's address/name.  ``threshold_us < 0`` disables logging;
``threshold_us == 0`` logs every command — both Redis behaviors.

Argument truncation mirrors Redis: at most 32 args (the last slot
replaced by a "... (N more arguments)" marker) and at most 128 bytes
per arg (suffixed with "... (N more bytes)").
"""

from __future__ import annotations

import threading
import time
from collections import deque

MAX_ARGS = 32
MAX_ARG_BYTES = 128


class SlowLogEntry:
    __slots__ = ("id", "unix_ts", "duration_us", "args", "client_addr",
                 "client_name", "trace_id")

    def __init__(self, id, unix_ts, duration_us, args, client_addr,
                 client_name, trace_id=""):
        self.id = id
        self.unix_ts = unix_ts
        self.duration_us = duration_us
        self.args = args
        self.client_addr = client_addr
        self.client_name = client_name
        # Slow-trace auto-capture (ISSUE 13): when the command was
        # sampled by the distributed tracer, its trace id rides the
        # slowlog entry so TRACE GET <id> answers "where did this slow
        # command's time go" directly from the SLOWLOG view.
        self.trace_id = trace_id


def _truncate_args(args) -> list[bytes]:
    out = []
    shown = args[: MAX_ARGS - 1] if len(args) > MAX_ARGS else args
    for a in shown:
        if not isinstance(a, bytes):
            a = str(a).encode()
        if len(a) > MAX_ARG_BYTES:
            a = a[:MAX_ARG_BYTES] + (
                b"... (%d more bytes)" % (len(a) - MAX_ARG_BYTES)
            )
        out.append(a)
    if len(args) > MAX_ARGS:
        out.append(b"... (%d more arguments)" % (len(args) - MAX_ARGS + 1))
    return out


class SlowLog:
    def __init__(self, max_len: int = 128, threshold_us: int = 10_000):
        self._lock = threading.Lock()
        self._ring: deque[SlowLogEntry] = deque(maxlen=max(1, max_len))
        self._next_id = 0
        self.threshold_us = threshold_us
        self.max_len = max(1, max_len)

    def maybe_add(self, duration_s: float, args, client_addr: str = "",
                  client_name: str = "", trace_id: str = "") -> bool:
        dur_us = int(duration_s * 1e6)
        if self.threshold_us < 0 or dur_us < self.threshold_us:
            return False
        entry_args = _truncate_args(args)
        with self._lock:
            e = SlowLogEntry(
                self._next_id, int(time.time()), dur_us, entry_args,
                client_addr, client_name or "", trace_id or "",
            )
            self._next_id += 1
            self._ring.append(e)
        return True

    def entries(self, count: int = -1) -> list[SlowLogEntry]:
        """Newest first, like SLOWLOG GET; count<0 = all."""
        with self._lock:
            out = list(self._ring)
        out.reverse()
        return out if count < 0 else out[:count]

    def reset(self) -> None:
        with self._lock:
            self._ring.clear()

    def __len__(self) -> int:
        with self._lock:
            return len(self._ring)

    # CONFIG SET hooks ------------------------------------------------------

    def set_threshold_us(self, us: int) -> None:
        self.threshold_us = int(us)

    def set_max_len(self, n: int) -> None:
        n = max(1, int(n))
        with self._lock:
            self.max_len = n
            self._ring = deque(self._ring, maxlen=n)
