"""Op-lifecycle spans (ISSUE 1 tentpole part 2).

One span per coalesced *launch* (segment), not per op — span cost
amortizes over the whole batch, so the producer-side submit path pays
nothing.  Phases are stamped as consecutive timestamps:

    submit ──(coalesce_wait)── stage start ──(host_stage)──
    staged ──(device_dispatch)── dispatched ──(d2h_fetch)── done

so the phase durations partition the end-to-end latency EXACTLY
(tests/test_observability.py asserts sum(phases) == end_to_end).
``host_stage`` covers the host-side pad/concat of the flush block,
which runs BEFORE the launch-slot wait so it overlaps in-flight device
execution (executor/coalescer.py _stage); ``device_dispatch`` therefore
includes any launch-slot wait plus the enqueue itself.  The
device-dispatch phase additionally runs under a
``jax.profiler.TraceAnnotation`` (see executor/coalescer.py), so a
captured device trace correlates with these host spans by name.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Optional

PHASES = ("coalesce_wait", "host_stage", "device_dispatch", "d2h_fetch")


class OpSpan:
    __slots__ = ("op", "nops", "t0", "stamps", "error", "_rec", "links",
                 "tenants")

    def __init__(self, op: str, nops: int, recorder: "SpanRecorder"):
        self.op = op
        self.nops = nops
        self.t0 = time.monotonic()
        self.stamps: list[tuple[str, float]] = []
        self.error = False
        self._rec = recorder
        # Distributed-trace parent links (ISSUE 13): TraceContexts of
        # sampled requests whose ops ride this launch.  A fused launch
        # carries one link per traced parent; the finish hook records
        # the launch span into EVERY linked trace.  None (not []) on the
        # untraced path — the common case allocates nothing.
        self.links = None
        # Load-attribution composition (ISSUE 16): [(tenant, nops)]
        # stashed by the coalescer's completer just before finish, so
        # the recorder can split this launch's device time per tenant.
        # None when no loadmap is armed — again allocates nothing.
        self.tenants = None

    def stamp(self, phase: str) -> None:
        """End the current phase NOW (phases are consecutive intervals:
        each stamp's duration runs from the previous stamp — or t0)."""
        self.stamps.append((phase, time.monotonic()))

    def add_ops(self, nops: int) -> None:
        self.nops += nops

    def link(self, ctx) -> None:
        """Attach a sampled request's TraceContext (or a tuple of them)
        as a parent of this launch.  DEDUPED by (trace, span) identity:
        one traced request whose K submits coalesce into this launch
        links once, not K times — duplicate links would flood the span
        ring with K identical launch spans per trace."""
        if self.links is None:
            self.links = []
        if isinstance(ctx, tuple):
            for c in ctx:
                self.link(c)
            return
        for ex in self.links:
            if ex.span_id == ctx.span_id and ex.trace_id == ctx.trace_id:
                return
        self.links.append(ctx)

    def phases(self) -> dict:
        out = {}
        prev = self.t0
        for name, t in self.stamps:
            out[name] = out.get(name, 0.0) + (t - prev)
            prev = t
        return out

    def end_to_end(self) -> float:
        return (self.stamps[-1][1] - self.t0) if self.stamps else 0.0

    def finish(self, error: bool = False) -> None:
        if self._rec is None:  # abandoned or already finished: no-op
            return
        self.error = error
        self._rec._finish(self)

    def abandon(self, into: "OpSpan" = None) -> None:
        """Merged-away segment: its ops ride another span — record
        nothing.  ``into`` (the surviving head span) inherits any trace
        parent links, so a merged launch still reports to every sampled
        request it serves."""
        self._rec = None
        if into is not None and self.links:
            into.link(tuple(self.links))
            self.links = None


class SpanRecorder:
    """Feeds finished spans into the registry's phase histograms and keeps
    the last ``keep`` spans for inspection (client.get_metrics views and
    the span-sum sanity test)."""

    def __init__(self, registry, keep: int = 256, latency=None):
        self._registry = registry
        # Optional LatencyMonitor (ISSUE 13): launches whose end-to-end
        # time meets latency-monitor-threshold record a "slow-launch"
        # event.  One compare per finish when disarmed.
        self.latency = latency
        # Optional LoadMap (ISSUE 16): retiring launches attribute
        # their device-side time (dispatch + fetch phases) to the
        # tenant composition stashed on the span.  One None-check per
        # finish when disarmed.
        self.loadmap = None
        self._phase_hist = registry.histogram(
            "rtpu_op_phase_seconds",
            "per-launch lifecycle phase durations", ("op", "phase"),
        )
        self._total_hist = registry.histogram(
            "rtpu_op_seconds", "per-launch end-to-end latency", ("op",),
        )
        self._ops = registry.counter(
            "rtpu_ops", "ops completed, by op type", ("op",),
        )
        self._errors = registry.counter(
            "rtpu_op_errors", "launches failed, by op type", ("op",),
        )
        self._recent: deque[OpSpan] = deque(maxlen=keep)
        self._lock = threading.Lock()

    def start(self, op: str, nops: int = 0) -> OpSpan:
        return OpSpan(op, nops, self)

    def _finish(self, span: OpSpan) -> None:
        span._rec = None
        phases = span.phases()
        e2e = span.end_to_end()
        for phase, dur in phases.items():
            self._phase_hist.observe((span.op, phase), dur)
        self._total_hist.observe((span.op,), e2e)
        if span.error:
            self._errors.inc((span.op,))
        else:
            self._ops.inc((span.op,), max(1, span.nops))
        lat = self.latency
        if lat is not None and lat.threshold_ms > 0:
            lat.record("slow-launch", e2e * 1e3)
        lm = self.loadmap
        if lm is not None and span.tenants and not span.error:
            # Device-side share of the launch: the dispatch (launch
            # wait + enqueue) and d2h fetch phases — host-side
            # coalesce/stage time is not device time and would inflate
            # a billing signal.
            us = (phases.get("device_dispatch", 0.0)
                  + phases.get("d2h_fetch", 0.0)) * 1e6
            try:
                lm.attribute_launch(span.op, span.tenants, us)
            except Exception:
                pass  # attribution must not fail the completer
        if span.links:
            self._feed_traces(span, phases, e2e)
        with self._lock:
            self._recent.append(span)

    @staticmethod
    def _feed_traces(span: OpSpan, phases: dict, e2e: float) -> None:
        """Record this launch into every linked trace (ISSUE 13): one
        span per sampled parent, each carrying the full phase breakdown
        and the total parent-link count — a fused launch stays visible
        as fused from inside any single trace."""
        nlinks = len(span.links)
        attrs = {
            "nops": span.nops,
            "links": nlinks,
        }
        for name, dur in phases.items():
            attrs[name + "_us"] = int(dur * 1e6)
        ts = time.time() - e2e  # wall start ≈ now - span length
        for ctx in span.links:
            try:
                ctx.tracer.record_span(
                    ctx, "launch:" + span.op, ts, e2e, attrs,
                    error=span.error,
                )
            except Exception:
                pass  # a dying tracer must not fail the completer

    def recent(self, op: Optional[str] = None) -> list[OpSpan]:
        with self._lock:
            spans = list(self._recent)
        return spans if op is None else [s for s in spans if s.op == op]

    def reset(self) -> None:
        """Zero the span-derived histograms/counters and the recent
        ring — the PUBLIC lifecycle surface (benches reset after warmup
        so compile-era samples don't pollute the warm-path evidence
        view; counters reset WITH the histograms — a snapshot mixing
        all-time op counts with reset-window percentiles would misstate
        ops-per-launch)."""
        self._phase_hist.reset()
        self._total_hist.reset()
        self._ops.reset()
        self._errors.reset()
        with self._lock:
            self._recent.clear()
