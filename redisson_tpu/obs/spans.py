"""Op-lifecycle spans (ISSUE 1 tentpole part 2).

One span per coalesced *launch* (segment), not per op — span cost
amortizes over the whole batch, so the producer-side submit path pays
nothing.  Phases are stamped as consecutive timestamps:

    submit ──(coalesce_wait)── stage start ──(host_stage)──
    staged ──(device_dispatch)── dispatched ──(d2h_fetch)── done

so the phase durations partition the end-to-end latency EXACTLY
(tests/test_observability.py asserts sum(phases) == end_to_end).
``host_stage`` covers the host-side pad/concat of the flush block,
which runs BEFORE the launch-slot wait so it overlaps in-flight device
execution (executor/coalescer.py _stage); ``device_dispatch`` therefore
includes any launch-slot wait plus the enqueue itself.  The
device-dispatch phase additionally runs under a
``jax.profiler.TraceAnnotation`` (see executor/coalescer.py), so a
captured device trace correlates with these host spans by name.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Optional

PHASES = ("coalesce_wait", "host_stage", "device_dispatch", "d2h_fetch")


class OpSpan:
    __slots__ = ("op", "nops", "t0", "stamps", "error", "_rec")

    def __init__(self, op: str, nops: int, recorder: "SpanRecorder"):
        self.op = op
        self.nops = nops
        self.t0 = time.monotonic()
        self.stamps: list[tuple[str, float]] = []
        self.error = False
        self._rec = recorder

    def stamp(self, phase: str) -> None:
        """End the current phase NOW (phases are consecutive intervals:
        each stamp's duration runs from the previous stamp — or t0)."""
        self.stamps.append((phase, time.monotonic()))

    def add_ops(self, nops: int) -> None:
        self.nops += nops

    def phases(self) -> dict:
        out = {}
        prev = self.t0
        for name, t in self.stamps:
            out[name] = out.get(name, 0.0) + (t - prev)
            prev = t
        return out

    def end_to_end(self) -> float:
        return (self.stamps[-1][1] - self.t0) if self.stamps else 0.0

    def finish(self, error: bool = False) -> None:
        if self._rec is None:  # abandoned or already finished: no-op
            return
        self.error = error
        self._rec._finish(self)

    def abandon(self) -> None:
        """Merged-away segment: its ops ride another span — record nothing."""
        self._rec = None


class SpanRecorder:
    """Feeds finished spans into the registry's phase histograms and keeps
    the last ``keep`` spans for inspection (client.get_metrics views and
    the span-sum sanity test)."""

    def __init__(self, registry, keep: int = 256):
        self._registry = registry
        self._phase_hist = registry.histogram(
            "rtpu_op_phase_seconds",
            "per-launch lifecycle phase durations", ("op", "phase"),
        )
        self._total_hist = registry.histogram(
            "rtpu_op_seconds", "per-launch end-to-end latency", ("op",),
        )
        self._ops = registry.counter(
            "rtpu_ops", "ops completed, by op type", ("op",),
        )
        self._errors = registry.counter(
            "rtpu_op_errors", "launches failed, by op type", ("op",),
        )
        self._recent: deque[OpSpan] = deque(maxlen=keep)
        self._lock = threading.Lock()

    def start(self, op: str, nops: int = 0) -> OpSpan:
        return OpSpan(op, nops, self)

    def _finish(self, span: OpSpan) -> None:
        span._rec = None
        for phase, dur in span.phases().items():
            self._phase_hist.observe((span.op, phase), dur)
        self._total_hist.observe((span.op,), span.end_to_end())
        if span.error:
            self._errors.inc((span.op,))
        else:
            self._ops.inc((span.op,), max(1, span.nops))
        with self._lock:
            self._recent.append(span)

    def recent(self, op: Optional[str] = None) -> list[OpSpan]:
        with self._lock:
            spans = list(self._recent)
        return spans if op is None else [s for s in spans if s.op == op]
