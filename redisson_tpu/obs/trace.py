"""Distributed tracing (ISSUE 13 tentpole part 1) — Dapper-style
sampled request tracing across the fleet (Sigelman et al. 2010,
PAPERS.md).

One ``Tracer`` per ``Observability`` bundle.  Head-based sampling: the
FIRST hop of a request (RESP ingress or the direct API) rolls the dice
once against ``sample_rate``; every downstream hop — reactor tick,
vectorizer run, coalescer segment, device launch, journal fsync fence,
and any cluster leg the request fans out to — inherits that decision.
Cross-process propagation rides an ``RTPU.TRACE <trace_id> <span_id>``
wire prelude (serve/resp.py): the cluster client / migration pump sends
it ahead of the traced command; a plain server errors on the unknown
command (harmless — the traced command still executes), a telemetry-
aware door consumes it like ASKING's one-shot flag.

Identifiers follow Dapper/W3C shape: 128-bit trace id, 64-bit span id,
parent span id; spans carry a wall-clock start, a duration, and a small
attr dict.  Finished spans land in a HARD-BOUNDED per-process ring
(``max_spans``) — tracing can never become a memory leak, only a
recency window.

Cost discipline (the chaos-module pattern): ``trace.ENABLED`` is a
module-level flag that is False while every live tracer's sample rate
is 0.  Every hot-path hook is guarded by ``if trace.ENABLED:`` so the
sampling-off cost is one attribute read + branch per site
(tests/test_observability.py bounds it at ≤5% on the submit path).

A fused launch serving ops from several traced requests records its
launch span into EVERY parent trace, each copy carrying the total
parent-link count (``links``) — the cross-connection batch-fusion
economics stay visible per trace.
"""

from __future__ import annotations

import json
import os
import random
import threading
import time
import weakref
from collections import deque
from typing import Optional

# Module guard (the chaos.ENABLED discipline): True iff ANY live tracer
# has a nonzero sample rate OR any trace scope is currently active in
# this process.  The second arm matters on fleet members whose OWN
# sampling is off: a remotely-propagated (RTPU.TRACE) span is forced —
# head-based sampling — and the coalescer hooks must still link its
# launches while its scope is live.  Hot-path hooks check this ONE
# module attribute before touching thread-locals or tracer state.
ENABLED = False

_tracers: "weakref.WeakSet" = weakref.WeakSet()
_guard_lock = threading.Lock()
_active_scopes = 0  # outermost live _Scope count (guarded)

_tls = threading.local()


def _recompute_enabled_locked() -> None:
    global ENABLED
    ENABLED = _active_scopes > 0 or any(
        t.sample_rate > 0.0 for t in _tracers
    )


def _recompute_enabled() -> None:
    with _guard_lock:
        _recompute_enabled_locked()


def current():
    """The ambient trace context(s) of this thread: None, one
    :class:`TraceContext`, or a tuple of them (a fused run executing on
    behalf of several traced requests)."""
    return getattr(_tls, "ctx", None)


class _Scope:
    """Context manager that installs ``ctx`` as the thread's ambient
    trace context for its body (restores the previous one on exit, so
    scopes nest).  An OUTERMOST scope also arms the module guard: a
    forced remote span must link its launches even on a node whose own
    sampling is off (the guard-lock round trip is paid only by traced
    commands, never by the off path)."""

    __slots__ = ("_ctx", "_prev", "_armed")

    def __init__(self, ctx):
        self._ctx = ctx
        self._prev = None
        self._armed = False

    def __enter__(self):
        self._prev = getattr(_tls, "ctx", None)
        _tls.ctx = self._ctx
        if self._ctx is not None and self._prev is None:
            global _active_scopes
            self._armed = True
            with _guard_lock:
                _active_scopes += 1
                _recompute_enabled_locked()
        return self._ctx

    def __exit__(self, *exc):
        _tls.ctx = self._prev
        if self._armed:
            global _active_scopes
            self._armed = False
            with _guard_lock:
                _active_scopes -= 1
                _recompute_enabled_locked()
        return False


def scope(ctx) -> _Scope:
    """``with trace.scope(span.ctx()): ...`` — anything that links the
    ambient context inside (coalescer submits, the fsync fence) joins
    the span's trace.  Accepts a single context or a tuple (multi-parent
    fused runs)."""
    return _Scope(ctx)


class TraceContext:
    """The propagatable identity of one live span: enough to parent a
    child span (locally or across the wire) and to reach the tracer
    that must record it."""

    __slots__ = ("tracer", "trace_id", "span_id")

    def __init__(self, tracer: "Tracer", trace_id: str, span_id: str):
        self.tracer = tracer
        self.trace_id = trace_id
        self.span_id = span_id

    def wire_args(self) -> list:
        """argv tail for the RTPU.TRACE prelude."""
        return [self.trace_id.encode(), self.span_id.encode()]


class TraceSpan:
    """One in-flight span.  ``end()`` records it into the tracer's ring;
    ``abandon()`` discards it (merged-away work whose ops ride another
    span).  rtpulint rule RT011 statically checks that every begin site
    reaches one of the two on all paths."""

    __slots__ = ("tracer", "trace_id", "span_id", "parent_id", "name",
                 "ts", "_t0", "attrs", "_done")

    def __init__(self, tracer, trace_id, span_id, parent_id, name):
        self.tracer = tracer
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_id = parent_id
        self.name = name
        self.ts = time.time()
        self._t0 = time.perf_counter()
        self.attrs: dict = {}
        self._done = False

    def ctx(self) -> TraceContext:
        return TraceContext(self.tracer, self.trace_id, self.span_id)

    def annotate(self, key: str, value) -> None:
        self.attrs[key] = value

    def end(self, error: bool = False) -> None:
        if self._done:
            return
        self._done = True
        dur_us = int((time.perf_counter() - self._t0) * 1e6)
        self.tracer._record({
            "trace_id": self.trace_id,
            "span_id": self.span_id,
            "parent_id": self.parent_id,
            "name": self.name,
            "ts": round(self.ts, 6),
            "dur_us": dur_us,
            "error": bool(error),
            "attrs": self.attrs,
        })

    def abandon(self) -> None:
        self._done = True


def _new_trace_id() -> str:
    return os.urandom(16).hex()


def _new_span_id() -> str:
    return os.urandom(8).hex()


class Tracer:
    """Per-process span collector with a live head-sampling knob and a
    hard ring bound.  ``sampled_counter`` / ``span_counter`` are
    optional registry families (the obs bundle passes its own) so trace
    volume is visible on /metrics."""

    def __init__(self, sample_rate: float = 0.0, max_spans: int = 2048,
                 sampled_counter=None, span_counter=None):
        self.sample_rate = 0.0
        self.max_spans = max(16, int(max_spans))
        self._spans: deque = deque(maxlen=self.max_spans)
        self._lock = threading.Lock()
        self._rng = random.Random()
        self.sampled = 0  # lifetime head-sampling hits
        self.evicted = 0  # spans pushed out of the ring
        self._sampled_counter = sampled_counter
        self._span_counter = span_counter
        _tracers.add(self)
        # Recompute the module guard when this tracer is GARBAGE
        # COLLECTED while armed: without this, dropping an armed tracer
        # (its WeakSet entry just vanishes) would leave ENABLED stuck
        # True and every hook paying the traced path forever.
        weakref.finalize(self, _recompute_enabled)
        if sample_rate:
            self.set_sample_rate(sample_rate)

    # -- sampling ----------------------------------------------------------

    def set_sample_rate(self, rate: float) -> None:
        rate = float(rate)
        if not 0.0 <= rate <= 1.0:
            raise ValueError(
                f"trace_sample_rate must be in [0, 1], got {rate!r}"
            )
        self.sample_rate = rate
        _recompute_enabled()

    def maybe_start(self, name: str,
                    parent: Optional[TraceContext] = None
                    ) -> Optional[TraceSpan]:
        """Head-sample a ROOT span (the request's first hop).  Returns
        None when the dice miss or sampling is off — callers guard with
        ``if trace.ENABLED`` so this is never reached on the off path."""
        rate = self.sample_rate
        if rate <= 0.0 or self._rng.random() >= rate:
            return None
        with self._lock:
            # Guarded: a bare += from N connection threads is a lossy
            # read-modify-write.
            self.sampled += 1
        if self._sampled_counter is not None:
            self._sampled_counter.inc()
        tid = parent.trace_id if parent is not None else _new_trace_id()
        pid = parent.span_id if parent is not None else ""
        return TraceSpan(self, tid, _new_span_id(), pid, name)

    def start(self, name: str, trace_id: str,
              parent_id: str = "") -> TraceSpan:
        """A FORCED span continuing an already-sampled trace (a remote
        hop's RTPU.TRACE prelude, or a local child): head-based sampling
        means the head's decision binds every downstream hop."""
        return TraceSpan(self, trace_id, _new_span_id(), parent_id, name)

    def start_child(self, parent: TraceSpan, name: str) -> TraceSpan:
        return self.start(name, parent.trace_id, parent.span_id)

    def span_scope(self, name: str):
        """Context manager for the direct API (client.trace(name)):
        mints a head-sampled root span and installs it as the ambient
        context, so every engine submit inside links to it.  Yields the
        span (or None when the dice missed)."""
        return _SpanScope(self, name)

    # -- recording ---------------------------------------------------------

    def _record(self, span: dict) -> None:
        with self._lock:
            if len(self._spans) >= self._spans.maxlen:
                self.evicted += 1
            self._spans.append(span)
        if self._span_counter is not None:
            self._span_counter.inc()

    def record_span(self, ctx: TraceContext, name: str, ts: float,
                    dur_s: float, attrs: Optional[dict] = None,
                    error: bool = False) -> None:
        """Record an already-timed span under ``ctx`` (the coalescer's
        launch spans arrive this way: timing came from the OpSpan, the
        parent from the submit-time link)."""
        self._record({
            "trace_id": ctx.trace_id,
            "span_id": _new_span_id(),
            "parent_id": ctx.span_id,
            "name": name,
            "ts": round(ts, 6),
            "dur_us": int(dur_s * 1e6),
            "error": bool(error),
            "attrs": dict(attrs or {}),
        })

    # -- export ------------------------------------------------------------

    def spans(self) -> list:
        with self._lock:
            return list(self._spans)

    def traces(self, trace_id: Optional[str] = None) -> dict:
        """{trace_id: [span dicts in arrival order]} — the ring grouped
        by trace; optionally filtered to one trace id."""
        out: dict = {}
        for s in self.spans():
            if trace_id is not None and s["trace_id"] != trace_id:
                continue
            out.setdefault(s["trace_id"], []).append(s)
        return out

    def traces_json(self, trace_id: Optional[str] = None) -> list:
        """One JSON document per trace (newest last) — the TRACE GET
        wire format, chosen so cross-node merges are a list concat."""
        return [
            json.dumps({"trace_id": tid, "spans": spans},
                       separators=(",", ":"))
            for tid, spans in self.traces(trace_id).items()
        ]

    def stats(self) -> dict:
        with self._lock:
            n = len(self._spans)
            tids = len({s["trace_id"] for s in self._spans})
        return {
            "sample_rate": self.sample_rate,
            "spans": n,
            "traces": tids,
            "max_spans": self.max_spans,
            "sampled": self.sampled,
            "evicted": self.evicted,
        }

    def reset(self) -> None:
        with self._lock:
            self._spans.clear()


class _SpanScope:
    __slots__ = ("_tracer", "_name", "_span", "_scope")

    def __init__(self, tracer: Tracer, name: str):
        self._tracer = tracer
        self._name = name
        self._span = None
        self._scope = None

    def __enter__(self):
        span = self._tracer.maybe_start(self._name) if ENABLED else None
        self._span = span
        if span is not None:
            self._scope = scope(span.ctx())
            self._scope.__enter__()
        return span

    def __exit__(self, exc_type, exc, tb):
        if self._scope is not None:
            self._scope.__exit__(exc_type, exc, tb)
        if self._span is not None:
            self._span.end(error=exc_type is not None)
        return False


__all__ = [
    "ENABLED",
    "TraceContext",
    "TraceSpan",
    "Tracer",
    "current",
    "scope",
]
