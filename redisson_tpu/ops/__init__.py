"""Jittable sketch kernels + golden NumPy twins.

This is L0 of the build plan (SURVEY.md §7): the device-side replacement for
what the Redis *server* does for SETBIT/GETBIT/PFADD/PFCOUNT/BITOP — the
reference client never implements sketch math itself (it ships commands,
→ org/redisson/RedissonBloomFilter.java, RedissonHyperLogLog.java), so these
kernels are new TPU-first designs, not ports.

Every kernel has a NumPy golden twin in ``ops/golden.py``; property tests
assert device-vs-golden equivalence (SURVEY.md §4's "golden CPU model").
"""
