"""Core bit-level device primitives shared by Bloom/BitSet kernels.

TPU-first replacement for what Redis does server-side on SETBIT/GETBIT
(the reference client only ships those commands in a pipelined batch,
→ org/redisson/RedissonBitSet.java, SURVEY.md §3.2): a whole batch of bit
ops becomes ONE XLA program — gathers for reads, and a sort-based
scatter-OR for writes.

Why the sort: XLA scatter with duplicate indexes has no bitwise-OR
combiner, and scatter-add would carry when two ops hit the same (word, bit).
We sort ops lexicographically by (word, bit) — stable, so arrival order is
preserved within a duplicate run — then only the *first* op of each run
contributes its mask to a scatter-add into a zero delta buffer (distinct
bits of one word sum to their OR), and the delta is OR-ed/AND-NOT-ed/XOR-ed
into the bitmap.  The run structure also yields exact *sequential* result
semantics (what value each op observed) matching one-op-at-a-time Redis
execution — SURVEY.md §7 hard part #2.

State convention: a pool of T tenant rows × W words lives as a flat
``uint32[T*W + 1]`` array; the trailing word is a scratch slot that padded
(invalid) ops target, so padding never perturbs run-detection for real ops
and scatters to it are harmless.

All functions here are pure and jittable; the executor layer applies
``jax.jit`` with buffer donation.
"""

from __future__ import annotations

import numpy as np
import jax.numpy as jnp
from jax import lax

_ONE = np.uint32(1)
_U5 = np.uint32(5)
_U7 = np.uint32(7)
_U31 = np.uint32(31)
_U127 = np.uint32(127)


def expand_km_indexes(h1m: jnp.ndarray, h2m: jnp.ndarray, m, k: int):
    """Kirsch–Mitzenmacher expansion: ``index_i = (h1 + i*h2) mod m``.

    Parity with RedissonBloomFilter#hash's index loop (SURVEY.md §2.2), in
    pure uint32: h1m, h2m are pre-reduced mod m on the host
    (hashing.km_reduce_mod), and m <= 2**31 guarantees ``a + b`` never wraps,
    so iterated conditional subtraction is exact.  Returns uint32[B, k].

    ``m`` may be a static int or a per-op ``uint32[B]`` array — the latter
    lets one compiled kernel serve every tenant of a size class even when
    their exact bit counts differ (same k, same word stride).
    """
    if isinstance(m, (int, np.integer)):
        if not 0 < m <= (1 << 31):
            raise ValueError(f"m must be in (0, 2**31], got {m}")
        m32 = np.uint32(m)
    else:
        m32 = m.astype(jnp.uint32)
    idx = h1m
    cols = [idx]
    for _ in range(k - 1):
        idx = idx + h2m
        idx = jnp.where(idx >= m32, idx - m32, idx)
        cols.append(idx)
    return jnp.stack(cols, axis=1)


def sort_runs(gword: jnp.ndarray, bit: jnp.ndarray):
    """Stable lexicographic sort of ops by (word, bit).

    Returns (sw, sb, sp, first, pos_in_run):
      sw/sb: sorted word/bit arrays (uint32),
      sp: original position of each sorted op (int32),
      first: bool mask — first op of each (word, bit) run,
      pos_in_run: 0-based rank of the op within its run (int32).
    """
    n = gword.shape[0]
    pos = jnp.arange(n, dtype=jnp.int32)
    sw, sb, sp = lax.sort((gword, bit, pos), num_keys=2, is_stable=True)
    first = jnp.concatenate(
        [jnp.ones((1,), bool), (sw[1:] != sw[:-1]) | (sb[1:] != sb[:-1])]
    )
    run_start = lax.cummax(jnp.where(first, pos, -1))
    return sw, sb, sp, first, pos - run_start


def segmented_exclusive_max(first: jnp.ndarray, vals: jnp.ndarray):
    """Exclusive running max within segments (segment starts where ``first``
    is True).  Classic segmented-scan via associative_scan; used to derive
    exact sequential semantics (what did op j observe?) for sorted
    duplicate runs without a serial loop."""

    def comb(a, b):
        f1, v1 = a
        f2, v2 = b
        return f1 | f2, jnp.where(f2, v2, jnp.maximum(v1, v2))

    _, inc = lax.associative_scan(comb, (first, vals))
    exc = jnp.concatenate([vals[:1] * 0, inc[:-1]])
    return jnp.where(first, vals * 0, exc)


def gather_words(flat: jnp.ndarray, gidx: jnp.ndarray):
    """Element gather from a flat pool array via the [R, 128] row-gather
    form (see gather_bits).  Works for any dtype; exact equivalent of
    ``flat[gidx]`` for in-range indexes."""
    n = flat.shape[0] - 1
    if n % 128 != 0:
        return flat[gidx]
    x2d = flat[:-1].reshape(n // 128, 128)
    rows = jnp.take(x2d, (gidx >> _U7).astype(jnp.int32), axis=0)
    lane = (gidx & _U127).astype(jnp.int32)
    onehot = jnp.arange(128, dtype=jnp.int32)[None, :] == lane[:, None]
    return jnp.sum(jnp.where(onehot, rows, 0), axis=1, dtype=flat.dtype)


def _scatter_onehot(flat, gidx, values, combine: str):
    """Elementwise scatter with duplicate indexes combined by ``combine``
    ('max' or 'add') — via one-hot 128-lane row scatter (the TPU-efficient
    scatter form).  Keeps the trailing scratch element.  Padded ops just
    need value 0 (the identity for both combiners over unsigned values).
    Falls back to element scatter for layouts that aren't 128-lane
    multiples (not produced by the registry)."""
    n = flat.shape[0] - 1
    if n % 128 != 0:
        ref = flat.at[gidx]
        return ref.max(values) if combine == "max" else ref.add(values)
    x2d = flat[:-1].reshape(n // 128, 128)
    brow = (gidx >> _U7).astype(jnp.int32)
    lane = (gidx & _U127).astype(jnp.int32)
    onehot = jnp.arange(128, dtype=jnp.int32)[None, :] == lane[:, None]
    upd = jnp.where(onehot, values[:, None], 0).astype(flat.dtype)
    ref = x2d.at[brow]
    new2d = ref.max(upd) if combine == "max" else ref.add(upd)
    return jnp.concatenate([new2d.reshape(-1), flat[-1:]])


def scatter_max_onehot(flat, gidx, values):
    """flat[gidx] = max(flat[gidx], values), duplicate-safe."""
    return _scatter_onehot(flat, gidx, values, "max")


def scatter_add_onehot(flat, gidx, values):
    """flat[gidx] += values, duplicates accumulate."""
    return _scatter_onehot(flat, gidx, values, "add")


def pack_bool_u32(flags):
    """bool[N] -> uint32[N/32] (N % 32 == 0), little-endian bit order.

    Per-op boolean results (contains hits, newly flags, prev bits) leave
    the device packed 32-to-a-word: D2H link bytes are the scarce resource
    on a tunneled host (measured ~300x slower than H2D), and 1 bit/op is
    the information-theoretic floor.  Host side unpacks with
    ``unpack_bool_u32``.
    """
    w = flags.reshape(-1, 32).astype(jnp.uint32)
    weights = (np.uint32(1) << np.arange(32, dtype=np.uint32))[None, :]
    return (w * weights).sum(axis=1, dtype=jnp.uint32)


def unpack_bool_u32(words, n: int) -> np.ndarray:
    """Host twin of pack_bool_u32: uint32[N/32] -> bool[n]."""
    b = np.unpackbits(
        np.ascontiguousarray(words, dtype=np.uint32).view(np.uint8),
        bitorder="little",
    )
    return b[:n].astype(bool)


def host_pack_bool_u32(flags: np.ndarray) -> np.ndarray:
    """Host twin of pack_bool_u32 for the H2D direction: bool[N]
    (N % 32 == 0) -> uint32[N/32], same little-endian bit order.  Boolean
    op columns (is_add, opcode flags) ship packed inside the fused
    staging block at 1 bit/op instead of 1 byte/op."""
    by = np.packbits(np.ascontiguousarray(flags), bitorder="little")
    if by.shape[0] % 4:
        by = np.concatenate([by, np.zeros(4 - by.shape[0] % 4, np.uint8)])
    return by.view(np.uint32)


def unpack_bool_u32_dev(words, n: int):
    """Device twin of unpack_bool_u32 for use INSIDE a jit: uint32[n/32]
    -> bool[n] (little-endian bit order, matching host_pack_bool_u32)."""
    idx = jnp.arange(n, dtype=jnp.int32)
    w = words[idx >> 5]
    return ((w >> (idx & 31).astype(jnp.uint32)) & _ONE).astype(jnp.bool_)


def route_invalid_to_scratch(gword, valid, flat_len: int):
    """Send padded ops to the trailing scratch word so they can't perturb
    run-detection or results of real ops (see module docstring)."""
    if valid is None:
        return gword
    return jnp.where(valid, gword, np.uint32(flat_len - 1))


def gather_bits(flat_words: jnp.ndarray, gword: jnp.ndarray, bit: jnp.ndarray):
    """GETBIT batch: uint32[N] of 0/1.

    TPU-shaped formulation: element gathers over a flat array lower to a
    pathological per-element path on TPU (~20x slower, measured on v5e), so
    the word array is viewed as [R, 128] lanes and whole 128-lane rows are
    gathered (the efficient TPU gather form), with the target word selected
    by a one-hot lane compare.  Exactly equivalent to flat_words[gword].

    Pool states keep (len-1) % 128 == 0 (registry classes are 128-word
    multiples); padded ops routed to the scratch word read out of range and
    are clipped by jnp.take's default clamping — their results are masked
    by the caller.
    """
    return (gather_words(flat_words, gword) >> bit) & _ONE


def scatter_set_bits(flat_words, gword, bit):
    """SETBIT(...,1) batch.  Returns (new_flat, prev_bit[N] in arrival order).

    prev_bit has exact sequential semantics: an op observes 1 if the bit was
    set pre-batch OR an earlier op in the batch set it.
    """
    sw, sb, sp, first, _ = sort_runs(gword, bit)
    pre = gather_bits(flat_words, sw, sb)
    delta = jnp.zeros_like(flat_words).at[sw].add((_ONE << sb) * first.astype(jnp.uint32))
    new = flat_words | delta
    prev_sorted = jnp.where(first, pre, _ONE)
    prev = jnp.zeros_like(prev_sorted).at[sp].set(prev_sorted)
    return new, prev


def scatter_set_bits_masked(flat_words, gword, bit, is_write):
    """SETBIT batch where only ``is_write`` ops set their bit; EVERY op
    (writer or reader) observes the bit value at its sequence position —
    set pre-batch OR by an earlier *writer* in the batch.

    This is the combined add+contains primitive: mixed read/write traffic
    on one pool coalesces into a single segment (one device launch) while
    keeping the exact one-op-at-a-time semantics of sequential Redis
    execution.  Returns (new_flat, observed uint32[N] 0/1, arrival order).
    """
    n = gword.shape[0]
    pos = jnp.arange(n, dtype=jnp.int32)
    wr = is_write.astype(jnp.int32)
    sw, sb, sp, swr = lax.sort((gword, bit, pos, wr), num_keys=2, is_stable=True)
    first = jnp.concatenate(
        [jnp.ones((1,), bool), (sw[1:] != sw[:-1]) | (sb[1:] != sb[:-1])]
    )
    # Earlier writer exists in this run <=> exclusive segmented max of
    # (pos+1 for writers, 0 for readers) is nonzero.
    earlier_writer = segmented_exclusive_max(first, swr * (sp + 1)) > 0
    pre = gather_bits(flat_words, sw, sb)
    obs_sorted = pre | earlier_writer.astype(jnp.uint32)
    contributes = (swr > 0) & ~earlier_writer
    delta = jnp.zeros_like(flat_words).at[sw].add(
        (_ONE << sb) * contributes.astype(jnp.uint32)
    )
    new = flat_words | delta
    obs = jnp.zeros_like(obs_sorted).at[sp].set(obs_sorted)
    return new, obs


def _segmented_affine_scan(first, b, a):
    """Segmented scan of bit-affine maps ``x -> a ^ (b & x)`` composed
    earlier-first.  Returns (eb, ea, ib, ia): exclusive and inclusive
    composites per element (exclusive = identity (1, 0) at segment starts).
    Composition (g after f): b = b_g & b_f, a = a_g ^ (b_g & a_f); the
    segment-reset combine is the standard Blelloch segmented-scan operator,
    associative because the underlying composition is."""

    def comb(x, y):
        f1, b1, a1 = x
        f2, b2, a2 = y
        return (
            f1 | f2,
            jnp.where(f2, b2, b2 & b1),
            jnp.where(f2, a2, a2 ^ (b2 & a1)),
        )

    _, ib, ia = lax.associative_scan(comb, (first, b, a))
    one = jnp.ones_like(b)
    zero = jnp.zeros_like(a)
    eb = jnp.where(first, one, jnp.concatenate([one[:1], ib[:-1]]))
    ea = jnp.where(first, zero, jnp.concatenate([zero[:1], ia[:-1]]))
    return eb, ea, ib, ia


def scatter_bit_affine(flat_words, gword, bit, b_coef, a_coef):
    """Unified GETBIT/SETBIT/clear/flip batch.  Each op applies
    ``x -> a ^ (b & x)`` to its bit — get:(1,0), set:(0,1), clear:(0,0),
    flip:(1,1) — and observes the value just *before* its own application
    (exact sequential semantics, so set/clear/flip report prev and get
    reports current).  One launch serves arbitrarily interleaved opcodes,
    which is what lets the coalescer keep a single segment per bitset pool.
    Returns (new_flat, observed uint32[N] 0/1, arrival order)."""
    n = gword.shape[0]
    pos = jnp.arange(n, dtype=jnp.int32)
    sw, sb, sp, sbc, sac = lax.sort(
        (
            gword,
            bit,
            pos,
            b_coef.astype(jnp.uint32),
            a_coef.astype(jnp.uint32),
        ),
        num_keys=2,
        is_stable=True,
    )
    first = jnp.concatenate(
        [jnp.ones((1,), bool), (sw[1:] != sw[:-1]) | (sb[1:] != sb[:-1])]
    )
    eb, ea, ib, ia = _segmented_affine_scan(first, sbc, sac)
    pre = gather_bits(flat_words, sw, sb)
    obs_sorted = ea ^ (eb & pre)
    # The last element of each run knows the run's final bit value; write
    # it with a clear+set pair of deltas (distinct bits of one word OR via
    # scatter-add of disjoint masks).
    last_of_run = jnp.concatenate([first[1:], jnp.ones((1,), bool)])
    final = ia ^ (ib & pre)
    t_delta = jnp.zeros_like(flat_words).at[sw].add(
        (_ONE << sb) * last_of_run.astype(jnp.uint32)
    )
    f_delta = jnp.zeros_like(flat_words).at[sw].add(
        (_ONE << sb) * (final * last_of_run.astype(jnp.uint32))
    )
    new = (flat_words & ~t_delta) | f_delta
    obs = jnp.zeros_like(obs_sorted).at[sp].set(obs_sorted)
    return new, obs


def scatter_clear_bits(flat_words, gword, bit):
    """SETBIT(...,0) batch.  Sequential prev semantics (0 after an earlier
    clear in the same batch)."""
    sw, sb, sp, first, _ = sort_runs(gword, bit)
    pre = gather_bits(flat_words, sw, sb)
    delta = jnp.zeros_like(flat_words).at[sw].add((_ONE << sb) * first.astype(jnp.uint32))
    new = flat_words & ~delta
    prev_sorted = jnp.where(first, pre, np.uint32(0))
    prev = jnp.zeros_like(prev_sorted).at[sp].set(prev_sorted)
    return new, prev


def scatter_flip_bits(flat_words, gword, bit):
    """Batch bit flip with parity-exact duplicate handling.

    A run of d flips of the same bit nets to ``d mod 2`` flips; op j in the
    run observes ``pre ^ (j mod 2)``.
    """
    sw, sb, sp, first, pos_in_run = sort_runs(gword, bit)
    pre = gather_bits(flat_words, sw, sb)
    nxt_first = jnp.concatenate([first[1:], jnp.ones((1,), bool)])
    odd_run = (pos_in_run & 1) == 0  # run length parity: last element's rank
    last_of_run = nxt_first
    contributes = last_of_run & odd_run  # one entry per odd-length run
    delta = jnp.zeros_like(flat_words).at[sw].add(
        (_ONE << sb) * contributes.astype(jnp.uint32)
    )
    new = flat_words ^ delta
    prev_sorted = pre ^ (pos_in_run & 1).astype(jnp.uint32)
    prev = jnp.zeros_like(prev_sorted).at[sp].set(prev_sorted)
    return new, prev


def row_slice(flat_words: jnp.ndarray, row, words_per_row: int):
    """Dynamic view of one tenant row (row may be a traced scalar)."""
    return lax.dynamic_slice(
        flat_words, (row * words_per_row,), (words_per_row,)
    )


def row_update(flat_words: jnp.ndarray, row, new_row: jnp.ndarray, words_per_row: int):
    return lax.dynamic_update_slice(flat_words, new_row, (row * words_per_row,))


def popcount_row(flat_words, row, words_per_row: int):
    """BITCOUNT of one tenant row."""
    words = row_slice(flat_words, row, words_per_row)
    return jnp.sum(lax.population_count(words).astype(jnp.int32))


def bit_length_row(flat_words, row, words_per_row: int):
    """Index of highest set bit + 1 (java BitSet.length()); 0 if empty."""
    words = row_slice(flat_words, row, words_per_row)
    nz = words != 0
    any_set = jnp.any(nz)
    widx = jnp.arange(words_per_row, dtype=jnp.int32)
    last_word = jnp.max(jnp.where(nz, widx, -1))
    w = words[jnp.maximum(last_word, 0)]
    msb = _U31 - lax.clz(w)  # valid only when w != 0
    length = last_word * 32 + msb.astype(jnp.int32) + 1
    return jnp.where(any_set, length, 0)


def bitpos_row(flat_words, row, words_per_row: int, target_bit: int):
    """BITPOS: index of first bit equal to ``target_bit``.

    Redis semantics: no set bit → -1; no clear bit within the value →
    the first index past it (size), never -1 for target 0.
    """
    words = row_slice(flat_words, row, words_per_row)
    if target_bit == 0:
        words = ~words
    nz = words != 0
    widx = jnp.arange(words_per_row, dtype=jnp.int32)
    first_word = jnp.min(jnp.where(nz, widx, words_per_row))
    w = words[jnp.minimum(first_word, words_per_row - 1)]
    # Lowest set bit: count trailing zeros = 31 - clz(w & -w).
    lsb = _U31 - lax.clz(w & (~w + _ONE))
    pos = first_word * 32 + lsb.astype(jnp.int32)
    none_found = np.int32(words_per_row * 32 if target_bit == 0 else -1)
    return jnp.where(jnp.any(nz), pos, none_found)


def range_mask_words(words_per_row: int, from_bit, to_bit):
    """uint32[W] mask with bits [from_bit, to_bit) set (traced scalars ok)."""
    widx = jnp.arange(words_per_row, dtype=jnp.int32)
    base = widx * 32
    # Per word, number of masked bits below/above.
    lo = jnp.clip(from_bit - base, 0, 32)
    hi = jnp.clip(to_bit - base, 0, 32)
    full = np.uint32(0xFFFFFFFF)
    # mask = bits [lo, hi) within the word.
    def below(n):  # bits [0, n) set, n in [0, 32]
        n = n.astype(jnp.uint32)
        return jnp.where(n >= 32, full, (_ONE << n) - _ONE)

    return below(hi) & ~below(lo)
