"""BitSet device kernels — semantics of org/redisson/RedissonBitSet.java
(Redis bitmap SETBIT/GETBIT/BITCOUNT/BITPOS/BITOP/range-set) on stacked
tenant bitmaps.

Single-bit batches ride the shared sort+scatter machinery in ops/bitops.py
(exact sequential prev-value semantics, duplicate-safe).  Range ops
(set(from,to), clear(from,to)) are word-mask kernels — one vector op over
the row instead of the reference's thousands of batched SETBITs
(SURVEY.md §2.2 RBitSet row).  Cross-key BITOP AND/OR/XOR/NOT runs
elementwise on gathered rows; its cross-shard variant lives in parallel/.
"""

from __future__ import annotations

import numpy as np
import jax.numpy as jnp

from redisson_tpu.ops import bitops


def _flat(rows, idx, words_per_row: int):
    gword = rows.astype(jnp.uint32) * np.uint32(words_per_row) + (idx >> np.uint32(5))
    return gword, idx & np.uint32(31)


def bitset_get(flat_words, rows, idx, *, words_per_row: int):
    gw, bt = _flat(rows, idx, words_per_row)
    return bitops.gather_bits(flat_words, gw, bt).astype(bool)


def bitset_set(flat_words, rows, idx, *, words_per_row: int, valid=None):
    gw, bt = _flat(rows, idx, words_per_row)
    gw = bitops.route_invalid_to_scratch(gw, valid, flat_words.shape[0])
    new, prev = bitops.scatter_set_bits(flat_words, gw, bt)
    return new, prev.astype(bool)


def bitset_clear(flat_words, rows, idx, *, words_per_row: int, valid=None):
    gw, bt = _flat(rows, idx, words_per_row)
    gw = bitops.route_invalid_to_scratch(gw, valid, flat_words.shape[0])
    new, prev = bitops.scatter_clear_bits(flat_words, gw, bt)
    return new, prev.astype(bool)


def bitset_flip(flat_words, rows, idx, *, words_per_row: int, valid=None):
    gw, bt = _flat(rows, idx, words_per_row)
    gw = bitops.route_invalid_to_scratch(gw, valid, flat_words.shape[0])
    new, prev = bitops.scatter_flip_bits(flat_words, gw, bt)
    return new, prev.astype(bool)


# Opcode encoding for bitset_mixed: (b << 1) | a of the bit-affine map
# x -> a ^ (b & x) each op applies to its bit.
OP_CLEAR, OP_SET, OP_GET, OP_FLIP = 0, 1, 2, 3


def bitset_mixed(flat_words, rows, idx, opcodes, *, words_per_row: int, valid=None):
    """Unified single-bit batch: per-op opcode in {OP_GET, OP_SET,
    OP_CLEAR, OP_FLIP} (see encoding above).  Exact sequential semantics:
    every op observes the bit value just before its own application.
    Returns (new_flat, observed bool[B])."""
    gw, bt = _flat(rows, idx, words_per_row)
    gw = bitops.route_invalid_to_scratch(gw, valid, flat_words.shape[0])
    b_coef = (opcodes >> np.uint32(1)) & np.uint32(1)
    a_coef = opcodes & np.uint32(1)
    new, obs = bitops.scatter_bit_affine(flat_words, gw, bt, b_coef, a_coef)
    return new, obs.astype(bool)


def bitset_set_range(flat_words, row, from_bit, to_bit, *, words_per_row: int, value: bool = True):
    """set(from, to) — word-mask kernel; from/to may be traced scalars."""
    mask = bitops.range_mask_words(words_per_row, from_bit, to_bit)
    cur = bitops.row_slice(flat_words, row, words_per_row)
    new_row = (cur | mask) if value else (cur & ~mask)
    return bitops.row_update(flat_words, row, new_row, words_per_row)


def bitset_cardinality(flat_words, row, *, words_per_row: int):
    return bitops.popcount_row(flat_words, row, words_per_row)


def bitset_length(flat_words, row, *, words_per_row: int):
    return bitops.bit_length_row(flat_words, row, words_per_row)


def bitset_bitpos(flat_words, row, *, words_per_row: int, target_bit: int):
    return bitops.bitpos_row(flat_words, row, words_per_row, target_bit)


def bitset_bitop(flat_words, dst_row, src_rows_words, *, words_per_row: int, op: str, limit_bits=None):
    """BITOP dst = op(src_1, ..., src_n) — cross-key op on pre-gathered rows.

    src_rows_words: uint32[S, W].  op in {and, or, xor, not}; `not` uses the
    first source only (Redis BITOP NOT is unary) and complements exactly the
    source's logical length ``limit_bits`` (a traced scalar) — bits beyond
    it stay 0, preserving the physical invariant that untouched tail bits
    of a size-class row are clear.
    """
    if op == "and":
        res = src_rows_words[0]
        for i in range(1, src_rows_words.shape[0]):
            res = res & src_rows_words[i]
    elif op == "or":
        res = src_rows_words[0]
        for i in range(1, src_rows_words.shape[0]):
            res = res | src_rows_words[i]
    elif op == "xor":
        res = src_rows_words[0]
        for i in range(1, src_rows_words.shape[0]):
            res = res ^ src_rows_words[i]
    elif op == "not":
        res = ~src_rows_words[0]
        if limit_bits is not None:
            res = res & bitops.range_mask_words(words_per_row, 0, limit_bits)
    else:
        raise ValueError(f"unknown bitop: {op}")
    return bitops.row_update(flat_words, dst_row, res, words_per_row)


def bitset_get_row(flat_words, row, *, words_per_row: int):
    """Raw bitmap fetch (asBitSet()/toByteArray() analog)."""
    return bitops.row_slice(flat_words, row, words_per_row)


def bitset_bitop_rows(flat_words, dst_row, src_rows, *, words_per_row: int, op: str, n_src: int, limit_bits=None):
    """BITOP with in-kernel source gather: src_rows is int32[n_src]."""
    rows2d = flat_words[:-1].reshape(-1, words_per_row)
    return bitset_bitop(
        flat_words,
        dst_row,
        rows2d[src_rows],
        words_per_row=words_per_row,
        op=op,
        limit_bits=limit_bits,
    )
