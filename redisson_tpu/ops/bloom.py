"""Bloom filter device kernels over stacked multi-tenant bitmaps.

Replaces the reference's per-call batched SETBIT/GETBIT fan-out
(→ org/redisson/RedissonBloomFilter.java add/contains via
CommandBatchService, SURVEY.md §3.2): a batch of B keys becomes one XLA
program — KM index expansion in-kernel, one gather (contains) or one
sort+scatter (add) over the pool.

Pool layout: ``uint32[T*W + 1]`` flat words (see ops/bitops.py), all
tenants in one size class share (m, W); per-op tenant rows route each key.
``k`` (hash iterations) is static per launch — the coalescer groups ops by
(size class, k).
"""

from __future__ import annotations

import numpy as np
import jax.numpy as jnp

from redisson_tpu.ops import bitops


def _op_words(rows, idx, words_per_row: int):
    """(row, bit index) -> flat word index + bit-in-word, uint32."""
    gword = rows.astype(jnp.uint32) * np.uint32(words_per_row) + (idx >> np.uint32(5))
    return gword, idx & np.uint32(31)


def bloom_contains(flat_words, rows, h1m, h2m, *, m: int, k: int, words_per_row: int):
    """bool[B]: all k bits set per key."""
    idx = bitops.expand_km_indexes(h1m, h2m, m, k)  # [B, k]
    gword, bit = _op_words(rows[:, None], idx, words_per_row)
    bits = bitops.gather_bits(flat_words, gword.reshape(-1), bit.reshape(-1))
    return bits.reshape(idx.shape).all(axis=1)


def bloom_add(flat_words, rows, h1m, h2m, *, m: int, k: int, words_per_row: int, valid=None):
    """Insert batch.  Returns (new_flat, newly_added bool[B]).

    newly_added matches Redisson add() semantics under sequential execution:
    True iff at least one of the key's k bits was unset both pre-batch and
    by all earlier keys in the batch.  ``valid``: optional bool[B] padding
    mask — invalid ops are routed to the scratch word and write nothing.
    """
    idx = bitops.expand_km_indexes(h1m, h2m, m, k)
    gword, bit = _op_words(rows[:, None], idx, words_per_row)
    if valid is not None:
        gword = bitops.route_invalid_to_scratch(
            gword, valid[:, None], flat_words.shape[0]
        )
    gw, bt = gword.reshape(-1), bit.reshape(-1)
    new, prev = bitops.scatter_set_bits(flat_words, gw, bt)
    newly = (prev == 0).reshape(idx.shape).any(axis=1)
    return new, newly


def bloom_mixed(flat_words, rows, h1m, h2m, is_add, *, m, k: int, words_per_row: int, valid=None):
    """Combined add+contains batch with exact sequential semantics.

    ``is_add`` bool[B] selects per op: add ops set their k bits and report
    newly-added (some bit unset both pre-batch and by all earlier adds in
    the batch); contains ops write nothing and report membership at their
    sequence position (bits set pre-batch or by earlier adds count).

    One kernel for both opcodes lets the coalescer keep a single segment
    per (pool, k) under mixed traffic — the config-4 shape — instead of
    breaking a new segment on every add/contains alternation.
    Returns (new_flat, result bool[B]).
    """
    idx = bitops.expand_km_indexes(h1m, h2m, m, k)
    gword, bit = _op_words(rows[:, None], idx, words_per_row)
    if valid is not None:
        gword = bitops.route_invalid_to_scratch(
            gword, valid[:, None], flat_words.shape[0]
        )
    gw, bt = gword.reshape(-1), bit.reshape(-1)
    wr = jnp.broadcast_to(is_add[:, None], idx.shape).reshape(-1)
    new, obs = bitops.scatter_set_bits_masked(flat_words, gw, bt, wr)
    all_set = (obs == 1).reshape(idx.shape).all(axis=1)
    result = jnp.where(is_add, ~all_set, all_set)
    return new, result


def bloom_cardinality(flat_words, row, *, m: int, k: int, words_per_row: int):
    """BITCOUNT-based estimate pieces: returns the set-bit count X of one
    tenant row; the host applies ``-m/k * ln(1 - X/m)``
    (→ RedissonBloomFilter#count)."""
    return bitops.popcount_row(flat_words, row, words_per_row)


def bloom_clear_row(flat_words, row, *, words_per_row: int):
    """Delete/clear one tenant's bitmap (RObject.delete analog)."""
    zeros = jnp.zeros((words_per_row,), dtype=jnp.uint32)
    return bitops.row_update(flat_words, row, zeros, words_per_row)
