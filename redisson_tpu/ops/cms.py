"""Count-min sketch device kernels — the new RCountMinSketch object.

Does NOT exist in the reference (SURVEY.md §2.2): BASELINE.json requires it
as a new RObject-idiom sketch.  Geometry: per tenant, ``d`` rows × ``w``
counters, stacked as ``uint32[T*d*w + 1]`` flat.  Update is a scatter-add
(duplicate keys in a batch each count — add semantics need no dedup);
estimate is a gather + min over rows.  Depth-row indexes reuse the KM
double-hash expansion with the per-row stride, matching the standard CMS
construction h_r(x) = (h1 + r*h2) mod w.
"""

from __future__ import annotations

import numpy as np
import jax.numpy as jnp

from redisson_tpu.ops import bitops


def _cell_indexes(rows, h1w, h2w, *, d: int, w: int, cells_per_row: int):
    """int32[B, d] flat cell indexes; h1w/h2w pre-reduced mod w.

    cells_per_row is the pool row stride — padded to a 128-multiple by the
    registry, which may exceed d*w (tail cells unused).
    """
    idx = bitops.expand_km_indexes(h1w, h2w, w, d)  # uint32[B, d]
    depth = np.uint32(w) * jnp.arange(d, dtype=jnp.uint32)[None, :]
    base = rows.astype(jnp.uint32)[:, None] * np.uint32(cells_per_row)
    return (base + depth + idx).astype(jnp.int32)


def cms_update(flat_counts, rows, h1w, h2w, weights, *, d: int, w: int, cells_per_row: int):
    """Add ``weights[B]`` (uint32, typically 1) to each key's d cells.
    One-hot row scatter-add: duplicates accumulate exactly."""
    cells = _cell_indexes(rows, h1w, h2w, d=d, w=w, cells_per_row=cells_per_row)
    upd = jnp.broadcast_to(weights.astype(jnp.uint32)[:, None], cells.shape)
    return bitops.scatter_add_onehot(
        flat_counts, cells.reshape(-1), upd.reshape(-1)
    )


def cms_estimate(flat_counts, rows, h1w, h2w, *, d: int, w: int, cells_per_row: int):
    """Point estimate: min over the d cells (classic CMS upper bound)."""
    cells = _cell_indexes(rows, h1w, h2w, d=d, w=w, cells_per_row=cells_per_row)
    vals = bitops.gather_words(flat_counts, cells.reshape(-1))
    return vals.reshape(cells.shape).min(axis=1)


def cms_update_and_estimate(
    flat_counts, rows, h1w, h2w, weights, *, d: int, w: int, cells_per_row: int
):
    """Fused streaming step (the heavy-hitter ingest path, BASELINE config
    5): apply updates, then return post-update estimates for the same keys —
    the host-side top-K tracker consumes the estimates.
    """
    new = cms_update(
        flat_counts, rows, h1w, h2w, weights, d=d, w=w, cells_per_row=cells_per_row
    )
    return new, cms_estimate(
        new, rows, h1w, h2w, d=d, w=w, cells_per_row=cells_per_row
    )


def cms_merge_rows(flat_counts, dst_row, src_rows_counts, *, cells_per_row: int):
    """Merge = elementwise sum of counter arrays (CMS is linear)."""
    dst = bitops.row_slice(flat_counts, dst_row, cells_per_row)
    merged = dst + src_rows_counts.sum(axis=0, dtype=jnp.uint32)
    return bitops.row_update(flat_counts, dst_row, merged, cells_per_row)


def cms_merge(flat_counts, dst_row, src_rows, *, cells_per_row: int):
    """Merge with in-kernel source gather: src_rows is int32[S]."""
    rows2d = flat_counts[:-1].reshape(-1, cells_per_row)
    return cms_merge_rows(
        flat_counts, dst_row, rows2d[src_rows], cells_per_row=cells_per_row
    )


def cms_clear_row(flat_counts, row, *, cells_per_row: int):
    zeros = jnp.zeros((cells_per_row,), dtype=jnp.uint32)
    return bitops.row_update(flat_counts, row, zeros, cells_per_row)
