"""Single-tenant fast-path bloom kernels — the bulk/bench hot path.

Design rationale (measured on the v5e chip): the exact sort-based add in
ops/bitops.py pays an O(B·k log) lexicographic sort per batch for exact
sequential duplicate semantics; this path instead materializes the batch's
bits in an int8 bit-delta ([bits/128, 128] rows, scatter-MAX of one-hot
rows — idempotent, so duplicate bits need no dedup), packs it to uint32
words with a weighted lane reduction, and ORs it into the tenant row.
~4x faster adds.

Semantic difference (documented, opt-in via
``Config.use_tpu_sketch(exact_add_semantics=False)``): the returned
``newly_added`` flags are computed against the PRE-BATCH state — two
identical keys in one batch both report True, where the exact path reports
True then False.  Bit-level results are identical; only duplicate-key
flags within a single batch differ.

The single-tenant restriction keeps the bit-delta at one row's size
(m/8 bytes); multi-tenant coalesced batches use the exact path.
"""

from __future__ import annotations

import numpy as np
import jax.numpy as jnp

from redisson_tpu.ops import bitops


def bloom_add_fast_st(flat_words, row, h1m, h2m, m, valid, *, k: int, words_per_row: int):
    """Single-tenant bulk add.  row and m are traced scalars (no per-op
    arrays to transfer).  Returns (new_flat, newly bool[B] vs pre-batch).
    """
    B = h1m.shape[0]
    idx = bitops.expand_km_indexes(h1m, h2m, m, k)  # [B, k] bit indexes
    # Pre-batch membership for newly flags (row gathers, exact).
    base_word = row.astype(jnp.uint32) * np.uint32(words_per_row)
    gword = base_word + (idx >> np.uint32(5))
    bit = idx & np.uint32(31)
    pre = bitops.gather_bits(flat_words, gword.reshape(-1), bit.reshape(-1))
    newly = (pre == 0).reshape(B, k).any(axis=1)

    # int8 bit-delta over this tenant's row only, plus one padding row that
    # absorbs invalid (batch-padding) ops.
    rb = words_per_row * 32 // 128  # bit-rows in one tenant row
    local_bit = idx.reshape(-1)
    brow = (local_bit >> np.uint32(7)).astype(jnp.int32)
    if valid is not None:
        valid_flat = jnp.broadcast_to(valid[:, None], idx.shape).reshape(-1)
        brow = jnp.where(valid_flat, brow, rb)
    lane = (local_bit & np.uint32(127)).astype(jnp.int32)
    onehot = (
        jnp.arange(128, dtype=jnp.int32)[None, :] == lane[:, None]
    ).astype(jnp.int8)
    delta8 = jnp.zeros((rb + 1, 128), jnp.int8).at[brow].max(onehot)
    # Pack 128 bits/row -> 4 uint32 words/row (weighted lane reduction).
    weights = (np.uint32(1) << np.arange(32, dtype=np.uint32))[None, None, :]
    packed = (delta8[:rb].reshape(rb, 4, 32).astype(jnp.uint32) * weights).sum(
        axis=-1, dtype=jnp.uint32
    )
    delta_words = packed.reshape(-1)  # [words_per_row]

    cur = bitops.row_slice(flat_words, row, words_per_row)
    new = bitops.row_update(flat_words, row, cur | delta_words, words_per_row)
    return new, newly


def bloom_contains_st(flat_words, row, h1m, h2m, m, *, k: int, words_per_row: int):
    """Single-tenant contains with scalar row/m operands (halves the H2D
    transfer volume vs the per-op array form).  Bit-exact with
    ops/bloom.bloom_contains."""
    B = h1m.shape[0]
    idx = bitops.expand_km_indexes(h1m, h2m, m, k)
    base_word = row.astype(jnp.uint32) * np.uint32(words_per_row)
    gword = base_word + (idx >> np.uint32(5))
    bit = idx & np.uint32(31)
    bits = bitops.gather_bits(flat_words, gword.reshape(-1), bit.reshape(-1))
    return bits.reshape(B, k).all(axis=1)


# --------------------------------------------------------------------------
# Device-side hashing: ship raw codec lanes, hash + reduce in-kernel.
#
# The host pipeline (murmur batch + uint64 km_reduce_mod) tops out around
# ~20M keys/s/core and serializes with dispatch; hashing on the VPU rides
# along with the gather kernel for free and shrinks H2D to the raw key
# bytes.  The 64-bit ``h % m`` that km_reduce_mod does with cheap host
# uint64 is reproduced EXACTLY in uint32 via 64 unrolled bit-Horner steps
# (r = 2r + bit; r -= m if r >= m — one conditional subtract suffices since
# r < m <= 2**31 keeps 2r + bit < 2**32), so device-hashed results are
# bit-identical to the host/golden path and cross-engine parity holds.
# --------------------------------------------------------------------------


def mod64_bits(hi, lo, m32):
    """Exact ``(hi * 2**32 + lo) % m`` for uint32 lanes, m <= 2**31."""
    r = jnp.zeros_like(hi)
    one = np.uint32(1)
    for word in (hi, lo):
        for b in range(31, -1, -1):
            bit = (word >> np.uint32(b)) & one
            r = (r << one) | bit
            r = jnp.where(r >= m32, r - m32, r)
    return r


def pad_lanes(blocks, target_lanes: int):
    """Restore trailing all-zero lanes the host trimmed off before H2D
    (link bytes are scarce; zeros are free to rebuild).  ``target_lanes``
    must be the ORIGINAL lane count — murmur mixes every 16-byte block,
    zeros included, so the block count is part of the hash input."""
    lanes = blocks.shape[-1]
    if lanes == target_lanes:
        return blocks
    return jnp.concatenate(
        [
            blocks,
            jnp.zeros((*blocks.shape[:-1], target_lanes - lanes), blocks.dtype),
        ],
        axis=-1,
    )


def _hash_km_device(blocks, lengths, m, target_lanes: int):
    """murmur3_x86_128 on device → (h1m, h2m) uint32[B], bit-identical to
    hashing.hash128_np + hashing.km_reduce_mod."""
    from redisson_tpu.utils import hashing

    blocks = pad_lanes(blocks, target_lanes)
    c0, c1, c2, c3 = hashing.murmur3_x86_128(blocks, lengths, xp=jnp)
    m32 = m.astype(jnp.uint32) if hasattr(m, "astype") else np.uint32(m)
    # hash128_np: h1 = c0 | c1<<32, h2 = c2 | c3<<32.
    h1m = mod64_bits(c1, c0, m32)
    h2m = mod64_bits(c3, c2, m32)
    return h1m, h2m


def bloom_add_keys_st(flat_words, row, blocks, lengths, m, valid, *, k: int, words_per_row: int, target_lanes: int):
    """Single-tenant bulk add from raw key lanes (device-side hashing)."""
    h1m, h2m = _hash_km_device(blocks, lengths, m, target_lanes)
    return bloom_add_fast_st(
        flat_words, row, h1m, h2m, m, valid, k=k, words_per_row=words_per_row
    )


def bloom_contains_keys_st(flat_words, row, blocks, lengths, m, *, k: int, words_per_row: int, target_lanes: int):
    """Single-tenant contains from raw key lanes (device-side hashing)."""
    h1m, h2m = _hash_km_device(blocks, lengths, m, target_lanes)
    return bloom_contains_st(
        flat_words, row, h1m, h2m, m, k=k, words_per_row=words_per_row
    )


def bloom_mixed_keys(flat_words, rows, blocks, lengths, m_arr, is_add, valid, *, k: int, words_per_row: int, target_lanes: int):
    """Multi-tenant combined add+contains from raw key lanes: murmur +
    exact 64-bit mod run in-kernel (bit-identical to the host pipeline),
    then the exact sequential mixed kernel.  This is the coalesced hot
    path: producers ship only codec bytes, so host threads never hash —
    the config-4 offered-load regime stops serializing on the GIL."""
    from redisson_tpu.ops import bloom

    h1m, h2m = _hash_km_device(blocks, lengths, m_arr, target_lanes)
    return bloom.bloom_mixed(
        flat_words, rows, h1m, h2m, is_add,
        m=m_arr, k=k, words_per_row=words_per_row, valid=valid,
    )


def hll_add_keys_single(flat_regs, row, blocks, lengths, valid, *, target_lanes: int):
    """Single-tenant PFADD from raw key lanes — murmur on device, then the
    standard scatter-max; returns (new, changed)."""
    from redisson_tpu.ops import hll as hll_ops
    from redisson_tpu.utils import hashing

    c0, c1, c2, _ = hashing.murmur3_x86_128(
        pad_lanes(blocks, target_lanes), lengths, xp=jnp
    )
    return hll_ops.hll_add_single(flat_regs, row, c0, c1, c2, valid=valid)
