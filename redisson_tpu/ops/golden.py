"""Golden NumPy models of every sketch — the test oracle.

The reference has no such layer: Redisson trusts the Redis server for sketch
semantics (→ org/redisson/RedissonHyperLogLog.java is a thin PFADD/PFCOUNT
wrapper; SURVEY.md §2.2).  We build what upstream's test strategy lacks
(SURVEY.md §4): every device kernel is property-tested against these models,
FPP is checked against analytic bounds, and HLL error against 1.04/sqrt(m).

These models are deliberately simple (bool arrays, np.maximum.at, np.add.at)
— clarity over speed.  The device kernels in ops/*.py must match them
behaviorally (not layout-wise).
"""

from __future__ import annotations

import math

import numpy as np

# --------------------------------------------------------------------------
# Bloom filter — parity with org/redisson/RedissonBloomFilter.java math:
#   m = ceil(-n ln p / (ln 2)^2),  k = max(1, round(m/n * ln 2)),
#   index_i = (h1 + i*h2) mod m  (Kirsch–Mitzenmacher double hashing).
# --------------------------------------------------------------------------

MAX_BLOOM_BITS = 1 << 31  # device kernels require m <= 2**31 (uint32 index math)


def optimal_num_of_bits(expected_insertions: int, false_probability: float,
                        max_bits: int = MAX_BLOOM_BITS) -> int:
    """→ RedissonBloomFilter#optimalNumOfBits (standard formula)."""
    if false_probability <= 0 or false_probability >= 1:
        raise ValueError("falseProbability must be in (0, 1)")
    n = max(1, expected_insertions)
    m = math.ceil(-n * math.log(false_probability) / (math.log(2) ** 2))
    max_bits = min(int(max_bits), MAX_BLOOM_BITS)
    if m > max_bits:
        # The reference rejects oversized filters rather than silently
        # degrading FPP (RedissonBloomFilter caps size, SURVEY.md §2.2).
        raise ValueError(
            f"bloom filter needs {m} bits for n={expected_insertions}, "
            f"p={false_probability}; max is {max_bits}"
        )
    return max(m, 16)


def optimal_num_of_hash_functions(expected_insertions: int, size: int) -> int:
    """→ RedissonBloomFilter#optimalNumOfHashFunctions."""
    n = max(1, expected_insertions)
    return max(1, round(size / n * math.log(2)))


class GoldenBloomFilter:
    """Plain bool-array Bloom filter fed pre-reduced (h1m, h2m) pairs."""

    def __init__(self, size: int, hash_iterations: int):
        self.size = int(size)
        self.hash_iterations = int(hash_iterations)
        self.bits = np.zeros(self.size, dtype=bool)

    def _indexes(self, h1m: np.ndarray, h2m: np.ndarray) -> np.ndarray:
        i = np.arange(self.hash_iterations, dtype=np.uint64)
        return (
            h1m[:, None].astype(np.uint64) + i[None, :] * h2m[:, None].astype(np.uint64)
        ) % np.uint64(self.size)

    def add_hashed(self, h1m: np.ndarray, h2m: np.ndarray) -> np.ndarray:
        """Returns bool[B]: True where at least one bit was newly set
        (Redisson's add() result semantics)."""
        idx = self._indexes(h1m, h2m)
        newly = np.zeros(idx.shape[0], dtype=bool)
        for b in range(idx.shape[0]):  # sequential: later keys see earlier bits
            row = idx[b]
            newly[b] = bool(np.any(~self.bits[row]))
            self.bits[row] = True
        return newly

    def contains_hashed(self, h1m: np.ndarray, h2m: np.ndarray) -> np.ndarray:
        idx = self._indexes(h1m, h2m)
        return self.bits[idx].all(axis=1)

    def cardinality_estimate(self) -> int:
        """BITCOUNT-based inversion: n ≈ -m/k * ln(1 - X/m)
        (→ RedissonBloomFilter#count)."""
        x = int(self.bits.sum())
        if x >= self.size:
            return self.size
        return int(
            round(-self.size / self.hash_iterations * math.log(1 - x / self.size))
        )


# --------------------------------------------------------------------------
# HyperLogLog — Redis-server parity geometry: p=14 → 16384 registers, 6-bit
# register values 0..51 (q=50).  The reference client never does this math
# (server-side PFADD/PFCOUNT); we use the Ertl improved raw estimator, which
# needs no empirical bias tables and beats the stock bias-corrected
# HLL within the 1.04/sqrt(m) ≈ 0.81% error budget.
# --------------------------------------------------------------------------

HLL_P = 14
HLL_M = 1 << HLL_P
HLL_Q = 50  # max rank = Q + 1 = 51, fits 6-bit Redis registers


def hll_index_rank(c0: np.ndarray, c1: np.ndarray, c2: np.ndarray):
    """Map three 32-bit hash lanes to (register index, rank).

    index = low 14 bits of c0; rank = leading-zero count of the 50-bit
    stream (c1 ++ top-18-bits-of-c2) plus one, i.e. 51 - bit_length(u50).
    Uses lanes independent of the index lane, so index/rank correlation is
    zero by construction.
    """
    idx = (c0 & np.uint32(HLL_M - 1)).astype(np.int64)
    u50 = (c1.astype(np.uint64) << np.uint64(18)) | (
        c2.astype(np.uint64) >> np.uint64(14)
    )
    # Exact bit_length via frexp: u50 < 2**50 < 2**53 so float64 is exact.
    _, exp = np.frexp(u50.astype(np.float64))
    rank = (np.int64(HLL_Q + 1) - exp.astype(np.int64)).astype(np.uint8)
    return idx, rank


def _sigma(x: float) -> float:
    if x == 1.0:
        return math.inf
    y, z = 1.0, x
    while True:
        x = x * x
        z_prev = z
        z = z + x * y
        y = y + y
        if z == z_prev:
            return z


def _tau(x: float) -> float:
    if x == 0.0 or x == 1.0:
        return 0.0
    y, z = 1.0, 1.0 - x
    while True:
        x = math.sqrt(x)
        z_prev = z
        y = 0.5 * y
        z = z - (1.0 - x) ** 2 * y
        if z == z_prev:
            return z / 3.0


def ertl_estimate(counts: np.ndarray, m: int = HLL_M, q: int = HLL_Q) -> float:
    """Ertl improved raw estimator from the register-value histogram.

    counts: int[q+2] — multiplicity of each register value 0..q+1.
    """
    z = m * _tau(1.0 - counts[q + 1] / m)
    for k in range(q, 0, -1):
        z = 0.5 * (z + float(counts[k]))
    z = z + m * _sigma(counts[0] / m)
    alpha_inf = 0.5 / math.log(2.0)
    return alpha_inf * m * m / z


class GoldenHyperLogLog:
    def __init__(self):
        self.regs = np.zeros(HLL_M, dtype=np.uint8)

    def add_hashed(self, c0, c1, c2) -> None:
        idx, rank = hll_index_rank(c0, c1, c2)
        np.maximum.at(self.regs, idx, rank)

    def count(self) -> int:
        counts = np.bincount(self.regs, minlength=HLL_Q + 2)
        return int(round(ertl_estimate(counts)))

    def merge(self, *others: "GoldenHyperLogLog") -> None:
        for o in others:
            np.maximum(self.regs, o.regs, out=self.regs)


# --------------------------------------------------------------------------
# BitSet — semantics of org/redisson/RedissonBitSet.java over Redis bitmaps:
# auto-grow on set, BITCOUNT/BITPOS, cross-key BITOP AND/OR/XOR/NOT.
# --------------------------------------------------------------------------


class GoldenCountMinSketch:
    """Golden CMS twin (the new RObject — no reference counterpart).

    Counters are uint32 — the device pool dtype — so per-cell totals wrap
    mod 2**32 *identically* in both engines (np.add.at and the device
    scatter-add share two's-complement wrap semantics).  The documented
    contract is therefore: per-cell counts are exact up to 2**32-1; callers
    needing larger totals must shard keys or widen at the application
    level.
    """

    def __init__(self, depth: int, width: int):
        self.depth = int(depth)
        self.width = int(width)
        self.counts = np.zeros((self.depth, self.width), dtype=np.uint32)

    def _cells(self, h1w: np.ndarray, h2w: np.ndarray) -> np.ndarray:
        r = np.arange(self.depth, dtype=np.uint64)
        return (
            h1w[:, None].astype(np.uint64) + r[None, :] * h2w[:, None].astype(np.uint64)
        ) % np.uint64(self.width)

    def add_hashed(self, h1w, h2w, weights=None) -> None:
        cells = self._cells(h1w, h2w)
        w = (
            np.ones(len(h1w), np.uint32)
            if weights is None
            else np.asarray(weights, np.uint32)
        )
        for r in range(self.depth):
            np.add.at(self.counts[r], cells[:, r], w)

    def estimate_hashed(self, h1w, h2w) -> np.ndarray:
        cells = self._cells(h1w, h2w)
        return self.counts[np.arange(self.depth)[None, :], cells].min(axis=1)

    def merge(self, other: "GoldenCountMinSketch") -> None:
        self.counts += other.counts


class GoldenBitSet:
    def __init__(self, nbits: int = 0):
        self.bits = np.zeros(int(nbits), dtype=bool)

    def _grow(self, nbits: int) -> None:
        if nbits > self.bits.size:
            nb = np.zeros(int(nbits), dtype=bool)
            nb[: self.bits.size] = self.bits
            self.bits = nb

    @staticmethod
    def _check_indexes(indexes) -> np.ndarray:
        indexes = np.asarray(indexes, dtype=np.int64)
        if indexes.size and int(indexes.min()) < 0:
            # Java BitSet semantics: negative index is an error, never a wrap.
            raise IndexError("bit index must be non-negative")
        return indexes

    def set(self, indexes: np.ndarray, value: bool = True) -> np.ndarray:
        indexes = self._check_indexes(indexes)
        if indexes.size:
            self._grow(int(indexes.max()) + 1)
        prev = np.empty(indexes.shape, dtype=bool)
        # Sequential semantics for duplicate indexes inside one batch.
        for j, ix in enumerate(indexes):
            prev[j] = self.bits[ix]
            self.bits[ix] = value
        return prev

    def get(self, indexes: np.ndarray) -> np.ndarray:
        indexes = self._check_indexes(indexes)
        out = np.zeros(indexes.shape, dtype=bool)
        in_range = indexes < self.bits.size
        out[in_range] = self.bits[indexes[in_range]]
        return out

    def cardinality(self) -> int:
        return int(self.bits.sum())

    def length(self) -> int:
        """Index of highest set bit + 1 (java BitSet.length semantics)."""
        nz = np.nonzero(self.bits)[0]
        return int(nz[-1]) + 1 if nz.size else 0
