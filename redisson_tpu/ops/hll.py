"""HyperLogLog device kernels — the math Redis keeps server-side.

The reference client is a thin PFADD/PFCOUNT/PFMERGE command wrapper
(→ org/redisson/RedissonHyperLogLog.java, SURVEY.md §2.2); the sketch
itself (registers, estimator, merge) lives in the Redis server.  Here it is
TPU-native: registers are a stacked ``uint8[T*16384 + 1]`` array (p=14,
6-bit value range 0..51 — Redis geometry, error ≈ 0.81%), PFADD is one
scatter-max (idempotent, so duplicate indexes need no dedup machinery),
PFMERGE is an elementwise max, PFCOUNT builds a device histogram finalized
on the host with the Ertl estimator (golden.ertl_estimate — bit-identical
to the NumPy twin).
"""

from __future__ import annotations

import numpy as np
import jax.numpy as jnp
from jax import lax

from redisson_tpu.ops import bitops
from redisson_tpu.ops.golden import HLL_M, HLL_P, HLL_Q


def hll_index_rank_device(c0, c1, c2):
    """Device twin of golden.hll_index_rank (uint32 lanes -> idx, rank).

    rank = 51 - bit_length(c1 ++ top18(c2)); computed with lax.clz to avoid
    64-bit emulation.  Verified equal to the golden frexp formulation in
    tests.
    """
    idx = (c0 & np.uint32(HLL_M - 1)).astype(jnp.int32)
    u18 = c2 >> np.uint32(14)
    rank = jnp.where(
        c1 != 0,
        lax.clz(c1) + np.uint32(1),
        jnp.where(
            u18 != 0,
            lax.clz(u18) - np.uint32(14) + np.uint32(33),
            np.uint32(HLL_Q + 1),
        ),
    )
    return idx, rank.astype(jnp.uint8)


def hll_add(flat_regs, rows, c0, c1, c2, valid=None):
    """PFADD batch: scatter-max of ranks via the one-hot row form (element
    scatters are pathological on TPU).  Padded ops get rank 0 — a no-op
    under max — so no scratch routing is needed."""
    idx, rank = hll_index_rank_device(c0, c1, c2)
    if valid is not None:
        rank = jnp.where(valid, rank, np.uint8(0))
    gidx = rows * np.int32(HLL_M) + idx
    return bitops.scatter_max_onehot(flat_regs, gidx, rank)


def hll_histogram(flat_regs, row):
    """Register-value histogram int32[52] of one tenant (host finalizes with
    golden.ertl_estimate — exact parity with the golden model)."""
    regs = bitops.row_slice(flat_regs, row, HLL_M)
    return jnp.zeros((HLL_Q + 2,), jnp.int32).at[regs.astype(jnp.int32)].add(1)


def hll_histograms_all(regs2d):
    """Histograms for every tenant row at once: uint8[T, M] -> int32[T, 52].
    One-hot matmul formulation — MXU-friendly for the PFCOUNT bench."""
    onehot = (
        regs2d[:, :, None] == jnp.arange(HLL_Q + 2, dtype=jnp.uint8)[None, None, :]
    )
    return onehot.sum(axis=1, dtype=jnp.int32)


def hll_merge_rows(flat_regs, dst_row, src_rows_regs):
    """PFMERGE: dst = elementwise max(dst, max over sources).

    src_rows_regs: uint8[S, M] — pre-gathered source rows (the tenancy layer
    gathers; cross-shard merge rides a psum-style max collective instead,
    see parallel/).
    """
    dst = bitops.row_slice(flat_regs, dst_row, HLL_M)
    merged = jnp.maximum(dst, src_rows_regs.max(axis=0))
    return bitops.row_update(flat_regs, dst_row, merged, HLL_M)


def hll_merge(flat_regs, dst_row, src_rows):
    """PFMERGE with in-kernel source gather: src_rows is int32[S]."""
    regs2d = flat_regs[:-1].reshape(-1, HLL_M)
    return hll_merge_rows(flat_regs, dst_row, regs2d[src_rows])


def hll_add_changed(flat_regs, rows, c0, c1, c2, valid=None):
    """Multi-tenant PFADD returning per-op 'changed' booleans with exact
    sequential semantics: op j changed its register iff
    rank_j > max(pre-batch value, ranks of earlier ops on the same
    register).  Sort by register + segmented exclusive max scan (the
    coalesced-path variant of RHyperLogLog#add's boolean)."""
    from jax import lax

    idx, rank = hll_index_rank_device(c0, c1, c2)
    if valid is not None:
        rank = jnp.where(valid, rank, np.uint8(0))
    gidx = (rows * np.int32(HLL_M) + idx).astype(jnp.uint32)
    new = bitops.scatter_max_onehot(flat_regs, gidx.astype(jnp.int32), rank)

    n = gidx.shape[0]
    pos = jnp.arange(n, dtype=jnp.int32)
    sg, sr, sp = lax.sort((gidx, rank.astype(jnp.int32), pos), num_keys=1, is_stable=True)
    pre = bitops.gather_words(flat_regs, sg).astype(jnp.int32)
    first = jnp.concatenate([jnp.ones((1,), bool), sg[1:] != sg[:-1]])
    run_prev = bitops.segmented_exclusive_max(first, sr)
    observed = jnp.maximum(pre, run_prev)
    changed_sorted = sr > observed
    changed = jnp.zeros((n,), bool).at[sp].set(changed_sorted)
    return new, changed


def hll_add_single(flat_regs, row, c0, c1, c2, valid=None):
    """PFADD for one tenant, returning (new, changed) — changed is
    RHyperLogLog.add()'s boolean: did any register increase?  Computed as a
    before/after register-sum comparison on the tenant's row (registers only
    ever grow, so sums differ iff something changed)."""
    before = bitops.row_slice(flat_regs, row, HLL_M).astype(jnp.int32).sum()
    rows = jnp.full(c0.shape, row, jnp.int32)
    new = hll_add(flat_regs, rows, c0, c1, c2, valid=valid)
    after = bitops.row_slice(new, row, HLL_M).astype(jnp.int32).sum()
    return new, after != before


def ertl_estimate_device(hist):
    """Fully-on-device Ertl estimator (float32), for the batched PFCOUNT
    bench path.  Fixed-trip-count loops (they converge geometrically well
    within 64/32 iterations at float32 precision); host path keeps the
    float64 golden finalize for count() API calls.
    """
    m = np.float32(HLL_M)
    q = HLL_Q
    hist = hist.astype(jnp.float32)

    # tau(x), x = 1 - C[q+1]/m
    x = 1.0 - hist[..., q + 1] / m

    def tau_body(_, state):
        x, y, z = state
        x = jnp.sqrt(x)
        y = 0.5 * y
        z = z - jnp.square(1.0 - x) * y
        return x, y, z

    x0 = x
    _, _, z_tau = lax.fori_loop(0, 64, tau_body, (x, jnp.float32(1.0), 1.0 - x))
    z_tau = jnp.where((x0 == 0.0) | (x0 == 1.0), 0.0, z_tau / 3.0)

    z = m * z_tau
    for kk in range(q, 0, -1):
        z = 0.5 * (z + hist[..., kk])

    # sigma(x), x = C[0]/m
    xs = hist[..., 0] / m

    def sigma_body(_, state):
        x, y, z = state
        x = x * x
        z = z + x * y
        y = y + y
        return x, y, z

    xs0 = xs
    _, _, z_sig = lax.fori_loop(0, 32, sigma_body, (xs, jnp.float32(1.0), xs))
    z_sig = jnp.where(xs0 == 1.0, jnp.float32(np.inf), z_sig)

    z = z + m * z_sig
    alpha_inf = np.float32(0.5 / np.log(2.0))
    return alpha_inf * m * m / z
