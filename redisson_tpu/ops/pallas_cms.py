"""Pallas heavy-hitter kernel (BASELINE config 5): single-tenant CMS
update+estimate with the counter table resident in VMEM.

Why Pallas here: the streaming heavy-hitter step is a scatter/gather loop
with per-op data dependence (op j's estimate must include ops < j — the
true streaming semantics).  The XLA path (ops/cms.py) vectorizes by
applying ALL updates then estimating, so same-batch duplicates see each
other's counts; this kernel walks ops IN ORDER against the VMEM-resident
table, giving exact sequential streaming estimates while the table stays
on-chip for the whole batch (one HBM round trip per launch instead of
d gathers + d scatters).

Geometry bound: the [d, w] table must fit VMEM — d*w*4 bytes ≲ 8MB, which
covers every BASELINE config-5 shape (5 × 65536 = 1.3MB).

Semantics note (tested in tests/test_pallas_cms.py): for batches with no
duplicate keys the outputs are IDENTICAL to the XLA path; for duplicates
the sequential estimates are each ≤ the batch-final XLA estimate and both
remain valid CMS upper bounds of the true counts.
"""

from __future__ import annotations

import functools

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax


def _kernel(h1_ref, h2_ref, wt_ref, state_in_ref, state_ref, out_ref, *,
            d: int, w: int):
    # state_in_ref aliases state_ref (input_output_aliases): all reads and
    # writes go through the OUTPUT ref so the table updates in place.
    del state_in_ref
    import jax.experimental.pallas as pl

    B = h1_ref.shape[1]
    w_i = jnp.int32(w)
    lanes = jnp.arange(128, dtype=jnp.int32)

    # Mosaic requires dynamic VMEM slice starts to be PROVABLY 128-aligned:
    # every dynamic access is a 128-lane block read-modify-write with a
    # one-hot lane select (q*128 is syntactically a lane multiple).  All
    # in-kernel arithmetic runs in int32 (Mosaic lacks unsigned reductions
    # and scalar bitcasts) — counters must stay < 2**31, a non-constraint
    # for CMS counts; uint32<->int32 happens as lossless VECTOR bitcasts
    # at the block boundary.
    def _i32(blk):
        return lax.bitcast_convert_type(blk, jnp.int32)

    def _u32(blk):
        return lax.bitcast_convert_type(blk, jnp.uint32)

    def _load1(ref, pos):
        q = pos >> 7
        lane = pos & 127
        blk = _i32(ref[0, pl.ds(q * 128, 128)])
        return jnp.sum(jnp.where(lanes == lane, blk, 0))

    def _rmw_add(ref, pos, delta):
        q = pos >> 7
        lane = pos & 127
        blk = _i32(ref[0, pl.ds(q * 128, 128)])
        hit = lanes == lane
        new = jnp.sum(jnp.where(hit, blk, 0)) + delta
        ref[0, pl.ds(q * 128, 128)] = _u32(jnp.where(hit, new, blk))
        return new

    def _store1(ref, pos, value):
        q = pos >> 7
        lane = pos & 127
        blk = _i32(ref[0, pl.ds(q * 128, 128)])
        ref[0, pl.ds(q * 128, 128)] = _u32(
            jnp.where(lanes == lane, value, blk)
        )

    def body(j, carry):
        h1 = _load1(h1_ref, j)
        h2 = _load1(h2_ref, j)
        wt = _load1(wt_ref, j)
        est = jnp.int32(2**31 - 1)
        idx = h1
        for r in range(d):  # static unroll over depth
            if r:
                # KM expansion idx_r = (h1 + r*h2) mod w via conditional
                # subtract (h1, h2 pre-reduced mod w, so one step per add).
                idx = idx + h2
                idx = jnp.where(idx >= w_i, idx - w_i, idx)
            cur = _rmw_add(state_ref, jnp.int32(r * w) + idx, wt)
            est = jnp.minimum(est, cur)
        _store1(out_ref, j, est)
        return carry

    lax.fori_loop(0, B, body, jnp.int32(0))


@functools.partial(jax.jit, static_argnames=("d", "w", "interpret"))
def cms_update_estimate_seq(table, h1w, h2w, weights, *, d: int, w: int,
                            interpret: bool = False):
    """(new_table, est[B]): sequential streaming update+estimate.

    Args:
      table: uint32[d, w] counter table (one tenant).
      h1w/h2w: uint32[B] pre-reduced mod w (hashing.km_reduce_mod).
      weights: uint32[B] per-op increments (0 = pure estimate op).
    """
    import jax.experimental.pallas as pl

    if (d * w) % 128 != 0:
        raise ValueError("d*w must be a multiple of 128 (VMEM lane blocks)")
    B = h1w.shape[0]
    if B == 0:  # a (1, 0) output fails Mosaic layout verification
        return table, jnp.zeros((0,), jnp.uint32)
    Bp = -(-B // 128) * 128  # pad ops to whole lane blocks; padded ops
    if Bp != B:  # carry weight 0 (the scatter-add identity)
        pad = Bp - B
        h1w = jnp.concatenate([h1w, jnp.zeros(pad, jnp.uint32)])
        h2w = jnp.concatenate([h2w, jnp.zeros(pad, jnp.uint32)])
        weights = jnp.concatenate([weights, jnp.zeros(pad, jnp.uint32)])
    kern = functools.partial(_kernel, d=d, w=w)
    new_flat, est = pl.pallas_call(
        kern,
        out_shape=(
            jax.ShapeDtypeStruct((1, d * w), jnp.uint32),
            jax.ShapeDtypeStruct((1, Bp), jnp.uint32),
        ),
        input_output_aliases={3: 0},  # table updates in place in VMEM
        interpret=interpret,
    )(h1w[None], h2w[None], weights[None], table.reshape(1, d * w))
    return new_flat.reshape(d, w), est[0, :B]


def golden_seq(table: np.ndarray, h1w, h2w, weights, *, d: int, w: int):
    """NumPy twin: the exact sequential semantics the kernel implements."""
    table = table.copy()
    est = np.zeros(len(h1w), np.uint32)
    for j in range(len(h1w)):
        vals = []
        idx = int(h1w[j])
        for r in range(d):
            if r:
                idx += int(h2w[j])
                if idx >= w:
                    idx -= w
            table[r, idx] += int(weights[j])
            vals.append(table[r, idx])
        est[j] = min(vals)
    return table, est
