"""Ambient op-deadline propagation (overload control plane, ISSUE 7).

Redis bounds a command's life with ``timeout``/``busy-reply-threshold``;
the TPU dispatch path is deeper — RESP ingress → engine submit →
coalescer segment → device dispatch → D2H fetch — and an op can rot at
any of those stages.  One absolute deadline, attached where the op
enters the system, rides the whole path:

- **RESP ingress** stamps every command with the config default
  (``op_deadline_ms``) or the connection's ``CLIENT DEADLINE`` override.
- **Direct API** callers use :func:`deadline_scope` (surfaced as
  ``client.op_deadline(ms)``).
- The **coalescer** reads the ambient deadline at submit (admission
  control + queue shedding) and the returned future honors the residual
  budget at ``.result()``.

The deadline is carried in a thread-local STACK of absolute
``time.monotonic()`` instants: nesting works (the innermost scope wins),
and pushing ``None`` explicitly disables any outer deadline (the
``CLIENT DEADLINE 0`` semantics).  No scope installed means no deadline
— the blocking, wait-forever behavior stays the default.

Deadlines here are best-effort shedding hints, not transactions: an op
shed by any stage was NEVER dispatched (no acked-write hazard), while an
op that merely missed its fetch wait may still complete on device — it
just was not acked (see failures.DeadlineExceededError.stage).
"""

from __future__ import annotations

import threading
import time
from typing import Optional

from redisson_tpu.executor.failures import DeadlineExceededError  # noqa: F401
# (re-exported: deadline consumers want the scope and the error together)

_ctl = threading.local()


def current_deadline() -> Optional[float]:
    """The innermost ambient deadline (absolute ``time.monotonic()``
    seconds) or None when the current thread has none in scope."""
    stack = getattr(_ctl, "stack", None)
    return stack[-1] if stack else None


def remaining(deadline: Optional[float],
              now: Optional[float] = None) -> Optional[float]:
    """Residual budget in seconds (may be negative); None for no
    deadline."""
    if deadline is None:
        return None
    return deadline - (time.monotonic() if now is None else now)


class deadline_scope:
    """Context manager attaching a deadline ``seconds`` from entry to
    every engine op submitted inside the block on this thread.
    ``seconds=None`` pushes an explicit no-deadline frame (shadows any
    outer scope)."""

    __slots__ = ("_seconds", "_abs")

    def __init__(self, seconds: Optional[float] = None, *,
                 at: Optional[float] = None):
        if seconds is not None and at is not None:
            raise ValueError("pass seconds or at=, not both")
        self._seconds = seconds
        self._abs = at

    def __enter__(self) -> "deadline_scope":
        stack = getattr(_ctl, "stack", None)
        if stack is None:
            stack = _ctl.stack = []
        if self._abs is not None:
            stack.append(self._abs)
        elif self._seconds is not None:
            stack.append(time.monotonic() + self._seconds)
        else:
            stack.append(None)
        return self

    def __exit__(self, *exc) -> bool:
        _ctl.stack.pop()
        return False


__all__ = [
    "DeadlineExceededError",
    "current_deadline",
    "deadline_scope",
    "remaining",
]
