"""Sharding & replication over a jax.sharding.Mesh — L4 of the build plan.

The reference's distribution axes (SURVEY.md §2.4) map here:
- cluster slot-sharding (CRC16 → 16384 slots → master entry,
  → org/redisson/cluster/ClusterConnectionManager.java) becomes **tenant
  sharding**: tenant row r lives on shard ``r % S``;
- giant single keys (2^30-bit RBitSet) shard along the bit axis
  (**m-sharding**), the analog of the reference's inability to split one
  key — we CAN, via index arithmetic + collectives;
- replication/`WAIT syncSlaves` and cross-key BITOP/PFMERGE become XLA
  collectives over ICI (psum/pmax inside shard_map) instead of
  Netty/RESP round trips.
"""

from redisson_tpu.parallel.mesh import (
    MeshContext,
    sharded_bloom_add,
    sharded_bloom_contains,
    sharded_hll_add,
    sharded_hll_histogram,
    sharded_mbit_get,
    sharded_mbit_set,
)

__all__ = [
    "MeshContext",
    "sharded_bloom_add",
    "sharded_bloom_contains",
    "sharded_hll_add",
    "sharded_hll_histogram",
    "sharded_mbit_get",
    "sharded_mbit_set",
]
