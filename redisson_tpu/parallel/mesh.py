"""shard_map kernels for multi-chip execution.

Pattern (the embedding-table classic): op batches are replicated to every
shard; each shard computes an ownership mask, routes non-owned ops to its
scratch slot, executes the same single-device kernel from ops/ on its local
pool block, and contributes masked results to a ``psum`` — one ICI
all-reduce per batch, no host round trips.  Writes need no collective at
all (each shard owns its rows).

State layout: ``[S, local_len]`` sharded along axis 0 of a 1-D mesh
(axis name "shard").  Tenant row r → shard ``r % S``, local row ``r // S``
(round-robin keeps hot tenants spread).  A giant single-tenant bitmap
shards along words instead: global word g → shard ``g // W_local``
(contiguous blocks, so range ops touch few shards).

These functions return jitted closures bound to a mesh.  They are exercised
three ways: directly by the parallel test suite, by the driver's
``dryrun_multichip`` on a virtual CPU mesh (SURVEY.md §4's "many
redis-servers on one host" analog), and from the public API through
``ShardedTpuCommandExecutor`` (executor/sharded_executor.py) when
``Config.use_tpu_sketch(num_shards=S)`` selects cluster mode.
"""

from __future__ import annotations

from functools import partial

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from redisson_tpu.ops import bitops, bloom, hll as hll_ops

# jax.shard_map graduated from jax.experimental in newer releases; the
# keyword call shape (f, mesh=, in_specs=, out_specs=) is identical in
# both homes, so bind whichever this jax provides.
if hasattr(jax, "shard_map"):
    shard_map = jax.shard_map
else:  # pre-graduation jax
    from jax.experimental.shard_map import shard_map


class MeshContext:
    """Owns the device mesh and sharding specs (the ConnectionManager-role
    object for the device 'cluster', → SURVEY.md §2.4)."""

    def __init__(self, devices=None, n_shards: int | None = None):
        if devices is None:
            devices = jax.devices()
        if n_shards is not None:
            devices = devices[:n_shards]
        self.devices = devices
        self.n_shards = len(devices)
        self.mesh = Mesh(np.array(devices), axis_names=("shard",))
        self.state_sharding = NamedSharding(self.mesh, P("shard"))
        self.replicated = NamedSharding(self.mesh, P())

    def make_state(self, local_len: int, dtype):
        """Allocate a [S, local_len] pool block-sharded over the mesh."""
        return jax.device_put(
            jnp.zeros((self.n_shards, local_len), dtype), self.state_sharding
        )


# --------------------------------------------------------------------------
# Tenant-sharded bloom
# --------------------------------------------------------------------------


def _own_and_local(rows, valid, S: int):
    my = lax.axis_index("shard")
    own = (rows % S == my)
    if valid is not None:
        own = own & valid
    return own, rows // S


def sharded_bloom_add(ctx: MeshContext, *, k: int, words_per_row: int, pack_results: bool = False):
    """Returns jitted fn(state[S,L], rows, h1m, h2m, m_arr, valid) ->
    (new_state, newly bool[B]) with exact single-device semantics.
    ``pack_results``: return newly packed 32-per-uint32 (bitops.pack_bool_u32)
    to shrink D2H bytes."""
    S = ctx.n_shards

    def inner(state, rows, h1m, h2m, m_arr, valid):
        local = state[0]
        own, local_rows = _own_and_local(rows, valid, S)
        new_local, newly = bloom.bloom_add(
            local, local_rows, h1m, h2m, m=m_arr, k=k,
            words_per_row=words_per_row, valid=own,
        )
        newly = lax.psum(jnp.where(own, newly, False).astype(jnp.int32), "shard")
        out = newly > 0
        if pack_results:
            out = bitops.pack_bool_u32(out)
        return new_local[None], out

    fn = shard_map(
        inner,
        mesh=ctx.mesh,
        in_specs=(P("shard"), P(), P(), P(), P(), P()),
        out_specs=(P("shard"), P()),
    )
    return jax.jit(fn, donate_argnums=(0,))


def sharded_bloom_contains(ctx: MeshContext, *, k: int, words_per_row: int, pack_results: bool = False):
    S = ctx.n_shards

    def inner(state, rows, h1m, h2m, m_arr, valid):
        local = state[0]
        own, local_rows = _own_and_local(rows, valid, S)
        safe_rows = jnp.where(own, local_rows, 0)
        res = bloom.bloom_contains(
            local, safe_rows, h1m, h2m, m=m_arr, k=k, words_per_row=words_per_row
        )
        res = lax.psum(jnp.where(own, res, False).astype(jnp.int32), "shard")
        out = res > 0
        if pack_results:
            out = bitops.pack_bool_u32(out)
        return out

    fn = shard_map(
        inner,
        mesh=ctx.mesh,
        in_specs=(P("shard"), P(), P(), P(), P(), P()),
        out_specs=P(),
    )
    return jax.jit(fn)


# --------------------------------------------------------------------------
# Tenant-sharded HLL
# --------------------------------------------------------------------------


def sharded_hll_add(ctx: MeshContext):
    S = ctx.n_shards

    def inner(state, rows, c0, c1, c2, valid):
        local = state[0]
        own, local_rows = _own_and_local(rows, valid, S)
        safe_rows = jnp.where(own, local_rows, 0)
        new_local = hll_ops.hll_add(local, safe_rows, c0, c1, c2, valid=own)
        return new_local[None]

    fn = shard_map(
        inner,
        mesh=ctx.mesh,
        in_specs=(P("shard"), P(), P(), P(), P(), P()),
        out_specs=P("shard"),
    )
    return jax.jit(fn, donate_argnums=(0,))


def sharded_hll_histogram(ctx: MeshContext):
    """PFCOUNT path: row lives on one shard; others contribute zeros."""
    S = ctx.n_shards

    def inner(state, row):
        local = state[0]
        my = lax.axis_index("shard")
        own = (row % S) == my
        hist = hll_ops.hll_histogram(local, jnp.where(own, row // S, 0))
        hist = lax.psum(jnp.where(own, hist, 0), "shard")
        return hist

    fn = shard_map(
        inner, mesh=ctx.mesh, in_specs=(P("shard"), P()), out_specs=P()
    )
    return jax.jit(fn)


# --------------------------------------------------------------------------
# m-sharded giant bitmap (config 3: 2^30-bit RBitSet)
# --------------------------------------------------------------------------


def sharded_mbit_set(ctx: MeshContext, *, words_local: int):
    """SETBIT batch on a bitmap sharded along words: global word g lives on
    shard g // words_local.  Returns fn(state[S, words_local+1], idx,
    valid) -> (new_state, prev bool[B])."""
    S = ctx.n_shards

    def inner(state, idx, valid):
        local = state[0]  # [words_local + 1], trailing scratch
        my = lax.axis_index("shard")
        gword = idx >> np.uint32(5)
        bit = idx & np.uint32(31)
        own = (gword // np.uint32(words_local)) == my.astype(jnp.uint32)
        if valid is not None:
            own = own & valid
        local_word = gword - my.astype(jnp.uint32) * np.uint32(words_local)
        # route_invalid_to_scratch overwrites every ~own entry itself —
        # no pre-select needed.
        local_word = bitops.route_invalid_to_scratch(
            local_word, own, words_local + 1
        )
        new_local, prev = bitops.scatter_set_bits(local, local_word, bit)
        prev = lax.psum(jnp.where(own, prev, 0).astype(jnp.int32), "shard")
        return new_local[None], prev > 0

    fn = shard_map(
        inner,
        mesh=ctx.mesh,
        in_specs=(P("shard"), P(), P()),
        out_specs=(P("shard"), P()),
    )
    return jax.jit(fn, donate_argnums=(0,))


def sharded_mbit_get(ctx: MeshContext, *, words_local: int):
    S = ctx.n_shards

    def inner(state, idx):
        local = state[0]
        my = lax.axis_index("shard")
        gword = idx >> np.uint32(5)
        bit = idx & np.uint32(31)
        own = (gword // np.uint32(words_local)) == my.astype(jnp.uint32)
        local_word = jnp.where(
            own, gword - my.astype(jnp.uint32) * np.uint32(words_local), 0
        )
        res = bitops.gather_bits(local, local_word, bit)
        res = lax.psum(jnp.where(own, res, 0).astype(jnp.int32), "shard")
        return res > 0

    fn = shard_map(
        inner, mesh=ctx.mesh, in_specs=(P("shard"), P()), out_specs=P()
    )
    return jax.jit(fn)


# --------------------------------------------------------------------------
# Partition-by-owner kernels (round 3): the host splits each batch by owner
# shard (row % S) into [S, Bp] op blocks — the slot-routing role of
# CommandBatchService#executeAsync grouping commands per MasterSlaveEntry
# (SURVEY.md §3.2).  in_specs=P("shard") hands every shard ONLY its ops, so
# total device work is B (not S×B as under replicate-and-mask), writes stay
# shard-local, and per-op results come back [S, Bp] with NO collective at
# all.  Collectives remain only where data genuinely crosses shards
# (BITOP/PFMERGE/m-sharded bitmaps below).
# --------------------------------------------------------------------------


def _psharded(ctx: MeshContext, inner, n_op_args: int, *, out_state: bool, donate: bool = True):
    """shard_map wrapper for partitioned op batches: ``inner(local_state,
    *op_cols)`` sees one shard's [Bp]-shaped columns and returns
    (new_local, res[Bp-packed]) or just res."""

    def wrapped(state, *ops):
        local = state[0]
        cols = [o[0] for o in ops]
        return inner(local, *cols)

    out_specs = (P("shard"), P("shard")) if out_state else P("shard")
    fn = shard_map(
        wrapped,
        mesh=ctx.mesh,
        in_specs=(P("shard"),) + (P("shard"),) * n_op_args,
        out_specs=out_specs,
    )
    return jax.jit(fn, donate_argnums=(0,) if (out_state and donate) else ())


def psharded_bloom_mixed(ctx: MeshContext, *, k: int, words_per_row: int):
    """fn(state, lrows, h1m, h2m, m, is_add, valid) -> (new_state,
    packed[S, Bp/32]); every column [S, Bp], rows already shard-local."""

    def inner(local, lrows, h1m, h2m, m_arr, is_add, valid):
        new_local, res = bloom.bloom_mixed(
            local, lrows, h1m, h2m, is_add,
            m=m_arr, k=k, words_per_row=words_per_row, valid=valid,
        )
        return new_local[None], bitops.pack_bool_u32(res)[None]

    return _psharded(ctx, inner, 6, out_state=True)


def psharded_bloom_mixed_keys(ctx: MeshContext, *, k: int, words_per_row: int, target_lanes: int):
    """Device-hash variant: raw codec lanes [S, Bp, L] hash in-kernel (the
    round-2 sharded mode shipped 16-byte host hashes — the fast path now
    works sharded too)."""
    from redisson_tpu.ops import fastpath

    def inner(local, lrows, blocks, lengths, m_arr, is_add, valid):
        new_local, res = fastpath.bloom_mixed_keys(
            local, lrows, blocks, lengths, m_arr, is_add, valid,
            k=k, words_per_row=words_per_row, target_lanes=target_lanes,
        )
        return new_local[None], bitops.pack_bool_u32(res)[None]

    return _psharded(ctx, inner, 6, out_state=True)


def psharded_bitset_mixed(ctx: MeshContext, *, words_per_row: int):
    from redisson_tpu.ops import bitset as bitset_ops

    def inner(local, lrows, idx, opcodes, valid):
        new_local, obs = bitset_ops.bitset_mixed(
            local, lrows, idx, opcodes, words_per_row=words_per_row, valid=valid
        )
        return new_local[None], bitops.pack_bool_u32(obs)[None]

    return _psharded(ctx, inner, 4, out_state=True)


def psharded_bitset_rw(ctx: MeshContext, kernel, *, words_per_row: int):
    def inner(local, lrows, idx, valid):
        new_local, prev = kernel(
            local, lrows, idx, words_per_row=words_per_row, valid=valid
        )
        return new_local[None], bitops.pack_bool_u32(prev)[None]

    return _psharded(ctx, inner, 3, out_state=True)


def psharded_bitset_get(ctx: MeshContext, *, words_per_row: int):
    from redisson_tpu.ops import bitset as bitset_ops

    def inner(local, lrows, idx, valid):
        res = bitset_ops.bitset_get(
            local, jnp.where(valid, lrows, 0), idx, words_per_row=words_per_row
        )
        return bitops.pack_bool_u32(res & valid)[None]

    return _psharded(ctx, inner, 3, out_state=False)


def psharded_hll_add_changed(ctx: MeshContext):
    def inner(local, lrows, c0, c1, c2, valid):
        new_local, changed = hll_ops.hll_add_changed(
            local, jnp.where(valid, lrows, 0), c0, c1, c2, valid=valid
        )
        return new_local[None], bitops.pack_bool_u32(changed)[None]

    return _psharded(ctx, inner, 5, out_state=True)


def psharded_hll_add_keys(ctx: MeshContext, *, target_lanes: int):
    """Device-hash PFADD: murmur in-kernel, then scatter-max with changed
    flags."""
    from redisson_tpu.ops import fastpath
    from redisson_tpu.utils import hashing

    def inner(local, lrows, blocks, lengths, valid):
        c0, c1, c2, _ = hashing.murmur3_x86_128(
            fastpath.pad_lanes(blocks, target_lanes), lengths, xp=jnp
        )
        new_local, changed = hll_ops.hll_add_changed(
            local, jnp.where(valid, lrows, 0), c0, c1, c2, valid=valid
        )
        return new_local[None], bitops.pack_bool_u32(changed)[None]

    return _psharded(ctx, inner, 4, out_state=True)


def psharded_cms_update_estimate(ctx: MeshContext, *, d: int, w: int, cells_per_row: int, estimate_only: bool = False, update_only: bool = False):
    from redisson_tpu.ops import cms as cms_ops

    def inner(local, lrows, h1w, h2w, weights, valid):
        safe_rows = jnp.where(valid, lrows, 0)
        if estimate_only:
            new_local = local
        else:
            wts = jnp.where(valid, weights, 0)
            new_local = cms_ops.cms_update(
                local, safe_rows, h1w, h2w, wts, d=d, w=w, cells_per_row=cells_per_row
            )
        if update_only:
            return new_local[None]
        est = cms_ops.cms_estimate(
            new_local, safe_rows, h1w, h2w, d=d, w=w, cells_per_row=cells_per_row
        )
        est = jnp.where(valid, est, 0)
        if estimate_only:
            return est[None]
        return new_local[None], est[None]

    if estimate_only:
        return _psharded(ctx, inner, 5, out_state=False)
    if update_only:
        def wrapped(state, *ops):
            return inner(state[0], *[o[0] for o in ops])
        fn = shard_map(
            wrapped,
            mesh=ctx.mesh,
            in_specs=(P("shard"),) * 6,
            out_specs=P("shard"),
        )
        return jax.jit(fn, donate_argnums=(0,))
    return _psharded(ctx, inner, 5, out_state=True)


# --------------------------------------------------------------------------
# m-sharded multi-tenant bitset pools (config 3, SURVEY.md §7-L4): rows at
# or above Config.mbit_threshold_words split their WORDS contiguously
# across shards — global word g of row r lives on shard g // W_local at
# local row r.  Batch ops partition by word-shard host-side and reuse the
# psharded_* kernels with local coordinates; the builders below cover the
# whole-row ops (scalar reduces, range writes, BITOP), which are
# embarrassingly shard-local — per-shard partial results return [S] to the
# host for combination, no collective at all.
# --------------------------------------------------------------------------


def msharded_row_map(ctx: MeshContext, fn_local):
    """Each shard computes ``fn_local(local_state, row)`` over its word
    slice of the row; results come back stacked [S, ...] for host-side
    combination (sum for popcount, offset-max for length, …)."""

    def inner(state, row):
        v = jnp.asarray(fn_local(state[0], row))
        return v[None]

    fn = shard_map(
        inner, mesh=ctx.mesh, in_specs=(P("shard"), P()), out_specs=P("shard")
    )
    return jax.jit(fn)


def msharded_row_write(ctx: MeshContext, *, words_local: int):
    """Overwrite one row: data arrives pre-split [S, W_local]."""

    def inner(state, row, data):
        local = state[0]
        return bitops.row_update(local, row, data[0], words_local)[None]

    fn = shard_map(
        inner,
        mesh=ctx.mesh,
        in_specs=(P("shard"), P(), P("shard")),
        out_specs=P("shard"),
    )
    return jax.jit(fn, donate_argnums=(0,))


def msharded_set_range(ctx: MeshContext, *, words_local: int, value: bool):
    """Range set/clear: the host clips the global [from, to) to each
    shard's word window; every shard applies its local mask."""

    def inner(state, row, fb, tb):
        local = state[0]
        mask = bitops.range_mask_words(words_local, fb[0], tb[0])
        cur = bitops.row_slice(local, row, words_local)
        new_row = (cur | mask) if value else (cur & ~mask)
        return bitops.row_update(local, row, new_row, words_local)[None]

    fn = shard_map(
        inner,
        mesh=ctx.mesh,
        in_specs=(P("shard"), P(), P("shard"), P("shard")),
        out_specs=P("shard"),
    )
    return jax.jit(fn, donate_argnums=(0,))


def msharded_bitop(ctx: MeshContext, *, words_local: int, op: str, n_src: int, masked: bool = False):
    """BITOP on m-sharded rows: every operand's words for this shard are
    local, so each shard computes its slice independently — no collective
    (contrast sharded_bitop above, where whole rows live on one shard).
    ``limit`` arrives per-shard (the NOT mask clipped to the local window).
    """
    from redisson_tpu.ops import bitset as bitset_ops

    def inner(state, dst_row, src_rows, limit):
        local = state[0]
        return bitset_ops.bitset_bitop_rows(
            local, dst_row, src_rows, words_per_row=words_local, op=op,
            n_src=n_src, limit_bits=limit[0] if masked else None,
        )[None]

    fn = shard_map(
        inner,
        mesh=ctx.mesh,
        in_specs=(P("shard"), P(), P(), P("shard")),
        out_specs=P("shard"),
    )
    return jax.jit(fn, donate_argnums=(0,))


# --------------------------------------------------------------------------
# Cross-shard collectives: PFMERGE / BITOP between rows on different shards
# --------------------------------------------------------------------------


def sharded_hll_merge(ctx: MeshContext):
    """dst_row ← max(dst_row, src rows), rows anywhere on the mesh.  Each
    shard broadcasts its owned source rows via psum(max is monotone: zeros
    elsewhere), then only the dst owner writes."""
    S = ctx.n_shards

    def inner(state, dst_row, src_rows):
        from redisson_tpu.ops.golden import HLL_M

        local = state[0]
        my = lax.axis_index("shard")
        regs2d = local[:-1].reshape(-1, HLL_M)
        own_src = (src_rows % S) == my
        contrib = jnp.where(
            own_src[:, None], regs2d[jnp.where(own_src, src_rows // S, 0)], 0
        )
        # pmax, not psum: registers owned by different shards must combine
        # by max (zeros from non-owners are the identity for max too).
        merged_src = lax.pmax(contrib.max(axis=0).astype(jnp.int32), "shard")
        own_dst = (dst_row % S) == my
        dst_local = jnp.where(own_dst, dst_row // S, 0)
        cur = bitops.row_slice(local, dst_local, HLL_M)
        new_row = jnp.maximum(cur, merged_src.astype(jnp.uint8))
        new_row = jnp.where(own_dst, new_row, cur)
        new_local = bitops.row_update(local, dst_local, new_row, HLL_M)
        return new_local[None]

    fn = shard_map(
        inner, mesh=ctx.mesh, in_specs=(P("shard"), P(), P()), out_specs=P("shard")
    )
    return jax.jit(fn, donate_argnums=(0,))


def sharded_bitop(ctx: MeshContext, *, words_per_row: int, op: str, n_src: int, masked: bool = False):
    """BITOP across shards: operand rows are broadcast via psum (each shard
    contributes rows it owns, zeros otherwise), every shard computes the op,
    only the dst owner writes the result.  ``masked`` (NOT path): the
    complement is ANDed with a [0, limit_bits) mask — the byte-aligned
    logical-length semantics of engines.bitset_bitop."""
    S = ctx.n_shards

    def inner(state, dst_row, src_rows, limit):
        local = state[0]
        my = lax.axis_index("shard")
        rows2d = local[:-1].reshape(-1, words_per_row)
        own_src = (src_rows % S) == my
        gathered = jnp.where(
            own_src[:, None], rows2d[jnp.where(own_src, src_rows // S, 0)], 0
        )
        full = lax.psum(gathered, "shard")  # [n_src, W] now complete rows
        if op == "and":
            res = full[0]
            for i in range(1, n_src):
                res = res & full[i]
        elif op == "or":
            res = full[0]
            for i in range(1, n_src):
                res = res | full[i]
        elif op == "xor":
            res = full[0]
            for i in range(1, n_src):
                res = res ^ full[i]
        elif op == "not":
            res = ~full[0]
            if masked:
                res = res & bitops.range_mask_words(words_per_row, 0, limit)
        else:
            raise ValueError(op)
        own_dst = (dst_row % S) == my
        dst_local = jnp.where(own_dst, dst_row // S, 0)
        cur = bitops.row_slice(local, dst_local, words_per_row)
        new_row = jnp.where(own_dst, res, cur)
        new_local = bitops.row_update(local, dst_local, new_row, words_per_row)
        return new_local[None]

    fn = shard_map(
        inner,
        mesh=ctx.mesh,
        in_specs=(P("shard"), P(), P(), P()),
        out_specs=P("shard"),
    )
    return jax.jit(fn, donate_argnums=(0,))


# --------------------------------------------------------------------------
# Builders for the sharded executor (executor/sharded_executor.py): the
# remaining op surface — bitset single-bit batches, row scalars/reads/
# writes, CMS, HLL changed-flags — in the same ownership-mask pattern.
# --------------------------------------------------------------------------


def sharded_bitset_set_range(ctx: MeshContext, *, words_per_row: int, value: bool):
    S = ctx.n_shards

    def inner(state, row, from_bit, to_bit):
        local = state[0]
        my = lax.axis_index("shard")
        own = (row % S) == my
        lrow = row // S
        mask = bitops.range_mask_words(words_per_row, from_bit, to_bit)
        cur = bitops.row_slice(local, lrow, words_per_row)
        new_row = (cur | mask) if value else (cur & ~mask)
        new_row = jnp.where(own, new_row, cur)
        return bitops.row_update(local, lrow, new_row, words_per_row)[None]

    fn = shard_map(
        inner,
        mesh=ctx.mesh,
        in_specs=(P("shard"), P(), P(), P()),
        out_specs=P("shard"),
    )
    return jax.jit(fn, donate_argnums=(0,))


def sharded_row_reduce(ctx: MeshContext, fn_local):
    """Owner-computes-scalar pattern: ``fn_local(local_state, local_row)``
    runs on the owning shard; everyone else contributes zeros to the psum.
    Serves BITCOUNT/length/bitpos/popcount/histogram (vector results psum
    elementwise the same way)."""
    S = ctx.n_shards

    def inner(state, row):
        local = state[0]
        my = lax.axis_index("shard")
        own = (row % S) == my
        v = fn_local(local, row // S)
        return lax.psum(jnp.where(own, v, 0), "shard")

    fn = shard_map(
        inner, mesh=ctx.mesh, in_specs=(P("shard"), P()), out_specs=P()
    )
    return jax.jit(fn)


def sharded_row_read(ctx: MeshContext, *, row_units: int):
    """Fetch one tenant row to every shard (psum broadcast from the owner)."""

    S = ctx.n_shards

    def inner(state, row):
        local = state[0]
        my = lax.axis_index("shard")
        own = (row % S) == my
        v = bitops.row_slice(local, row // S, row_units)
        # Only the owner contributes non-zeros, so a native-dtype psum is an
        # exact broadcast (no overflow possible).
        return lax.psum(jnp.where(own, v, jnp.zeros_like(v)), "shard")

    fn = shard_map(
        inner, mesh=ctx.mesh, in_specs=(P("shard"), P()), out_specs=P()
    )
    return jax.jit(fn)


def sharded_row_write(ctx: MeshContext, *, row_units: int):
    """Overwrite one tenant row (only the owner applies the update)."""
    S = ctx.n_shards

    def inner(state, row, data):
        local = state[0]
        my = lax.axis_index("shard")
        own = (row % S) == my
        lrow = row // S
        cur = bitops.row_slice(local, lrow, row_units)
        new_row = jnp.where(own, data, cur)
        return bitops.row_update(local, lrow, new_row, row_units)[None]

    fn = shard_map(
        inner,
        mesh=ctx.mesh,
        in_specs=(P("shard"), P(), P()),
        out_specs=P("shard"),
    )
    return jax.jit(fn, donate_argnums=(0,))


def sharded_cms_merge(ctx: MeshContext, *, cells_per_row: int):
    """CMS merge: sources broadcast via psum gather, dst owner adds the sum
    (CMS is linear)."""
    S = ctx.n_shards

    def inner(state, dst_row, src_rows):
        local = state[0]
        my = lax.axis_index("shard")
        rows2d = local[:-1].reshape(-1, cells_per_row)
        own_src = (src_rows % S) == my
        gathered = jnp.where(
            own_src[:, None], rows2d[jnp.where(own_src, src_rows // S, 0)], 0
        )
        full = lax.psum(gathered, "shard")
        summed = full.sum(axis=0, dtype=jnp.uint32)
        own_dst = (dst_row % S) == my
        dst_local = jnp.where(own_dst, dst_row // S, 0)
        cur = bitops.row_slice(local, dst_local, cells_per_row)
        new_row = jnp.where(own_dst, cur + summed, cur)
        return bitops.row_update(local, dst_local, new_row, cells_per_row)[None]

    fn = shard_map(
        inner, mesh=ctx.mesh, in_specs=(P("shard"), P(), P()), out_specs=P("shard")
    )
    return jax.jit(fn, donate_argnums=(0,))
