"""shard_map kernels for multi-chip execution.

Pattern (the embedding-table classic): op batches are replicated to every
shard; each shard computes an ownership mask, routes non-owned ops to its
scratch slot, executes the same single-device kernel from ops/ on its local
pool block, and contributes masked results to a ``psum`` — one ICI
all-reduce per batch, no host round trips.  Writes need no collective at
all (each shard owns its rows).

State layout: ``[S, local_len]`` sharded along axis 0 of a 1-D mesh
(axis name "shard").  Tenant row r → shard ``r % S``, local row ``r // S``
(round-robin keeps hot tenants spread).  A giant single-tenant bitmap
shards along words instead: global word g → shard ``g // W_local``
(contiguous blocks, so range ops touch few shards).

These functions return jitted closures bound to a mesh.  They are exercised
three ways: directly by the parallel test suite, by the driver's
``dryrun_multichip`` on a virtual CPU mesh (SURVEY.md §4's "many
redis-servers on one host" analog), and from the public API through
``ShardedTpuCommandExecutor`` (executor/sharded_executor.py) when
``Config.use_tpu_sketch(num_shards=S)`` selects cluster mode.
"""

from __future__ import annotations

from functools import partial

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from redisson_tpu.ops import bitops, bloom, hll as hll_ops


class MeshContext:
    """Owns the device mesh and sharding specs (the ConnectionManager-role
    object for the device 'cluster', → SURVEY.md §2.4)."""

    def __init__(self, devices=None, n_shards: int | None = None):
        if devices is None:
            devices = jax.devices()
        if n_shards is not None:
            devices = devices[:n_shards]
        self.devices = devices
        self.n_shards = len(devices)
        self.mesh = Mesh(np.array(devices), axis_names=("shard",))
        self.state_sharding = NamedSharding(self.mesh, P("shard"))
        self.replicated = NamedSharding(self.mesh, P())

    def make_state(self, local_len: int, dtype):
        """Allocate a [S, local_len] pool block-sharded over the mesh."""
        return jax.device_put(
            jnp.zeros((self.n_shards, local_len), dtype), self.state_sharding
        )


# --------------------------------------------------------------------------
# Tenant-sharded bloom
# --------------------------------------------------------------------------


def _own_and_local(rows, valid, S: int):
    my = lax.axis_index("shard")
    own = (rows % S == my)
    if valid is not None:
        own = own & valid
    return own, rows // S


def sharded_bloom_add(ctx: MeshContext, *, k: int, words_per_row: int, pack_results: bool = False):
    """Returns jitted fn(state[S,L], rows, h1m, h2m, m_arr, valid) ->
    (new_state, newly bool[B]) with exact single-device semantics.
    ``pack_results``: return newly packed 32-per-uint32 (bitops.pack_bool_u32)
    to shrink D2H bytes."""
    S = ctx.n_shards

    def inner(state, rows, h1m, h2m, m_arr, valid):
        local = state[0]
        own, local_rows = _own_and_local(rows, valid, S)
        new_local, newly = bloom.bloom_add(
            local, local_rows, h1m, h2m, m=m_arr, k=k,
            words_per_row=words_per_row, valid=own,
        )
        newly = lax.psum(jnp.where(own, newly, False).astype(jnp.int32), "shard")
        out = newly > 0
        if pack_results:
            out = bitops.pack_bool_u32(out)
        return new_local[None], out

    fn = jax.shard_map(
        inner,
        mesh=ctx.mesh,
        in_specs=(P("shard"), P(), P(), P(), P(), P()),
        out_specs=(P("shard"), P()),
    )
    return jax.jit(fn, donate_argnums=(0,))


def sharded_bloom_contains(ctx: MeshContext, *, k: int, words_per_row: int, pack_results: bool = False):
    S = ctx.n_shards

    def inner(state, rows, h1m, h2m, m_arr, valid):
        local = state[0]
        own, local_rows = _own_and_local(rows, valid, S)
        safe_rows = jnp.where(own, local_rows, 0)
        res = bloom.bloom_contains(
            local, safe_rows, h1m, h2m, m=m_arr, k=k, words_per_row=words_per_row
        )
        res = lax.psum(jnp.where(own, res, False).astype(jnp.int32), "shard")
        out = res > 0
        if pack_results:
            out = bitops.pack_bool_u32(out)
        return out

    fn = jax.shard_map(
        inner,
        mesh=ctx.mesh,
        in_specs=(P("shard"), P(), P(), P(), P(), P()),
        out_specs=P(),
    )
    return jax.jit(fn)


def sharded_bloom_mixed(ctx: MeshContext, *, k: int, words_per_row: int, pack_results: bool = False):
    """Combined add+contains (ops/bloom.bloom_mixed) under the ownership-
    mask pattern: non-owned ops route to the shard's scratch word and are
    masked out of the psum."""
    S = ctx.n_shards

    def inner(state, rows, h1m, h2m, m_arr, is_add, valid):
        local = state[0]
        own, local_rows = _own_and_local(rows, valid, S)
        new_local, res = bloom.bloom_mixed(
            local, local_rows, h1m, h2m, is_add,
            m=m_arr, k=k, words_per_row=words_per_row, valid=own,
        )
        res = lax.psum(jnp.where(own, res, False).astype(jnp.int32), "shard")
        out = res > 0
        if pack_results:
            out = bitops.pack_bool_u32(out)
        return new_local[None], out

    fn = jax.shard_map(
        inner,
        mesh=ctx.mesh,
        in_specs=(P("shard"), P(), P(), P(), P(), P(), P()),
        out_specs=(P("shard"), P()),
    )
    return jax.jit(fn, donate_argnums=(0,))


def sharded_bitset_mixed(ctx: MeshContext, *, words_per_row: int, pack_results: bool = False):
    """Unified set/clear/flip/get batch (ops/bitset.bitset_mixed), masked."""
    from redisson_tpu.ops import bitset as bitset_ops

    S = ctx.n_shards

    def inner(state, rows, idx, opcodes, valid):
        local = state[0]
        own, lrows = _own_and_local(rows, valid, S)
        new_local, obs = bitset_ops.bitset_mixed(
            local, lrows, idx, opcodes, words_per_row=words_per_row, valid=own
        )
        obs = lax.psum(jnp.where(own, obs, False).astype(jnp.int32), "shard")
        out = obs > 0
        if pack_results:
            out = bitops.pack_bool_u32(out)
        return new_local[None], out

    fn = jax.shard_map(
        inner,
        mesh=ctx.mesh,
        in_specs=(P("shard"), P(), P(), P(), P()),
        out_specs=(P("shard"), P()),
    )
    return jax.jit(fn, donate_argnums=(0,))


# --------------------------------------------------------------------------
# Tenant-sharded HLL
# --------------------------------------------------------------------------


def sharded_hll_add(ctx: MeshContext):
    S = ctx.n_shards

    def inner(state, rows, c0, c1, c2, valid):
        local = state[0]
        own, local_rows = _own_and_local(rows, valid, S)
        safe_rows = jnp.where(own, local_rows, 0)
        new_local = hll_ops.hll_add(local, safe_rows, c0, c1, c2, valid=own)
        return new_local[None]

    fn = jax.shard_map(
        inner,
        mesh=ctx.mesh,
        in_specs=(P("shard"), P(), P(), P(), P(), P()),
        out_specs=P("shard"),
    )
    return jax.jit(fn, donate_argnums=(0,))


def sharded_hll_histogram(ctx: MeshContext):
    """PFCOUNT path: row lives on one shard; others contribute zeros."""
    S = ctx.n_shards

    def inner(state, row):
        local = state[0]
        my = lax.axis_index("shard")
        own = (row % S) == my
        hist = hll_ops.hll_histogram(local, jnp.where(own, row // S, 0))
        hist = lax.psum(jnp.where(own, hist, 0), "shard")
        return hist

    fn = jax.shard_map(
        inner, mesh=ctx.mesh, in_specs=(P("shard"), P()), out_specs=P()
    )
    return jax.jit(fn)


# --------------------------------------------------------------------------
# m-sharded giant bitmap (config 3: 2^30-bit RBitSet)
# --------------------------------------------------------------------------


def sharded_mbit_set(ctx: MeshContext, *, words_local: int):
    """SETBIT batch on a bitmap sharded along words: global word g lives on
    shard g // words_local.  Returns fn(state[S, words_local+1], idx,
    valid) -> (new_state, prev bool[B])."""
    S = ctx.n_shards

    def inner(state, idx, valid):
        local = state[0]  # [words_local + 1], trailing scratch
        my = lax.axis_index("shard")
        gword = idx >> np.uint32(5)
        bit = idx & np.uint32(31)
        own = (gword // np.uint32(words_local)) == my.astype(jnp.uint32)
        if valid is not None:
            own = own & valid
        local_word = gword - my.astype(jnp.uint32) * np.uint32(words_local)
        local_word = bitops.route_invalid_to_scratch(
            jnp.where(own, local_word, 0), own, words_local + 1
        )
        new_local, prev = bitops.scatter_set_bits(local, local_word, bit)
        prev = lax.psum(jnp.where(own, prev, 0).astype(jnp.int32), "shard")
        return new_local[None], prev > 0

    fn = jax.shard_map(
        inner,
        mesh=ctx.mesh,
        in_specs=(P("shard"), P(), P()),
        out_specs=(P("shard"), P()),
    )
    return jax.jit(fn, donate_argnums=(0,))


def sharded_mbit_get(ctx: MeshContext, *, words_local: int):
    S = ctx.n_shards

    def inner(state, idx):
        local = state[0]
        my = lax.axis_index("shard")
        gword = idx >> np.uint32(5)
        bit = idx & np.uint32(31)
        own = (gword // np.uint32(words_local)) == my.astype(jnp.uint32)
        local_word = jnp.where(
            own, gword - my.astype(jnp.uint32) * np.uint32(words_local), 0
        )
        res = bitops.gather_bits(local, local_word, bit)
        res = lax.psum(jnp.where(own, res, 0).astype(jnp.int32), "shard")
        return res > 0

    fn = jax.shard_map(
        inner, mesh=ctx.mesh, in_specs=(P("shard"), P()), out_specs=P()
    )
    return jax.jit(fn)


# --------------------------------------------------------------------------
# Cross-shard collectives: PFMERGE / BITOP between rows on different shards
# --------------------------------------------------------------------------


def sharded_hll_merge(ctx: MeshContext):
    """dst_row ← max(dst_row, src rows), rows anywhere on the mesh.  Each
    shard broadcasts its owned source rows via psum(max is monotone: zeros
    elsewhere), then only the dst owner writes."""
    S = ctx.n_shards

    def inner(state, dst_row, src_rows):
        from redisson_tpu.ops.golden import HLL_M

        local = state[0]
        my = lax.axis_index("shard")
        regs2d = local[:-1].reshape(-1, HLL_M)
        own_src = (src_rows % S) == my
        contrib = jnp.where(
            own_src[:, None], regs2d[jnp.where(own_src, src_rows // S, 0)], 0
        )
        # pmax, not psum: registers owned by different shards must combine
        # by max (zeros from non-owners are the identity for max too).
        merged_src = lax.pmax(contrib.max(axis=0).astype(jnp.int32), "shard")
        own_dst = (dst_row % S) == my
        dst_local = jnp.where(own_dst, dst_row // S, 0)
        cur = bitops.row_slice(local, dst_local, HLL_M)
        new_row = jnp.maximum(cur, merged_src.astype(jnp.uint8))
        new_row = jnp.where(own_dst, new_row, cur)
        new_local = bitops.row_update(local, dst_local, new_row, HLL_M)
        return new_local[None]

    fn = jax.shard_map(
        inner, mesh=ctx.mesh, in_specs=(P("shard"), P(), P()), out_specs=P("shard")
    )
    return jax.jit(fn, donate_argnums=(0,))


def sharded_bitop(ctx: MeshContext, *, words_per_row: int, op: str, n_src: int, masked: bool = False):
    """BITOP across shards: operand rows are broadcast via psum (each shard
    contributes rows it owns, zeros otherwise), every shard computes the op,
    only the dst owner writes the result.  ``masked`` (NOT path): the
    complement is ANDed with a [0, limit_bits) mask — the byte-aligned
    logical-length semantics of engines.bitset_bitop."""
    S = ctx.n_shards

    def inner(state, dst_row, src_rows, limit):
        local = state[0]
        my = lax.axis_index("shard")
        rows2d = local[:-1].reshape(-1, words_per_row)
        own_src = (src_rows % S) == my
        gathered = jnp.where(
            own_src[:, None], rows2d[jnp.where(own_src, src_rows // S, 0)], 0
        )
        full = lax.psum(gathered, "shard")  # [n_src, W] now complete rows
        if op == "and":
            res = full[0]
            for i in range(1, n_src):
                res = res & full[i]
        elif op == "or":
            res = full[0]
            for i in range(1, n_src):
                res = res | full[i]
        elif op == "xor":
            res = full[0]
            for i in range(1, n_src):
                res = res ^ full[i]
        elif op == "not":
            res = ~full[0]
            if masked:
                res = res & bitops.range_mask_words(words_per_row, 0, limit)
        else:
            raise ValueError(op)
        own_dst = (dst_row % S) == my
        dst_local = jnp.where(own_dst, dst_row // S, 0)
        cur = bitops.row_slice(local, dst_local, words_per_row)
        new_row = jnp.where(own_dst, res, cur)
        new_local = bitops.row_update(local, dst_local, new_row, words_per_row)
        return new_local[None]

    fn = jax.shard_map(
        inner,
        mesh=ctx.mesh,
        in_specs=(P("shard"), P(), P(), P()),
        out_specs=P("shard"),
    )
    return jax.jit(fn, donate_argnums=(0,))


# --------------------------------------------------------------------------
# Builders for the sharded executor (executor/sharded_executor.py): the
# remaining op surface — bitset single-bit batches, row scalars/reads/
# writes, CMS, HLL changed-flags — in the same ownership-mask pattern.
# --------------------------------------------------------------------------


def sharded_bitset_rw(ctx: MeshContext, kernel, *, words_per_row: int, pack_results: bool = False):
    """SETBIT/clear/flip batch: ``kernel`` is one of ops.bitset.bitset_set/
    bitset_clear/bitset_flip.  Returns fn(state, rows, idx, valid) ->
    (new_state, prev bool[B]) with exact single-device semantics."""
    S = ctx.n_shards

    def inner(state, rows, idx, valid):
        local = state[0]
        own, lrows = _own_and_local(rows, valid, S)
        new_local, prev = kernel(
            local, lrows, idx, words_per_row=words_per_row, valid=own
        )
        prev = lax.psum(jnp.where(own, prev, False).astype(jnp.int32), "shard")
        out = prev > 0
        if pack_results:
            out = bitops.pack_bool_u32(out)
        return new_local[None], out

    fn = jax.shard_map(
        inner,
        mesh=ctx.mesh,
        in_specs=(P("shard"), P(), P(), P()),
        out_specs=(P("shard"), P()),
    )
    return jax.jit(fn, donate_argnums=(0,))


def sharded_bitset_get(ctx: MeshContext, *, words_per_row: int, pack_results: bool = False):
    from redisson_tpu.ops import bitset as bitset_ops

    S = ctx.n_shards

    def inner(state, rows, idx, valid):
        local = state[0]
        own, lrows = _own_and_local(rows, valid, S)
        res = bitset_ops.bitset_get(local, lrows, idx, words_per_row=words_per_row)
        res = lax.psum(jnp.where(own, res, False).astype(jnp.int32), "shard")
        out = res > 0
        if pack_results:
            out = bitops.pack_bool_u32(out)
        return out

    fn = jax.shard_map(
        inner,
        mesh=ctx.mesh,
        in_specs=(P("shard"), P(), P(), P()),
        out_specs=P(),
    )
    return jax.jit(fn)


def sharded_bitset_set_range(ctx: MeshContext, *, words_per_row: int, value: bool):
    S = ctx.n_shards

    def inner(state, row, from_bit, to_bit):
        local = state[0]
        my = lax.axis_index("shard")
        own = (row % S) == my
        lrow = row // S
        mask = bitops.range_mask_words(words_per_row, from_bit, to_bit)
        cur = bitops.row_slice(local, lrow, words_per_row)
        new_row = (cur | mask) if value else (cur & ~mask)
        new_row = jnp.where(own, new_row, cur)
        return bitops.row_update(local, lrow, new_row, words_per_row)[None]

    fn = jax.shard_map(
        inner,
        mesh=ctx.mesh,
        in_specs=(P("shard"), P(), P(), P()),
        out_specs=P("shard"),
    )
    return jax.jit(fn, donate_argnums=(0,))


def sharded_row_reduce(ctx: MeshContext, fn_local):
    """Owner-computes-scalar pattern: ``fn_local(local_state, local_row)``
    runs on the owning shard; everyone else contributes zeros to the psum.
    Serves BITCOUNT/length/bitpos/popcount/histogram (vector results psum
    elementwise the same way)."""
    S = ctx.n_shards

    def inner(state, row):
        local = state[0]
        my = lax.axis_index("shard")
        own = (row % S) == my
        v = fn_local(local, row // S)
        return lax.psum(jnp.where(own, v, 0), "shard")

    fn = jax.shard_map(
        inner, mesh=ctx.mesh, in_specs=(P("shard"), P()), out_specs=P()
    )
    return jax.jit(fn)


def sharded_row_read(ctx: MeshContext, *, row_units: int):
    """Fetch one tenant row to every shard (psum broadcast from the owner)."""

    S = ctx.n_shards

    def inner(state, row):
        local = state[0]
        my = lax.axis_index("shard")
        own = (row % S) == my
        v = bitops.row_slice(local, row // S, row_units)
        # Only the owner contributes non-zeros, so a native-dtype psum is an
        # exact broadcast (no overflow possible).
        return lax.psum(jnp.where(own, v, jnp.zeros_like(v)), "shard")

    fn = jax.shard_map(
        inner, mesh=ctx.mesh, in_specs=(P("shard"), P()), out_specs=P()
    )
    return jax.jit(fn)


def sharded_row_write(ctx: MeshContext, *, row_units: int):
    """Overwrite one tenant row (only the owner applies the update)."""
    S = ctx.n_shards

    def inner(state, row, data):
        local = state[0]
        my = lax.axis_index("shard")
        own = (row % S) == my
        lrow = row // S
        cur = bitops.row_slice(local, lrow, row_units)
        new_row = jnp.where(own, data, cur)
        return bitops.row_update(local, lrow, new_row, row_units)[None]

    fn = jax.shard_map(
        inner,
        mesh=ctx.mesh,
        in_specs=(P("shard"), P(), P()),
        out_specs=P("shard"),
    )
    return jax.jit(fn, donate_argnums=(0,))


def sharded_hll_add_changed(ctx: MeshContext, *, pack_results: bool = False):
    """Multi-tenant PFADD with exact per-op changed flags (coalesced path).
    Ops on different shards touch different rows, so per-shard sequential
    semantics compose exactly."""
    S = ctx.n_shards

    def inner(state, rows, c0, c1, c2, valid):
        local = state[0]
        own, lrows = _own_and_local(rows, valid, S)
        new_local, changed = hll_ops.hll_add_changed(
            local, jnp.where(own, lrows, 0), c0, c1, c2, valid=own
        )
        changed = lax.psum(jnp.where(own, changed, False).astype(jnp.int32), "shard")
        out = changed > 0
        if pack_results:
            out = bitops.pack_bool_u32(out)
        return new_local[None], out

    fn = jax.shard_map(
        inner,
        mesh=ctx.mesh,
        in_specs=(P("shard"), P(), P(), P(), P(), P()),
        out_specs=(P("shard"), P()),
    )
    return jax.jit(fn, donate_argnums=(0,))


def sharded_cms_update_estimate(ctx: MeshContext, *, d: int, w: int, cells_per_row: int, estimate_only: bool = False, update_only: bool = False):
    """CMS update/estimate/fused: non-owned ops scatter weight 0 (the add
    identity) into shard-local cells, and estimates psum from the owner."""
    from redisson_tpu.ops import cms as cms_ops

    S = ctx.n_shards

    def inner(state, rows, h1w, h2w, weights, valid):
        local = state[0]
        own, lrows = _own_and_local(rows, valid, S)
        safe_rows = jnp.where(own, lrows, 0)
        if estimate_only:
            new_local = local
        else:
            wts = jnp.where(own, weights, 0)
            new_local = cms_ops.cms_update(
                local, safe_rows, h1w, h2w, wts, d=d, w=w, cells_per_row=cells_per_row
            )
        if update_only:
            return new_local[None]
        est = cms_ops.cms_estimate(
            new_local, safe_rows, h1w, h2w, d=d, w=w, cells_per_row=cells_per_row
        )
        est = lax.psum(jnp.where(own, est, 0), "shard")
        if estimate_only:
            return est
        return new_local[None], est

    specs_in = (P("shard"), P(), P(), P(), P(), P())
    if estimate_only:
        out = P()
        donate = ()
    elif update_only:
        out = P("shard")
        donate = (0,)
    else:
        out = (P("shard"), P())
        donate = (0,)
    fn = jax.shard_map(inner, mesh=ctx.mesh, in_specs=specs_in, out_specs=out)
    return jax.jit(fn, donate_argnums=donate)


def sharded_cms_merge(ctx: MeshContext, *, cells_per_row: int):
    """CMS merge: sources broadcast via psum gather, dst owner adds the sum
    (CMS is linear)."""
    S = ctx.n_shards

    def inner(state, dst_row, src_rows):
        local = state[0]
        my = lax.axis_index("shard")
        rows2d = local[:-1].reshape(-1, cells_per_row)
        own_src = (src_rows % S) == my
        gathered = jnp.where(
            own_src[:, None], rows2d[jnp.where(own_src, src_rows // S, 0)], 0
        )
        full = lax.psum(gathered, "shard")
        summed = full.sum(axis=0, dtype=jnp.uint32)
        own_dst = (dst_row % S) == my
        dst_local = jnp.where(own_dst, dst_row // S, 0)
        cur = bitops.row_slice(local, dst_local, cells_per_row)
        new_row = jnp.where(own_dst, cur + summed, cur)
        return bitops.row_update(local, dst_local, new_row, cells_per_row)[None]

    fn = jax.shard_map(
        inner, mesh=ctx.mesh, in_specs=(P("shard"), P(), P()), out_specs=P("shard")
    )
    return jax.jit(fn, donate_argnums=(0,))
