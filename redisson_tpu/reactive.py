"""Reactive facade — → org/redisson/reactive/ + org/redisson/rx/
(RedissonReactiveClient / RedissonRxClient, SURVEY.md §2.3 facades row).

The reference wraps every object reflectively into Reactor ``Mono/Flux``
or RxJava types (ReactiveProxyBuilder/RxProxyBuilder).  The idiomatic
Python analog of that reactive idiom is **asyncio**: ``client.reactive()``
returns a client whose ``get_*`` factories hand out proxies where every
method call returns an awaitable — the blocking work runs off the event
loop (default executor), results resolve into the coroutine.

    rc = client.reactive()

    async def main():
        bf = rc.get_bloom_filter("users")
        await bf.try_init(1_000_000, 0.01)
        await bf.add("alice")
        hit = await bf.contains("alice")

Like the reference's reactive wrappers this is a REFLECTIVE facade over
the sync objects: the full method surface (camelCase aliases included)
is available without per-object adapter code.

Cancellation caveat (shared with the reference's reactive wrappers over
blocking drivers): cancelling/timing out an await abandons the result
but cannot interrupt the underlying worker thread — a parked blocking
call (queue take, lock wait) runs to completion off-loop.  Prefer the
timeout-taking method variants (poll(timeout), try_lock(wait)) over
asyncio.wait_for for operations that can block indefinitely.
"""

from __future__ import annotations

import functools


class ReactiveProxy:
    """One object's reactive view: every callable attribute returns a
    coroutine; non-callables pass through."""

    __slots__ = ("_obj",)

    def __init__(self, obj):
        object.__setattr__(self, "_obj", obj)

    def __getattr__(self, item):
        target = getattr(self._obj, item)  # resolves camelCase aliases too
        if not callable(target):
            return target

        @functools.wraps(target)
        async def call(*args, **kwargs):
            import asyncio

            from redisson_tpu.grid.base import _spawn_future

            # _spawn_future classifies by method name: possibly-blocking
            # ops (take/poll/lock/acquire/...) get dedicated threads so
            # they can never starve each other; everything else rides
            # ONE bounded pool — 5k concurrent awaits of map gets cost
            # pool-width threads, not 5k (grid/base.py _may_block).
            res = await asyncio.wrap_future(
                _spawn_future(target, args, kwargs)._fut
            )
            # Awaiting an already-async method (fooAsync / *_async)
            # must yield the VALUE, not a future handle.  Only the
            # framework's OWN future types unwrap — duck-typing on
            # result()/done() corrupted legitimate return values (a
            # queue holding concurrent.futures.Future objects would have
            # its elements awaited instead of returned).
            if (
                type(res).__module__.startswith("redisson_tpu")
                and hasattr(res, "result")
                and callable(getattr(res, "result"))
                and hasattr(res, "done")
            ):
                res = await asyncio.wrap_future(
                    _spawn_future(res.result, (), {})._fut
                )
            return res

        return call

    def __repr__(self) -> str:  # pragma: no cover — debugging aid
        return f"ReactiveProxy({self._obj!r})"


class ReactiveClient:
    """→ RedissonClient#reactive(): ``get_*`` factories mirror the sync
    client surface, returning ReactiveProxy-wrapped objects."""

    def __init__(self, client):
        self._client = client

    def __getattr__(self, item):
        if item.startswith("get_") or (
            item.startswith("get") and item[3:4].isupper()
        ):
            factory = getattr(self._client, item)

            def make(*args, **kwargs):
                return ReactiveProxy(factory(*args, **kwargs))

            return make
        raise AttributeError(item)
