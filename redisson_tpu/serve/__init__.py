"""Ingest & serving: metrics, topic bus, streaming pipelines (L5,
SURVEY.md §7)."""

from redisson_tpu.serve.metrics import Metrics
from redisson_tpu.serve.ingest import TopicCmsBridge

__all__ = ["Metrics", "TopicCmsBridge"]
