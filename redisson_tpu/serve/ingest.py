"""Streaming ingest: RTopic → CountMinSketch (BASELINE config 5).

The reference's ingest shape is a pub/sub listener feeding application
code (→ org/redisson/RedissonTopic.java listener delivery, SURVEY.md
§3.5).  Here the listener feeds the TPU coalescer: messages buffer into
batches and flush to ``cms.add_all_async`` on size or deadline, so a
100M-event stream becomes a steady sequence of large device batches —
the heavy-hitter pipeline of benchmark config 5.
"""

from __future__ import annotations

import threading
import time
from typing import Optional

import numpy as np

from redisson_tpu.analysis import witness as _witness


class TopicCmsBridge:
    """Subscribes to a topic and streams every message into a
    CountMinSketch.  Messages are the keys; an optional ``weight_fn``
    maps a message to its count (default 1).

    The flush path is asynchronous: batches ride ``add_all_async`` and a
    small in-flight window is collected in arrival order, so ingest
    throughput tracks the engine, not one blocking round trip.
    """

    def __init__(
        self,
        client,
        topic_name: str,
        cms_name: str,
        *,
        batch_size: int = 8192,
        flush_interval_s: float = 0.005,
        weight_fn=None,
        max_inflight: int = 8,
        max_launch_events: int = 1 << 18,
    ):
        self._client = client
        self._cms = client.get_count_min_sketch(cms_name)
        self._topic = client.get_topic(topic_name)
        self._batch_size = batch_size
        self._interval = flush_interval_s
        self._weight_fn = weight_fn
        self._lock = _witness.named(threading.Lock(), "serve.ingest")
        self._idle = threading.Condition(self._lock)
        self._active = 0  # _on_message calls currently executing
        self._buf: list = []
        self._weights: Optional[list] = [] if weight_fn else None
        # Array messages coalesce here up to max_launch_events before one
        # device launch: per-launch cost on the bench link is latency-
        # dominated, so a 32k-event message per launch caps throughput at
        # ~launch-rate; 8 messages per launch is ~8x in slow phases.
        # Entries are (array, weights|None) pairs; only SAME-dtype
        # messages coalesce (concatenating mixed dtypes would upcast and
        # change codec encodings — the count_min_sketch offer hazard).
        self._abuf: list = []
        self._abuf_n = 0
        self._last_aflush = time.monotonic()
        self._max_launch_events = max_launch_events
        self._inflight: list = []
        self._max_inflight = max_inflight
        self._events = 0
        self._closed = False
        self._last_flush = time.monotonic()
        self._listener_id = self._topic.add_listener(self._on_message)
        self._timer = threading.Thread(
            target=self._deadline_loop, name="rtpu-cms-ingest", daemon=True
        )
        self._timer.start()

    # -- listener path -----------------------------------------------------

    def _on_message(self, channel, message) -> None:
        """One message = one event, or — the high-throughput shape — an
        ndarray of events batched at the producer (the Kafka-style
        pattern; per-event Python dispatch tops out ~200k events/s).
        Array messages coalesce into up-to-``max_launch_events`` device
        launches (per-launch cost dominates on a latency-bound link);
        ``weight_fn`` receives each whole array and may return per-event
        weights."""
        with self._lock:
            if self._closed:
                return
            self._active += 1
        try:
            if isinstance(message, np.ndarray):
                w = self._weight_fn(message) if self._weight_fn else None
                pre = post = None
                with self._lock:
                    self._events += len(message)
                    if self._abuf and self._abuf[0][0].dtype != message.dtype:
                        pre = self._take_arrays_locked()  # dtype boundary
                    self._abuf.append((message, w))
                    self._abuf_n += len(message)
                    if self._abuf_n >= self._max_launch_events:
                        post = self._take_arrays_locked()
                if pre is not None:
                    self._dispatch(*self._concat_arrays(pre))
                if post is not None:
                    self._dispatch(*self._concat_arrays(post))
                return
            flush_now = None
            with self._lock:
                self._buf.append(message)
                if self._weights is not None:
                    self._weights.append(self._weight_fn(message))
                self._events += 1
                if len(self._buf) >= self._batch_size:
                    flush_now = self._take_locked()
            if flush_now is not None:
                self._dispatch(*flush_now)
        finally:
            with self._lock:
                self._active -= 1
                if self._active == 0:
                    self._idle.notify_all()

    def _take_locked(self):
        buf, self._buf = self._buf, []
        if self._weights is not None:
            w, self._weights = self._weights, []
        else:
            w = None
        self._last_flush = time.monotonic()
        return buf, w

    def _take_arrays_locked(self):
        """Detach the coalesced (array, weights) pairs — concatenation
        happens OUTSIDE the lock (multi-MB copies must not serialize
        listener delivery)."""
        pairs, self._abuf = self._abuf, []
        self._abuf_n = 0
        self._last_aflush = time.monotonic()
        return (pairs,)

    @staticmethod
    def _concat_arrays(taken):
        (pairs,) = taken
        arrays = [a for a, _ in pairs]
        buf = arrays[0] if len(arrays) == 1 else np.concatenate(arrays)
        ws = [w for _, w in pairs]
        if all(w is None for w in ws):
            return buf, None
        # Mixed per-message weights: None means "count 1 per event";
        # scalars broadcast — normalize each to a per-event array so the
        # concatenation stays aligned with its events.
        full = []
        for a, w in pairs:
            if w is None:
                full.append(np.ones(len(a), np.int64))
            else:
                w = np.asarray(w)
                full.append(
                    np.full(len(a), int(w), np.int64) if w.ndim == 0 else w
                )
        return buf, np.concatenate(full)

    def _dispatch(self, buf, weights) -> None:
        fut = self._cms.add_all_async(buf, weights)
        with self._lock:
            self._inflight.append(fut)
            drain = (
                self._inflight[: -self._max_inflight]
                if len(self._inflight) > self._max_inflight
                else []
            )
            self._inflight = self._inflight[len(drain):]
        if drain:
            # One mailbox flush for the whole drained window (each host
            # fetch costs a link round trip — the slow-phase killer).
            self._client.collect(drain)

    def _deadline_loop(self) -> None:
        while True:
            time.sleep(self._interval)
            with self._lock:
                if self._closed:
                    return
                now = time.monotonic()
                pending = (
                    self._take_locked()
                    if (
                        self._buf
                        and now - self._last_flush >= self._interval
                    )
                    else None
                )
                # Separate staleness clock: scalar-path flushes must not
                # keep resetting the array buffer's deadline (starvation).
                apending = (
                    self._take_arrays_locked()
                    if (
                        self._abuf
                        and now - self._last_aflush >= self._interval
                    )
                    else None
                )
            if pending is not None:
                self._dispatch(*pending)
            if apending is not None:
                self._dispatch(*self._concat_arrays(apending))

    # -- control -----------------------------------------------------------

    def flush(self) -> None:
        """Drain the buffer and wait for every in-flight batch — including
        listener callbacks still executing on bus workers (their futures
        must land in ``_inflight`` before we sample it)."""
        with self._idle:
            while self._active > 0:
                self._idle.wait(timeout=5.0)
        with self._lock:
            pending = self._take_locked() if self._buf else None
            apending = self._take_arrays_locked() if self._abuf else None
        if pending is not None:
            self._dispatch(*pending)
        if apending is not None:
            self._dispatch(*self._concat_arrays(apending))
        while True:
            with self._lock:
                batch, self._inflight = self._inflight, []
            if not batch:
                return
            self._client.collect(batch)  # one flush, not N fetches

    @property
    def events_ingested(self) -> int:
        return self._events

    def close(self) -> None:
        # Ordering: delist first (new publishes no longer target this
        # bridge), then wait out the CHANNEL's already-queued deliveries
        # (their target lists were snapshotted at publish, so
        # remove_listener does not cancel them — the old close dropped
        # exactly those), then flush buffered + in-flight batches, and
        # only then freeze.
        self._topic.remove_listener(self._listener_id)
        bus = getattr(self._topic, "_bus", None)
        if bus is not None:
            bus.drain(channel=self._topic.get_name())
        self.flush()
        with self._lock:
            self._closed = True
