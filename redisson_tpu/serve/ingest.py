"""Streaming ingest: RTopic → CountMinSketch (BASELINE config 5).

The reference's ingest shape is a pub/sub listener feeding application
code (→ org/redisson/RedissonTopic.java listener delivery, SURVEY.md
§3.5).  Here the listener feeds the TPU coalescer: messages buffer into
batches and flush to ``cms.add_all_async`` on size or deadline, so a
100M-event stream becomes a steady sequence of large device batches —
the heavy-hitter pipeline of benchmark config 5.
"""

from __future__ import annotations

import threading
import time
from typing import Optional


class TopicCmsBridge:
    """Subscribes to a topic and streams every message into a
    CountMinSketch.  Messages are the keys; an optional ``weight_fn``
    maps a message to its count (default 1).

    The flush path is asynchronous: batches ride ``add_all_async`` and a
    small in-flight window is collected in arrival order, so ingest
    throughput tracks the engine, not one blocking round trip.
    """

    def __init__(
        self,
        client,
        topic_name: str,
        cms_name: str,
        *,
        batch_size: int = 8192,
        flush_interval_s: float = 0.005,
        weight_fn=None,
        max_inflight: int = 8,
    ):
        self._cms = client.get_count_min_sketch(cms_name)
        self._topic = client.get_topic(topic_name)
        self._batch_size = batch_size
        self._interval = flush_interval_s
        self._weight_fn = weight_fn
        self._lock = threading.Lock()
        self._idle = threading.Condition(self._lock)
        self._active = 0  # _on_message calls currently executing
        self._buf: list = []
        self._weights: Optional[list] = [] if weight_fn else None
        self._inflight: list = []
        self._max_inflight = max_inflight
        self._events = 0
        self._closed = False
        self._last_flush = time.monotonic()
        self._listener_id = self._topic.add_listener(self._on_message)
        self._timer = threading.Thread(
            target=self._deadline_loop, name="rtpu-cms-ingest", daemon=True
        )
        self._timer.start()

    # -- listener path -----------------------------------------------------

    def _on_message(self, channel, message) -> None:
        """One message = one event, or — the high-throughput shape — an
        ndarray of events batched at the producer (the Kafka-style
        pattern; per-event Python dispatch tops out ~200k events/s).
        Array messages are already batches: they dispatch directly,
        skipping the per-event buffer; ``weight_fn`` then receives the
        whole array and may return per-event weights."""
        import numpy as np

        with self._lock:
            if self._closed:
                return
            self._active += 1
        try:
            if isinstance(message, np.ndarray):
                with self._lock:
                    self._events += len(message)
                w = self._weight_fn(message) if self._weight_fn else None
                self._dispatch(message, w)
                return
            flush_now = None
            with self._lock:
                self._buf.append(message)
                if self._weights is not None:
                    self._weights.append(self._weight_fn(message))
                self._events += 1
                if len(self._buf) >= self._batch_size:
                    flush_now = self._take_locked()
            if flush_now is not None:
                self._dispatch(*flush_now)
        finally:
            with self._lock:
                self._active -= 1
                if self._active == 0:
                    self._idle.notify_all()

    def _take_locked(self):
        buf, self._buf = self._buf, []
        if self._weights is not None:
            w, self._weights = self._weights, []
        else:
            w = None
        self._last_flush = time.monotonic()
        return buf, w

    def _dispatch(self, buf, weights) -> None:
        fut = self._cms.add_all_async(buf, weights)
        with self._lock:
            self._inflight.append(fut)
            drain = (
                self._inflight[: -self._max_inflight]
                if len(self._inflight) > self._max_inflight
                else []
            )
            self._inflight = self._inflight[len(drain):]
        for f in drain:
            f.result()

    def _deadline_loop(self) -> None:
        while True:
            time.sleep(self._interval)
            with self._lock:
                if self._closed:
                    return
                due = (
                    self._buf
                    and time.monotonic() - self._last_flush >= self._interval
                )
                pending = self._take_locked() if due else None
            if pending is not None:
                self._dispatch(*pending)

    # -- control -----------------------------------------------------------

    def flush(self) -> None:
        """Drain the buffer and wait for every in-flight batch — including
        listener callbacks still executing on bus workers (their futures
        must land in ``_inflight`` before we sample it)."""
        with self._idle:
            while self._active > 0:
                self._idle.wait(timeout=5.0)
        with self._lock:
            pending = self._take_locked() if self._buf else None
        if pending is not None:
            self._dispatch(*pending)
        while True:
            with self._lock:
                if not self._inflight:
                    return
                fut = self._inflight.pop(0)
            fut.result()

    @property
    def events_ingested(self) -> int:
        return self._events

    def close(self) -> None:
        # Ordering: delist first (new publishes no longer target this
        # bridge), then wait out the CHANNEL's already-queued deliveries
        # (their target lists were snapshotted at publish, so
        # remove_listener does not cancel them — the old close dropped
        # exactly those), then flush buffered + in-flight batches, and
        # only then freeze.
        self._topic.remove_listener(self._listener_id)
        bus = getattr(self._topic, "_bus", None)
        if bus is not None:
            bus.drain(channel=self._topic.get_name())
        self.flush()
        with self._lock:
            self._closed = True
