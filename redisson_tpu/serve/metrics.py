"""Metrics & batch tracing — built in from day one (SURVEY.md §5: the
reference's OSS core has none; monitoring is a Redisson PRO feature, so this
is an upgrade, and the BASELINE metrics — ops/sec, batch occupancy, p99
flush latency — must be measurable from inside the framework).
"""

from __future__ import annotations

import threading
import time

from redisson_tpu.analysis import witness as _witness


class _Reservoir:
    """Bounded latency reservoir for percentile estimates."""

    def __init__(self, cap: int = 4096):
        self.cap = cap
        self.values: list[float] = []
        self.n = 0

    def add(self, v: float) -> None:
        self.n += 1
        if len(self.values) < self.cap:
            self.values.append(v)
        else:
            # Sliding ring: keeps the most recent ``cap`` samples (NOT a
            # uniform sample of the whole run — steady-state windows are
            # what the percentiles describe).
            self.values[self.n % self.cap] = v

    def percentiles(self, ps) -> list:
        """Nearest-rank percentiles from ONE sort (int(p/100*n) overshot
        by a rank: p50 of [1,2,3,4] must be 2, not 3)."""
        import math

        if not self.values:
            return [0.0 for _ in ps]
        vals = sorted(self.values)
        n = len(vals)
        return [
            vals[min(n - 1, max(0, math.ceil(p / 100.0 * n) - 1))]
            for p in ps
        ]

    def percentile(self, p: float) -> float:
        return self.percentiles([p])[0]


class Metrics:
    def __init__(self):
        self._lock = _witness.named(threading.Lock(), "serve.metrics")
        self.started = time.monotonic()
        self.ops_total = 0
        self.batches_total = 0
        self.wait = _Reservoir()
        self.flush = _Reservoir()

    def reset(self) -> None:
        """Zero counters/reservoirs — benches call this after warmup so
        first-compile latencies don't pollute steady-state percentiles."""
        with self._lock:
            self.started = time.monotonic()
            self.ops_total = 0
            self.batches_total = 0
            self.wait = _Reservoir()
            self.flush = _Reservoir()

    def record_batch(self, *, nops: int, wait_s: float, flush_s: float) -> None:
        with self._lock:
            self.ops_total += nops
            self.batches_total += 1
            self.wait.add(wait_s)
            self.flush.add(flush_s)

    def record_dispatch(self, *, nops: int, enqueue_s: float) -> None:
        """Direct-dispatch recording (no coalescer in front): counts ops
        and the host-side enqueue latency.  The wait reservoir stays
        untouched — nothing queued, so there is no queueing delay to
        report (zeros would fake a perfect p99)."""
        with self._lock:
            self.ops_total += nops
            self.batches_total += 1
            self.flush.add(enqueue_s)

    def snapshot(self) -> dict:
        # Copy under the lock (it contends with the hot flush path), sort
        # OUTSIDE it — and only once per reservoir for both percentiles.
        with self._lock:
            elapsed = max(time.monotonic() - self.started, 1e-9)
            batches = max(self.batches_total, 1)
            ops_total = self.ops_total
            batches_total = self.batches_total
            wait = _Reservoir()
            wait.values = list(self.wait.values)
            flush = _Reservoir()
            flush.values = list(self.flush.values)
        w50, w99 = wait.percentiles([50, 99])
        f50, f99 = flush.percentiles([50, 99])
        return {
            "ops_total": ops_total,
            "batches_total": batches_total,
            "ops_per_sec": ops_total / elapsed,
            "mean_batch_occupancy": ops_total / batches,
            "p50_wait_ms": w50 * 1e3,
            "p99_wait_ms": w99 * 1e3,
            "p50_flush_ms": f50 * 1e3,
            "p99_flush_ms": f99 * 1e3,
        }

    # Monotonic snapshot keys: exported as Prometheus counters (they
    # already carry the required ``_total`` suffix).  Everything else in
    # the snapshot is a point-in-time/derived value -> gauge.  rate()
    # over a counter mis-typed as gauge silently yields garbage, so the
    # split is semantic, not cosmetic.
    _COUNTER_KEYS = ("ops_total", "batches_total")

    def render_prometheus(self) -> str:
        """Plain Prometheus text exposition (SURVEY.md §5 metrics row)."""
        s = self.snapshot()
        lines = []
        for k, v in s.items():
            kind = "counter" if k in self._COUNTER_KEYS else "gauge"
            lines.append(f"# TYPE redisson_tpu_{k} {kind}")
            lines.append(f"redisson_tpu_{k} {v}")
        return "\n".join(lines) + "\n"


class Profiler:
    """jax.profiler integration (SURVEY.md §5 tracing row): captures a
    device trace (TensorBoard/Perfetto-compatible) around a workload
    window, alongside the per-batch wait/flush reservoirs above.

    Usage::

        prof = client.get_profiler()
        prof.start("/tmp/rtpu-trace")
        ... workload ...
        prof.stop()   # trace dir now holds the .trace/.pb files

    Or as a context manager: ``with client.get_profiler().trace(dir): ...``
    """

    def __init__(self):
        import threading

        self._active = False
        self._plock = _witness.named(threading.Lock(), "serve.profiler")

    def start(self, log_dir: str) -> None:
        import jax

        with self._plock:
            if self._active:
                raise RuntimeError("a profiler trace is already active")
            self._active = True
        try:
            jax.profiler.start_trace(log_dir)
        except BaseException:
            with self._plock:
                self._active = False
            raise
        return

    def stop(self) -> None:
        import jax

        with self._plock:
            if not self._active:
                # Calling stop on an inactive profiler is a caller bug
                # (e.g. a FRESH instance where the active one is lost) —
                # silently no-opping left the jax trace running forever.
                raise RuntimeError("no active profiler trace to stop")
            self._active = False
        jax.profiler.stop_trace()

    def trace(self, log_dir: str):
        from contextlib import contextmanager

        @contextmanager
        def _ctx():
            self.start(log_dir)
            try:
                yield self
            finally:
                self.stop()

        return _ctx()

    @staticmethod
    def annotate(name: str):
        """Named region inside a trace (→ jax.profiler.TraceAnnotation)."""
        import jax

        return jax.profiler.TraceAnnotation(name)

    @staticmethod
    def device_memory() -> dict:
        """Current memory stats (bytes) for EVERY device, keyed by
        ``platform:id`` (the Node.address form) — a multi-chip run must
        not be blind on 7 of 8 chips.  Devices whose backend exposes no
        memory_stats() report an empty dict under their key."""
        import jax

        out: dict = {}
        try:
            devices = jax.devices()
        except Exception:
            return out
        for d in devices:
            key = f"{d.platform}:{d.id}"
            try:
                stats = d.memory_stats() or {}
                out[key] = {
                    "bytes_in_use": stats.get("bytes_in_use"),
                    "peak_bytes_in_use": stats.get("peak_bytes_in_use"),
                    "bytes_limit": stats.get("bytes_limit"),
                }
            except Exception:
                out[key] = {}
        return out
