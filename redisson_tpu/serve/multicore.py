"""Per-core front door (ISSUE 17 tentpole) — SO_REUSEPORT reactor
processes with an in-node slot→process map.

PR 11 measured the ceiling this module removes: one keyspace shard's
front door tops out at one GIL — a second in-process event loop is ~10%
*worse* because the merged vectorizer pass serializes on it.  The fix is
the cluster trick applied recursively INSIDE one node (the
Memcache-at-Facebook / Slicer shape, PAPERS.md §1/§3): K cooperating
reactor **processes** share one listen port via ``SO_REUSEPORT`` (the
kernel load-balances accepts), and the node's slot range is partitioned
contiguously across them behind an in-node slot→process map.

Routing rules (docs/performance.md "Per-core front door"):

* **keyless** commands (PING, INFO, CONFIG, SUBSCRIBE, ...) are served
  by whichever worker the connection landed on;
* **worker-local** keyed commands (every key's slot owned by this
  worker) dispatch inline, exactly as a single-process door would;
* a keyed command owned by a **sibling** worker takes a loopback
  in-node handoff: the command is proxied verbatim over a persistent
  unix-domain socket to the owning worker and the reply frame is
  relayed byte-for-byte — invisible to the client.  The in-node map
  itself NEVER emits -MOVED: only the owning worker's own cluster door
  (which sees the command after the handoff) can redirect, so redirects
  always describe the cluster topology, never node internals;
* **splittable** multi-key commands (MGET / MSET / DEL / EXISTS)
  spanning workers split per key, execute on each owner, and merge
  (array order / sums / OK) — byte-identical to the single-process
  reply;
* **fan-out** keyspace commands broadcast to every worker and merge:
  PUBLISH and DBSIZE sum integer replies, FLUSHALL acks once all
  workers acked, KEYS concatenates;
* any other multi-key command spanning workers gets -CROSSSLOT (the
  same key-discipline the cluster door enforces across nodes — use
  hash tags to co-locate).

Known worker-local views (documented, not bugs): SCAN cursors and
RANDOMKEY enumerate the landing worker's slice, and MONITOR streams the
landing worker's dispatches only.
"""

from __future__ import annotations

import logging
import os
import shutil
import signal
import socket
import subprocess
import sys
import tempfile
import threading
import time
from typing import Optional

from redisson_tpu import chaos
from redisson_tpu.analysis import witness as _witness
from redisson_tpu.cluster.slots import NSLOTS, command_keys, key_slot
from redisson_tpu.serve import wireutil

log = logging.getLogger("redisson_tpu.frontdoor")

# Commands broadcast to every worker (merge rule in _fanout): integer
# replies sum, FLUSHALL acks, KEYS concatenates.
_FANOUT_SUM = frozenset(("PUBLISH", "DBSIZE"))
_FANOUT = _FANOUT_SUM | frozenset(("FLUSHALL", "KEYS"))
# Per-key splittable multi-key commands: a span across workers splits
# into per-worker legs and merges byte-identically.
_SPLIT = frozenset(("MGET", "MSET", "DEL", "EXISTS"))

# Keep peer sockets bounded: idle legs beyond this per target close
# instead of repooling (each pooled leg is one fd on BOTH workers).
_POOL_CAP = 16


def reuseport_available() -> bool:
    """Probe SO_REUSEPORT by actually setting it on a throwaway socket —
    the constant existing in the socket module does not mean the kernel
    accepts it (satellite: never a crash at bind time)."""
    if not hasattr(socket, "SO_REUSEPORT"):
        return False
    try:
        s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        try:
            s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEPORT, 1)
        finally:
            s.close()
        return True
    except OSError:
        return False


def effective_processes(requested) -> int:
    """The satellite fallback contract: K > 1 on a platform without
    SO_REUSEPORT degrades to the single-process door with ONE logged
    INFO frontdoor line — never a crash at bind time.  (The caller's
    RespServer then publishes rtpu_frontdoor_processes = 1.)"""
    k = max(1, int(requested or 1))
    if k > 1 and not reuseport_available():
        log.info(
            "frontdoor: SO_REUSEPORT unavailable on this platform — "
            "serving with a single-process front door instead of the "
            "requested %d workers", k,
        )
        return 1
    return k


def device_slice_for_worker(index: int, nworkers: int,
                            ndevices: int) -> Optional[list]:
    """Contiguous per-worker device-index slice (the device analog of
    the slot partition).  None when the node has fewer devices than
    workers — then every worker shares the default enumeration (the
    CPU-backend test shape)."""
    if ndevices < nworkers:
        return None
    lo = index * ndevices // nworkers
    hi = (index + 1) * ndevices // nworkers
    return list(range(lo, hi))


def worker_of_slot(slot: int, nworkers: int) -> int:
    """Fixed contiguous slot partition: worker ``slot * K // NSLOTS``.
    Stable under cluster migration — the in-node map depends only on
    (slot, K), never on which slots the node currently owns."""
    return slot * nworkers // NSLOTS


def worker_slot_range(w: int, nworkers: int) -> tuple:
    """Inclusive (lo, hi) slot range owned by worker ``w``."""
    lo = (w * NSLOTS + nworkers - 1) // nworkers
    hi = ((w + 1) * NSLOTS + nworkers - 1) // nworkers - 1
    return lo, hi


def worker_tag(w: int, nworkers: int) -> str:
    """A short hash tag whose slot lands on worker ``w`` — bench/test
    clients use ``{tag}key`` keys to pin traffic to a known worker."""
    for i in range(100000):
        tag = "w%d" % i
        if worker_of_slot(key_slot(tag.encode()), nworkers) == w:
            return tag
    raise RuntimeError("no tag found (unreachable)")


def peer_sock_path(rundir: str, index: int) -> str:
    return os.path.join(rundir, f"worker-{index}.sock")


class _PeerPool:
    """Persistent unix-domain sockets to ONE sibling worker.  A leg that
    errors in any way is closed, never repooled (RT013: a desynced
    stream must not serve the next handoff)."""

    def __init__(self, path: str, connect_timeout_s: float = 15.0):
        self.path = path
        self.connect_timeout_s = connect_timeout_s
        self._free: list = []
        self._lock = _witness.named(
            threading.Lock(), "serve.multicore.pool"
        )
        self.closed = False

    def get(self) -> socket.socket:
        with self._lock:
            if self._free:
                return self._free.pop()
        # Workers start concurrently: the sibling's listener may not be
        # bound yet on the first handoff — retry within the deadline.
        deadline = time.monotonic() + self.connect_timeout_s
        while True:
            s = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
            try:
                s.connect(self.path)
                return s
            except OSError:
                s.close()
                if time.monotonic() > deadline:
                    raise
                time.sleep(0.05)

    def put(self, s: socket.socket) -> None:
        with self._lock:
            if not self.closed and len(self._free) < _POOL_CAP:
                self._free.append(s)
                return
        try:
            s.close()
        except OSError:
            pass

    def close_all(self) -> None:
        with self._lock:
            self.closed = True
            socks, self._free = self._free, []
        for s in socks:
            try:
                s.close()
            except OSError:
                pass


class MulticoreRouter:
    """The in-node slot→process map of ONE front-door worker: decides
    local vs handoff vs split vs fan-out for every dispatched command,
    serves sibling handoff legs on a unix-domain listener, and owns the
    peer socket pools."""

    def __init__(self, server, nworkers: int, index: int, rundir: str,
                 obs=None):
        if not rundir:
            raise ValueError("multicore worker mode requires frontdoor_dir")
        self.server = server
        self.nworkers = int(nworkers)
        self.index = int(index)
        if not (0 <= self.index < self.nworkers):
            raise ValueError(
                f"frontdoor_index {index} out of range for "
                f"{nworkers} workers"
            )
        self.rundir = rundir
        self.obs = obs
        self._closed = False
        self._pools = {
            w: _PeerPool(peer_sock_path(rundir, w))
            for w in range(self.nworkers)
            if w != self.index
        }
        # Lifetime counters (INFO frontdoor; obs mirrors them as the
        # rtpu_frontdoor_* families).  Ints bumped under the GIL.
        self.n_forward = 0
        self.n_split = 0
        self.n_fanout = 0
        self.n_errors = 0
        # Chaos injection at the handoff leg (the soak's error arm):
        # workers are subprocesses, so the rule arrives by env var and
        # feeds the standard deterministic chaos engine.
        rate = os.environ.get("RTPU_CHAOS_HANDOFF")
        if rate:
            chaos.inject(
                "handoff.leg", kind="error", rate=float(rate),
                seed=int(os.environ.get("RTPU_CHAOS_HANDOFF_SEED", "0") or 0),
            )
        # Serve sibling legs: a private unix listener per worker.  Peer
        # connections are admitted outside max_connections (refusing one
        # would wedge the sibling's forwarded client command).
        path = peer_sock_path(rundir, self.index)
        try:
            os.unlink(path)
        except OSError:
            pass
        self._lsock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        self._lsock.bind(path)
        self._lsock.listen(128)
        self._accept_thread = threading.Thread(
            target=self._peer_accept_loop,
            name="rtpu-frontdoor-peer-accept", daemon=True,
        )
        self._accept_thread.start()
        events = getattr(obs, "events", None)
        if events is not None:
            # Self-announce: the parent is a pure supervisor with no
            # obs ring, so each worker records its own spawn (and
            # siblings record deaths via dead peer listeners).
            events.emit("multicore.worker.spawn", index=self.index,
                        nworkers=self.nworkers, pid=os.getpid())

    # -- routing decisions ---------------------------------------------------

    def wrong_worker_keys(self, cmd) -> bool:
        keys = command_keys(cmd)
        if not keys:
            return False
        me = self.index
        n = self.nworkers
        for k in keys:
            if key_slot(k) * n // NSLOTS != me:
                return True
        return False

    def needs_handoff(self, cmd) -> bool:
        """Reactor detach check: True when dispatching ``cmd`` may block
        on a sibling worker (handoff/split/fan-out legs) — it must ride
        a worker thread, never the event loop."""
        name = cmd[0].decode("latin-1", "replace").upper()
        if name in _FANOUT:
            return True
        return self.wrong_worker_keys(cmd)

    def route(self, name: str, cmd, ctx) -> Optional[bytes]:
        """The _dispatch hook: a reply frame to relay to the client, or
        None to serve locally.  Runs BEFORE the cluster door, so a
        handed-off command is judged by the slot OWNER's door (the
        in-node map never emits -MOVED)."""
        if ctx.is_peer:
            # A sibling already routed this leg here: always local (the
            # no-proxy-loops invariant).
            return None
        if name in _FANOUT:
            return self._fanout(name, cmd, ctx)
        keys = command_keys(cmd)
        if not keys:
            return None
        me = self.index
        n = self.nworkers
        owners = {key_slot(k) * n // NSLOTS for k in keys}
        if owners == {me}:
            return None
        if len(owners) == 1:
            self.n_forward += 1
            self._count("forward")
            return self._forward(owners.pop(), cmd, ctx)
        if name in _SPLIT:
            self.n_split += 1
            self._count("split")
            return self._split(name, cmd, ctx)
        from redisson_tpu.serve.resp import RespError

        raise RespError(
            "CROSSSLOT Keys in request don't hash to the same "
            "front-door worker (use hash tags to co-locate them)"
        )

    # -- the handoff leg -----------------------------------------------------

    def _exchange_frames(self, w: int, cmds) -> list:
        """Ship ``cmds`` to sibling ``w`` over a pooled leg and return
        the raw reply frames VERBATIM (byte-identical relay is the
        differential soak's contract)."""
        payload = b"".join(wireutil.wire_command(c) for c in cmds)
        pool = self._pools[w]
        sock = pool.get()
        ok = False
        try:
            if chaos.ENABLED:
                chaos.fire("handoff.leg")
            sock.sendall(payload)
            frames: list = []
            buf = b""
            pos = 0
            while len(frames) < len(cmds):
                try:
                    end = wireutil.skip_reply_frame(buf, pos)
                except IndexError:
                    chunk = sock.recv(1 << 16)
                    if not chunk:
                        raise OSError("peer worker closed mid-reply")
                    buf += chunk
                    continue
                except ValueError as e:
                    raise OSError(f"corrupt handoff stream: {e}")
                frames.append(buf[pos:end])
                pos = end
            ok = True
            return frames
        finally:
            if ok:
                pool.put(sock)
            else:
                # RT013: the failed leg's socket may hold a half reply —
                # never repool it.
                try:
                    sock.close()
                except OSError:
                    pass

    def _broken(self, kind: str, w, exc) -> bytes:
        from redisson_tpu.serve.resp import _encode_error

        self.n_errors += 1
        if self.obs is not None:
            self.obs.frontdoor_handoff_errors.inc((kind,))
            events = getattr(self.obs, "events", None)
            if events is not None:
                events.emit("multicore.handoff.broken", severity="warn",
                            kind=kind, worker=str(w), error=str(exc))
                if isinstance(exc, (ConnectionRefusedError,
                                    FileNotFoundError)):
                    # The sibling's unix listener is GONE (not merely a
                    # broken stream): the worker itself died.
                    events.emit("multicore.worker.death",
                                severity="error", worker=str(w))
        return _encode_error(
            f"HANDOFFBROKEN in-node {kind} leg to worker {w} failed "
            f"({exc}); retry"
        )

    def _forward(self, w: int, cmd, ctx) -> bytes:
        cmds = [cmd]
        if ctx.asking:
            # The one-shot ASKING grant must travel WITH the command to
            # the owning worker (its door is the one honoring it).
            ctx.asking = False
            cmds = [[b"ASKING"], cmd]
        try:
            return self._exchange_frames(w, cmds)[-1]
        except (OSError, chaos.FaultInjected) as e:
            return self._broken("forward", w, e)

    # -- split / fan-out merges ---------------------------------------------

    def _split(self, name: str, cmd, ctx) -> bytes:
        """Per-key split of MGET/MSET/DEL/EXISTS across workers, merged
        byte-identically to the single-process reply."""
        from redisson_tpu.serve.resp import _encode_int, _encode_simple

        step = 2 if name == "MSET" else 1
        groups: dict = {}  # worker -> [(position, key-args slice)]
        args = cmd[1:]
        for pos in range(0, len(args), step):
            w = worker_of_slot(key_slot(args[pos]), self.nworkers)
            groups.setdefault(w, []).append((pos // step, args[pos:pos + step]))
        legs: dict = {}  # worker -> raw reply frame
        cname = cmd[0]
        for w, items in groups.items():
            sub = [cname] + [a for _, chunk in items for a in chunk]
            if w == self.index:
                # Local leg re-enters _dispatch (its keys are now all
                # local, so the hook passes it through).
                legs[w] = self.server._dispatch(sub, ctx, name=name)
            else:
                try:
                    legs[w] = self._exchange_frames(w, [sub])[0]
                except (OSError, chaos.FaultInjected) as e:
                    return self._broken("split", w, e)
        for f in legs.values():
            if f.startswith(b"-"):
                return f  # relay the first error leg verbatim
        if name == "MSET":
            return _encode_simple("OK")
        if name in ("DEL", "EXISTS"):
            return _encode_int(sum(int(f[1:-2]) for f in legs.values()))
        # MGET: scatter the per-leg array items back to request order.
        out: list = [None] * ((len(args) + step - 1) // step)
        for w, items in groups.items():
            vals, _ = wireutil.decode_reply(legs[w])
            for (pos, _chunk), v in zip(items, vals):
                out[pos] = v
        return wireutil.encode_reply(out)

    def _fanout(self, name: str, cmd, ctx) -> bytes:
        from redisson_tpu.serve.resp import _encode_int

        self.n_fanout += 1
        self._count("fanout")
        local = self.server._invoke_handler(name, cmd, ctx)
        legs: list = []
        for w in range(self.nworkers):
            if w == self.index:
                continue
            try:
                legs.append(self._exchange_frames(w, [cmd])[0])
            except (OSError, chaos.FaultInjected) as e:
                return self._broken("fanout", w, e)
        for f in legs:
            if f.startswith(b"-"):
                return f
        if name in _FANOUT_SUM:
            total = int(local[1:-2])
            for f in legs:
                total += int(f[1:-2])
            return _encode_int(total)
        if name == "KEYS":
            merged, _ = wireutil.decode_reply(local)
            for f in legs:
                vals, _ = wireutil.decode_reply(f)
                merged.extend(vals)
            return wireutil.encode_reply(merged)
        return local  # FLUSHALL: every worker acked

    # -- peer serving / lifecycle -------------------------------------------

    def _count(self, kind: str) -> None:
        if self.obs is not None:
            self.obs.frontdoor_handoffs.inc((kind,))

    def handoff_count(self) -> int:
        return self.n_forward + self.n_split + self.n_fanout

    def _peer_accept_loop(self) -> None:
        while not self._closed:
            try:
                conn, _ = self._lsock.accept()
            except OSError:
                return
            self.server._admit_peer(conn)

    def info_lines(self) -> list:
        return [
            f"frontdoor_handoffs_forward:{self.n_forward}",
            f"frontdoor_handoffs_split:{self.n_split}",
            f"frontdoor_handoffs_fanout:{self.n_fanout}",
            f"frontdoor_handoff_errors:{self.n_errors}",
        ]

    def close(self) -> None:
        self._closed = True
        try:
            self._lsock.close()
        except OSError:
            pass
        try:
            os.unlink(peer_sock_path(self.rundir, self.index))
        except OSError:
            pass
        for pool in self._pools.values():
            pool.close_all()


# -- process topology (the node parent) --------------------------------------


def _free_port(host: str) -> int:
    s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    try:
        s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        s.bind((host, 0))
        return s.getsockname()[1]
    finally:
        s.close()


class MulticoreNode:
    """Spawn and own K front-door worker processes sharing ONE listen
    port via SO_REUSEPORT.  The parent is a pure supervisor (the
    ClusterSupervisor idiom): it owns no engine, forwards shutdown, and
    reaps the workers — the pgrep no-orphans CI gate counts on that."""

    def __init__(self, nworkers: int, host: str = "127.0.0.1",
                 port: int = 0, platform: Optional[str] = "cpu",
                 rundir: Optional[str] = None,
                 metrics_port: Optional[int] = None,
                 extra_args=(), env_extra=None,
                 startup_timeout_s: float = 120.0):
        if nworkers < 2:
            raise ValueError("MulticoreNode wants nworkers >= 2")
        if not reuseport_available():
            raise RuntimeError("SO_REUSEPORT unavailable on this platform")
        self.nworkers = int(nworkers)
        self.host = host
        self.port = int(port) or _free_port(host)
        self.rundir = rundir or tempfile.mkdtemp(prefix="rtpu-frontdoor-")
        self._own_rundir = rundir is None
        self.metrics_ports = (
            [metrics_port + 1 + i for i in range(self.nworkers)]
            if metrics_port else []
        )
        self.procs: list = []
        env = dict(os.environ)
        if platform:
            env["JAX_PLATFORMS"] = platform
        env.update(env_extra or {})
        try:
            for i in range(self.nworkers):
                logf = open(
                    os.path.join(self.rundir, f"worker{i}.log"), "wb"
                )
                argv = [
                    sys.executable, "-m", "redisson_tpu",
                    "--host", host, "--port", str(self.port),
                    "--frontdoor-workers", str(self.nworkers),
                    "--frontdoor-index", str(i),
                    "--frontdoor-dir", self.rundir,
                ]
                if platform:
                    argv += ["--platform", platform]
                if self.metrics_ports:
                    argv += ["--metrics-port", str(self.metrics_ports[i])]
                self.procs.append(subprocess.Popen(
                    argv + list(extra_args),
                    stdout=logf, stderr=subprocess.STDOUT, env=env,
                ))
                logf.close()  # the child holds its own fd now
            self._await_ready(startup_timeout_s)
        except Exception:
            self.shutdown(timeout_s=2.0)
            raise

    def _await_ready(self, timeout_s: float) -> None:
        """PING every worker over ITS unix peer socket — the TCP port
        cannot address one worker (the kernel picks), the peer listener
        can."""
        deadline = time.monotonic() + timeout_s
        for i in range(self.nworkers):
            path = peer_sock_path(self.rundir, i)
            while True:
                if self.procs[i].poll() is not None:
                    raise RuntimeError(
                        f"front-door worker {i} exited rc="
                        f"{self.procs[i].returncode} during startup; see "
                        f"{self.rundir}/worker{i}.log"
                    )
                try:
                    s = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
                    try:
                        s.settimeout(2.0)
                        s.connect(path)
                        if wireutil.exchange(s, [[b"PING"]])[0] == b"PONG":
                            break
                    finally:
                        s.close()
                # rtpulint: disable=RT013 per-attempt probe socket: created and closed inside this try (the finally above), never pooled or reused — no reply bytes can survive into a later exchange
                except (OSError, ValueError):
                    pass
                if time.monotonic() > deadline:
                    raise TimeoutError(
                        f"front-door worker {i} not serving after "
                        f"{timeout_s:.0f}s; see {self.rundir}/worker{i}.log"
                    )
                time.sleep(0.1)

    def shutdown(self, timeout_s: float = 10.0) -> bool:
        """SIGTERM each worker, escalate to SIGKILL at the deadline.
        True when every worker exited on its own (the clean path)."""
        for p in self.procs:
            if p.poll() is None:
                try:
                    p.send_signal(signal.SIGTERM)
                except OSError:
                    pass
        clean = True
        deadline = time.monotonic() + timeout_s
        for p in self.procs:
            try:
                p.wait(timeout=max(0.1, deadline - time.monotonic()))
            except subprocess.TimeoutExpired:
                clean = False
                try:
                    p.kill()
                    p.wait(timeout=5.0)
                except (OSError, subprocess.TimeoutExpired):
                    pass
        if self._own_rundir:
            shutil.rmtree(self.rundir, ignore_errors=True)
        return clean
