"""ctypes loader for the native RESP codec (native/resp_codec.c).

Build-on-first-use: the shared object compiles with the system C
compiler into the package's ``native/`` directory (cached; rebuilt when
the source is newer).  Every consumer degrades to the pure-Python parser
when no compiler is available — the native path is a performance tier,
not a dependency (SURVEY.md §7: native code only where the Python host
loop binds).  Measured on this image: 585k cmds/s through _Reader on a
pipelined bulk stream vs 55k for the pure-Python path (10.7x — the
Python reader re-slices its buffer per line, going quadratic on big
pipelined recvs); ~1.7x on an idealized single-frame loop where
per-argument bytes materialization dominates both paths.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional

_SRC = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "native",
    "resp_codec.c",
)
_SO = os.path.join(os.path.dirname(_SRC), "_resp_codec.so")

# err codes from rtpu_resp_parse
PARSE_OK = 0
PARSE_PROTO_ERROR = 1
PARSE_FALLBACK = 2

# Per-tick byte budget of the native drain loop (mirrors the Python
# reactor's recv budget — one connection cannot monopolize a tick).
TICK_READ_BUDGET = 1 << 20

from redisson_tpu.analysis import witness as _witness

_lock = _witness.named(threading.Lock(), "serve.native_codec")
_parser: Optional["NativeRespParser"] = None
_load_failed = False


def _required() -> bool:
    """RTPU_REQUIRE_NATIVE_RESP turns every silent degrade of the native
    tier into a hard failure: parser load failure AND a stale .so missing
    rtpu_resp_tick / rtpu_resp_encode_bulks all raise instead of quietly
    dropping to Python.  An explicit RTPU_NO_NATIVE_RESP opt-out wins
    (tests that deliberately exercise the Python path)."""
    return bool(os.environ.get("RTPU_REQUIRE_NATIVE_RESP")) and not os.environ.get(
        "RTPU_NO_NATIVE_RESP"
    )


def _build() -> bool:
    if os.path.exists(_SO) and os.path.getmtime(_SO) >= os.path.getmtime(_SRC):
        return True
    tmp = f"{_SO}.{os.getpid()}.tmp"  # per-process: concurrent builders
    for cc in ("cc", "gcc", "g++", "clang"):  # (e.g. the two-process
        try:  # multihost test) must not promote each other's half-written .so
            r = subprocess.run(
                [cc, "-O2", "-shared", "-fPIC", _SRC, "-o", tmp],
                capture_output=True,
                timeout=60,
            )
        except (OSError, subprocess.TimeoutExpired):
            continue
        if r.returncode == 0:
            # fsync-then-rename (RT014): a host crash between the
            # rename and the page-cache writeback would publish a name
            # whose bytes are void — dlopen of a torn .so can crash the
            # process instead of falling back to the Python parser.
            fd = os.open(tmp, os.O_RDONLY)
            try:
                os.fsync(fd)
            finally:
                os.close(fd)
            os.replace(tmp, _SO)
            return True
    return False


class NativeRespParser:
    """Batch frame parser: ``parse(buf)`` returns
    ``(frames, consumed, err)`` where frames is a list of arg-lists
    (bytes), consumed counts the bytes those frames occupy, and err is
    one of the PARSE_* codes describing why parsing stopped."""

    MAX_FRAMES = 1 << 10
    MAX_ARGS = 1 << 13

    def __init__(self, lib: ctypes.CDLL):
        self._lib = lib
        self._fn = lib.rtpu_resp_parse
        self._fn.restype = ctypes.c_long
        L = ctypes.c_long
        self._fn.argtypes = [
            ctypes.c_char_p, L, L, L,
            ctypes.POINTER(L), ctypes.POINTER(L), ctypes.POINTER(L),
            ctypes.POINTER(L), ctypes.POINTER(L),
        ]
        self._enc = lib.rtpu_resp_encode_ints
        self._enc.restype = ctypes.c_long
        self._enc.argtypes = [ctypes.POINTER(L), L, ctypes.c_char_p, L]
        # Batch bulk-reply encoder (fused GET/MGET runs, container
        # reads).  getattr-guarded: a stale .so without the symbol (no
        # compiler to rebuild) must degrade this one call, not unload
        # the whole parser.
        self._enc_bulks = getattr(lib, "rtpu_resp_encode_bulks", None)
        if self._enc_bulks is None and _required():
            raise RuntimeError(
                "RTPU_REQUIRE_NATIVE_RESP: loaded _resp_codec.so is stale — "
                "rtpu_resp_encode_bulks is missing (rebuild requires a C compiler)"
            )
        if self._enc_bulks is not None:
            self._enc_bulks.restype = ctypes.c_long
            self._enc_bulks.argtypes = [
                ctypes.c_char_p, ctypes.POINTER(L), ctypes.POINTER(L), L,
                ctypes.c_char_p, L,
            ]
        self._counts = (L * self.MAX_FRAMES)()
        self._offs = (L * self.MAX_ARGS)()
        self._lens = (L * self.MAX_ARGS)()
        self._consumed = L()
        self._err = L()

    def parse(self, buf: bytes):
        n = self._fn(
            buf, len(buf), self.MAX_FRAMES, self.MAX_ARGS,
            self._counts, self._offs, self._lens,
            ctypes.byref(self._consumed), ctypes.byref(self._err),
        )
        frames = []
        a = 0
        offs, lens, counts = self._offs, self._lens, self._counts
        for f in range(n):
            c = counts[f]
            frames.append(
                [buf[offs[a + i] : offs[a + i] + lens[a + i]] for i in range(c)]
            )
            a += c
        return frames, self._consumed.value, self._err.value

    def encode_ints(self, vals) -> bytes:
        L = ctypes.c_long
        n = len(vals)
        arr = (L * n)(*vals)
        cap = 26 * n
        out = ctypes.create_string_buffer(cap)
        w = self._enc(arr, n, out, cap)
        if w < 0:  # pragma: no cover — cap is sized to the worst case
            raise ValueError("encode buffer overflow")
        return out.raw[:w]

    def encode_bulks(self, vals) -> Optional[bytes]:
        """Serialize ``vals`` (bytes or None per item) as concatenated
        RESP bulk-string replies in ONE native call; None when the loaded
        .so predates the symbol (caller keeps its Python path)."""
        if self._enc_bulks is None:
            return None
        L = ctypes.c_long
        n = len(vals)
        offs = (L * n)()
        lens = (L * n)()
        parts = []
        off = 0
        for i, v in enumerate(vals):
            if v is None:
                lens[i] = -1
            else:
                parts.append(v)
                offs[i] = off
                lens[i] = len(v)
                off += len(v)
        payload = b"".join(parts)
        cap = off + 26 * n
        out = ctypes.create_string_buffer(cap)
        w = self._enc_bulks(payload, offs, lens, n, out, cap)
        if w < 0:  # pragma: no cover — cap is sized to the worst case
            raise ValueError("encode buffer overflow")
        return out.raw[:w]


def _fail(reason: str) -> None:
    if _required():
        raise RuntimeError(f"RTPU_REQUIRE_NATIVE_RESP: {reason}")


def get_parser() -> Optional[NativeRespParser]:
    """Per-connection consumers each get their OWN parser instance
    (the descriptor arrays are per-instance scratch); this returns a
    template whose lib handle they share, or None when unavailable."""
    global _parser, _load_failed
    if os.environ.get("RTPU_NO_NATIVE_RESP"):
        return None
    if _parser is not None:
        return NativeRespParser(_parser._lib)
    if _load_failed:
        _fail("native RESP codec previously failed to load")
        return None
    with _lock:
        if _parser is not None:
            return NativeRespParser(_parser._lib)
        if _load_failed:
            _fail("native RESP codec previously failed to load")
            return None
        try:
            if not _build():
                _load_failed = True
                _fail("no C compiler available to build _resp_codec.so")
                return None
            lib = ctypes.CDLL(_SO)
            _parser = NativeRespParser(lib)
        except RuntimeError:
            _load_failed = True
            raise
        except (OSError, AttributeError):
            # AttributeError: the .so built but exports mangled/missing
            # symbols (e.g. compiled as C++ without extern "C") — degrade
            # to the Python parser instead of crashing every connection.
            _load_failed = True
            _fail("_resp_codec.so failed to load or is missing symbols")
            return None
    return NativeRespParser(_parser._lib)


class TickBuf:
    """Per-connection leftover buffer for :class:`NativeTicker` — starts
    tiny (idle connections are the common case at scale) and doubles when
    a single frame outgrows it."""

    INITIAL = 1 << 12
    # A hair over proto-max-bulk-len: one 512MB bulk plus framing always
    # fits; a frame that does not (multi-bulk gigabytes) falls back to
    # the unbounded Python framer.
    MAX = (1 << 29) + (1 << 16)

    __slots__ = ("buf", "cap", "have")

    def __init__(self):
        self.cap = self.INITIAL
        self.buf = ctypes.create_string_buffer(self.cap)
        self.have = 0

    def grow(self) -> bool:
        if self.cap >= self.MAX:
            return False
        ncap = min(self.cap * 2, self.MAX)
        nbuf = ctypes.create_string_buffer(ncap)
        ctypes.memmove(nbuf, self.buf, self.have)
        self.buf, self.cap = nbuf, ncap
        return True

    def take(self) -> bytes:
        """Drain the leftover bytes (handing a connection over to the
        slow-path framer)."""
        out = bytes(memoryview(self.buf)[: self.have])
        self.have = 0
        return out


class NativeTicker:
    """The native per-tick hot loop (rtpu_resp_tick): one readable-fd
    drain + RESP frame parse + per-frame family classification in a
    single ctypes call, leaving Python only dispatch decisions.

    One instance per reactor THREAD — the descriptor arrays are shared
    scratch, extracted before the next call; only the leftover bytes
    (:class:`TickBuf`) are per-connection state.
    """

    MAX_FRAMES = NativeRespParser.MAX_FRAMES
    MAX_ARGS = NativeRespParser.MAX_ARGS

    def __init__(self, lib: ctypes.CDLL):
        self._lib = lib
        L = ctypes.c_long
        self._fn = lib.rtpu_resp_tick
        self._fn.restype = L
        self._fn.argtypes = [
            L, ctypes.c_void_p, L, L, L, L, L,
            ctypes.POINTER(L), ctypes.POINTER(L), ctypes.POINTER(L),
            ctypes.POINTER(L), ctypes.POINTER(L), ctypes.POINTER(L),
            ctypes.POINTER(L), ctypes.POINTER(L),
        ]
        self._counts = (L * self.MAX_FRAMES)()
        self._offs = (L * self.MAX_ARGS)()
        self._lens = (L * self.MAX_ARGS)()
        self._fams = (L * self.MAX_FRAMES)()
        self._consumed = L()
        self._nread = L()
        self._eof = L()
        self._err = L()

    def new_buf(self) -> TickBuf:
        return TickBuf()

    def tick(self, fd: int, tbuf: TickBuf, out) -> tuple:
        """Drain ``fd`` and append ``(family, argv)`` tuples to ``out``.

        Returns ``(nread, eof, err)``.  err != PARSE_OK means the
        connection must fall back to the slow-path framer: feed it
        ``tbuf.take()`` and retire the tick path for this connection.
        The read budget caps BYTES READ per tick, never parsing — every
        complete frame already buffered is always surfaced (a frame left
        unparsed with no further bytes coming would hang, since the
        selector only fires on new readability).
        """
        total = 0
        eof = 0
        counts, offs, lens, fams = self._counts, self._offs, self._lens, self._fams
        while True:
            rem = TICK_READ_BUDGET - total
            if rem < 0:
                rem = 0
            n = self._fn(
                fd, tbuf.buf, tbuf.cap, tbuf.have, rem,
                self.MAX_FRAMES, self.MAX_ARGS,
                counts, offs, lens, fams,
                ctypes.byref(self._consumed), ctypes.byref(self._nread),
                ctypes.byref(self._eof), ctypes.byref(self._err),
            )
            have = tbuf.have + self._nread.value
            total += self._nread.value
            err = self._err.value
            mv = memoryview(tbuf.buf)
            a = 0
            for f in range(n):
                c = counts[f]
                out.append(
                    (
                        fams[f],
                        [
                            bytes(mv[offs[a + i] : offs[a + i] + lens[a + i]])
                            for i in range(c)
                        ],
                    )
                )
                a += c
            mv.release()
            consumed = self._consumed.value
            left = have - consumed
            if left and consumed:
                ctypes.memmove(tbuf.buf, ctypes.byref(tbuf.buf, consumed), left)
            tbuf.have = left
            if self._eof.value:
                eof = 1
            if err != PARSE_OK:
                return total, eof, err
            if n == 0:
                if left == tbuf.cap and not eof:
                    # One frame larger than the buffer: grow and re-drain.
                    if not tbuf.grow():
                        return total, eof, PARSE_FALLBACK
                    continue
                return total, eof, PARSE_OK
            # n > 0: the descriptor caps may have cut off complete frames
            # still in the leftover — loop until a scan yields nothing.


def get_ticker() -> Optional[NativeTicker]:
    """A :class:`NativeTicker` bound to the loaded library, or None (no
    compiler, RTPU_NO_NATIVE_RESP / RTPU_NO_NATIVE_TICK opt-outs, or the
    .so predates rtpu_resp_tick).  RTPU_NO_NATIVE_TICK exists for the
    native-tick A/B arm: it disables only the fused drain loop while the
    per-frame parser stays native."""
    if os.environ.get("RTPU_NO_NATIVE_TICK"):
        return None
    p = get_parser()
    if p is None:
        return None
    if getattr(p._lib, "rtpu_resp_tick", None) is None:
        _fail("loaded _resp_codec.so is stale — rtpu_resp_tick is missing")
        return None
    return NativeTicker(p._lib)
