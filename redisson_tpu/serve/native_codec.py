"""ctypes loader for the native RESP codec (native/resp_codec.c).

Build-on-first-use: the shared object compiles with the system C
compiler into the package's ``native/`` directory (cached; rebuilt when
the source is newer).  Every consumer degrades to the pure-Python parser
when no compiler is available — the native path is a performance tier,
not a dependency (SURVEY.md §7: native code only where the Python host
loop binds).  Measured on this image: 585k cmds/s through _Reader on a
pipelined bulk stream vs 55k for the pure-Python path (10.7x — the
Python reader re-slices its buffer per line, going quadratic on big
pipelined recvs); ~1.7x on an idealized single-frame loop where
per-argument bytes materialization dominates both paths.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional

_SRC = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "native",
    "resp_codec.c",
)
_SO = os.path.join(os.path.dirname(_SRC), "_resp_codec.so")

# err codes from rtpu_resp_parse
PARSE_OK = 0
PARSE_PROTO_ERROR = 1
PARSE_FALLBACK = 2

from redisson_tpu.analysis import witness as _witness

_lock = _witness.named(threading.Lock(), "serve.native_codec")
_parser: Optional["NativeRespParser"] = None
_load_failed = False


def _build() -> bool:
    if os.path.exists(_SO) and os.path.getmtime(_SO) >= os.path.getmtime(_SRC):
        return True
    tmp = f"{_SO}.{os.getpid()}.tmp"  # per-process: concurrent builders
    for cc in ("cc", "gcc", "g++", "clang"):  # (e.g. the two-process
        try:  # multihost test) must not promote each other's half-written .so
            r = subprocess.run(
                [cc, "-O2", "-shared", "-fPIC", _SRC, "-o", tmp],
                capture_output=True,
                timeout=60,
            )
        except (OSError, subprocess.TimeoutExpired):
            continue
        if r.returncode == 0:
            # fsync-then-rename (RT014): a host crash between the
            # rename and the page-cache writeback would publish a name
            # whose bytes are void — dlopen of a torn .so can crash the
            # process instead of falling back to the Python parser.
            fd = os.open(tmp, os.O_RDONLY)
            try:
                os.fsync(fd)
            finally:
                os.close(fd)
            os.replace(tmp, _SO)
            return True
    return False


class NativeRespParser:
    """Batch frame parser: ``parse(buf)`` returns
    ``(frames, consumed, err)`` where frames is a list of arg-lists
    (bytes), consumed counts the bytes those frames occupy, and err is
    one of the PARSE_* codes describing why parsing stopped."""

    MAX_FRAMES = 1 << 10
    MAX_ARGS = 1 << 13

    def __init__(self, lib: ctypes.CDLL):
        self._lib = lib
        self._fn = lib.rtpu_resp_parse
        self._fn.restype = ctypes.c_long
        L = ctypes.c_long
        self._fn.argtypes = [
            ctypes.c_char_p, L, L, L,
            ctypes.POINTER(L), ctypes.POINTER(L), ctypes.POINTER(L),
            ctypes.POINTER(L), ctypes.POINTER(L),
        ]
        self._enc = lib.rtpu_resp_encode_ints
        self._enc.restype = ctypes.c_long
        self._enc.argtypes = [ctypes.POINTER(L), L, ctypes.c_char_p, L]
        # Batch bulk-reply encoder (fused GET/MGET runs, container
        # reads).  getattr-guarded: a stale .so without the symbol (no
        # compiler to rebuild) must degrade this one call, not unload
        # the whole parser.
        self._enc_bulks = getattr(lib, "rtpu_resp_encode_bulks", None)
        if self._enc_bulks is not None:
            self._enc_bulks.restype = ctypes.c_long
            self._enc_bulks.argtypes = [
                ctypes.c_char_p, ctypes.POINTER(L), ctypes.POINTER(L), L,
                ctypes.c_char_p, L,
            ]
        self._counts = (L * self.MAX_FRAMES)()
        self._offs = (L * self.MAX_ARGS)()
        self._lens = (L * self.MAX_ARGS)()
        self._consumed = L()
        self._err = L()

    def parse(self, buf: bytes):
        n = self._fn(
            buf, len(buf), self.MAX_FRAMES, self.MAX_ARGS,
            self._counts, self._offs, self._lens,
            ctypes.byref(self._consumed), ctypes.byref(self._err),
        )
        frames = []
        a = 0
        offs, lens, counts = self._offs, self._lens, self._counts
        for f in range(n):
            c = counts[f]
            frames.append(
                [buf[offs[a + i] : offs[a + i] + lens[a + i]] for i in range(c)]
            )
            a += c
        return frames, self._consumed.value, self._err.value

    def encode_ints(self, vals) -> bytes:
        L = ctypes.c_long
        n = len(vals)
        arr = (L * n)(*vals)
        cap = 26 * n
        out = ctypes.create_string_buffer(cap)
        w = self._enc(arr, n, out, cap)
        if w < 0:  # pragma: no cover — cap is sized to the worst case
            raise ValueError("encode buffer overflow")
        return out.raw[:w]

    def encode_bulks(self, vals) -> Optional[bytes]:
        """Serialize ``vals`` (bytes or None per item) as concatenated
        RESP bulk-string replies in ONE native call; None when the loaded
        .so predates the symbol (caller keeps its Python path)."""
        if self._enc_bulks is None:
            return None
        L = ctypes.c_long
        n = len(vals)
        offs = (L * n)()
        lens = (L * n)()
        parts = []
        off = 0
        for i, v in enumerate(vals):
            if v is None:
                lens[i] = -1
            else:
                parts.append(v)
                offs[i] = off
                lens[i] = len(v)
                off += len(v)
        payload = b"".join(parts)
        cap = off + 26 * n
        out = ctypes.create_string_buffer(cap)
        w = self._enc_bulks(payload, offs, lens, n, out, cap)
        if w < 0:  # pragma: no cover — cap is sized to the worst case
            raise ValueError("encode buffer overflow")
        return out.raw[:w]


def get_parser() -> Optional[NativeRespParser]:
    """Per-connection consumers each get their OWN parser instance
    (the descriptor arrays are per-instance scratch); this returns a
    template whose lib handle they share, or None when unavailable."""
    global _parser, _load_failed
    if os.environ.get("RTPU_NO_NATIVE_RESP"):
        return None
    if _parser is not None:
        return NativeRespParser(_parser._lib)
    if _load_failed:
        return None
    with _lock:
        if _parser is not None:
            return NativeRespParser(_parser._lib)
        if _load_failed:
            return None
        try:
            if not _build():
                _load_failed = True
                return None
            lib = ctypes.CDLL(_SO)
            _parser = NativeRespParser(lib)
        except (OSError, AttributeError):
            # AttributeError: the .so built but exports mangled/missing
            # symbols (e.g. compiled as C++ without extern "C") — degrade
            # to the Python parser instead of crashing every connection.
            _load_failed = True
            return None
    return NativeRespParser(_parser._lib)
