"""NodesGroup — → org/redisson/api/NodesGroup / RedisNodes (SURVEY.md
§2.3 admin row): per-node ping/info.  Nodes here are the devices of the
execution backend (the mesh shards in cluster mode, the single chip
otherwise); ``ping`` round-trips a tiny computation through each device.
"""

from __future__ import annotations

import time
from typing import Any


class Node:
    def __init__(self, device, shard: int):
        self._device = device
        self.shard = shard

    @property
    def address(self) -> str:
        return f"{self._device.platform}:{self._device.id}"

    def ping(self, timeout_seconds: float = 30.0) -> bool:
        """One tiny device round trip (the PING health check analog),
        bounded by ``timeout_seconds`` — a wedged device returns False
        instead of hanging the health check."""
        import threading

        result = [False]

        def probe():
            import jax
            import jax.numpy as jnp

            try:
                x = jax.device_put(jnp.ones((8,), jnp.uint32), self._device)
                result[0] = int((x + 1).sum()) == 16
            except Exception:
                result[0] = False

        t = threading.Thread(target=probe, daemon=True)
        t.start()
        t.join(timeout_seconds)
        return result[0] and not t.is_alive()

    def info(self) -> dict[str, Any]:
        """→ Node#info (INFO reply analog): device identity + memory."""
        d = self._device
        out = {
            "id": d.id,
            "platform": d.platform,
            "device_kind": getattr(d, "device_kind", "unknown"),
            "process_index": getattr(d, "process_index", 0),
            "shard": self.shard,
        }
        try:
            stats = d.memory_stats()
            if stats:
                out["bytes_in_use"] = stats.get("bytes_in_use")
                out["bytes_limit"] = stats.get("bytes_limit")
        except Exception:
            pass
        return out

    def time(self) -> float:
        """→ Node#time (TIME): host clock — devices carry no wall clock."""
        return time.time()


class NodesGroup:
    """→ RedissonClient#getNodesGroup."""

    def __init__(self, client):
        self._client = client

    def _devices(self):
        engine = self._client._engine
        ctx = getattr(getattr(engine, "executor", None), "ctx", None)
        if ctx is not None:
            return list(ctx.devices)
        import jax

        try:
            return [jax.devices()[0]]
        except Exception:
            return []

    def get_nodes(self) -> list[Node]:
        return [Node(d, i) for i, d in enumerate(self._devices())]

    def ping_all(self) -> bool:
        nodes = self.get_nodes()
        return bool(nodes) and all(n.ping() for n in nodes)
