"""NodesGroup — → org/redisson/api/NodesGroup / RedisNodes (SURVEY.md
§2.3 admin row): per-node ping/info.  Nodes here are the devices of the
execution backend (the mesh shards in cluster mode, the single chip
otherwise); ``ping`` round-trips a tiny computation through each device.
"""

from __future__ import annotations

import time
from typing import Any


class Node:
    def __init__(self, device, shard: int):
        self._device = device
        self.shard = shard

    @property
    def address(self) -> str:
        return f"{self._device.platform}:{self._device.id}"

    def ping(self, timeout_seconds: float = 30.0) -> bool:
        """One tiny device round trip (the PING health check analog),
        bounded by ``timeout_seconds`` — a wedged device returns False
        instead of hanging the health check."""
        import threading

        # One probe thread per NODE, reused across pings: a wedged device
        # parks its probe forever, and spawning a fresh thread per call
        # leaked one stuck thread per monitor sweep (unbounded on a
        # long-running server).  While the previous probe is still
        # parked, the device is by definition not answering — report
        # down WITHOUT stacking another probe behind it.
        prev = getattr(self, "_probe_thread", None)
        if prev is not None and prev.is_alive():
            return False
        result = [False]

        def probe():
            import jax
            import jax.numpy as jnp

            try:
                x = jax.device_put(jnp.ones((8,), jnp.uint32), self._device)
                result[0] = int((x + 1).sum()) == 16
            except Exception:
                result[0] = False

        t = threading.Thread(target=probe, daemon=True)
        self._probe_thread = t
        t.start()
        t.join(timeout_seconds)
        return result[0] and not t.is_alive()

    def info(self) -> dict[str, Any]:
        """→ Node#info (INFO reply analog): device identity + memory."""
        d = self._device
        out = {
            "id": d.id,
            "platform": d.platform,
            "device_kind": getattr(d, "device_kind", "unknown"),
            "process_index": getattr(d, "process_index", 0),
            "shard": self.shard,
        }
        try:
            stats = d.memory_stats()
            if stats:
                out["bytes_in_use"] = stats.get("bytes_in_use")
                out["bytes_limit"] = stats.get("bytes_limit")
        except Exception:
            pass
        return out

    def time(self) -> float:
        """→ Node#time (TIME): host clock — devices carry no wall clock."""
        return time.time()


class NodeDownEvent:
    """Typed failure event: a shard's device stopped answering ping —
    the failedSlaveCheckInterval / PingConnectionHandler analog
    (SURVEY.md §5 failure row)."""

    def __init__(self, shard: int, address: str):
        self.shard = shard
        self.address = address

    def __repr__(self) -> str:  # pragma: no cover — debugging aid
        return f"NodeDownEvent(shard={self.shard}, address={self.address!r})"


class NodeUpEvent:
    """Recovery counterpart of NodeDownEvent."""

    def __init__(self, shard: int, address: str):
        self.shard = shard
        self.address = address

    def __repr__(self) -> str:  # pragma: no cover — debugging aid
        return f"NodeUpEvent(shard={self.shard}, address={self.address!r})"


class FailureMonitor:
    """Background monitor consuming ``Node.ping`` on an interval and
    surfacing dead/recovered shards as typed events — the topology-
    monitor loop of ClusterConnectionManager reduced to its pod-local
    substance.  Listeners receive NodeDownEvent exactly once per
    down-transition (and NodeUpEvent on recovery), not once per failed
    ping."""

    def __init__(self, nodes_group: "NodesGroup", interval_s: float = 1.0,
                 ping_timeout_s: float = 10.0):
        import threading

        self._ng = nodes_group
        self.interval_s = interval_s
        self.ping_timeout_s = ping_timeout_s
        self._listeners: list = []
        self._down: set[int] = set()
        self._stop = threading.Event()
        self._thread = None
        self._threading = threading
        # Serializes sweeps: a monitor thread whose stop() join timed out
        # (wedged ping) may overlap the next start()'s thread briefly —
        # the lock keeps _down/listener emission race-free until the old
        # thread sees its own stop event and exits.
        from redisson_tpu.analysis import witness as _witness

        self._sweep_lock = _witness.named(
            threading.Lock(), "serve.nodes.sweep"
        )

    def add_listener(self, cb) -> None:
        """``cb(event)`` is invoked from the monitor thread."""
        self._listeners.append(cb)

    def down_shards(self) -> set:
        return set(self._down)

    def check_once(self) -> list:
        """One synchronous sweep (also what the thread loops); returns the
        events emitted."""
        with self._sweep_lock:
            return self._check_once_locked()

    def _check_once_locked(self) -> list:
        events = []
        for node in self._ng.get_nodes():
            ok = node.ping(self.ping_timeout_s)
            if not ok and node.shard not in self._down:
                self._down.add(node.shard)
                events.append(NodeDownEvent(node.shard, node.address))
            elif ok and node.shard in self._down:
                self._down.discard(node.shard)
                events.append(NodeUpEvent(node.shard, node.address))
        for ev in events:
            for cb in self._listeners:
                try:
                    cb(ev)
                except Exception:  # pragma: no cover — listener bug
                    pass
        return events

    def start(self) -> None:
        with self._sweep_lock:  # start/stop are thread-safe
            if self._thread is not None and self._thread.is_alive():
                if not self._stop.is_set():
                    return  # already running
            # Each thread closes over its OWN stop event: clearing a
            # shared event would resurrect a zombie thread whose stop()
            # join timed out on a wedged ping (it would loop forever
            # beside the new one).
            stop = self._threading.Event()
            self._stop = stop

            def loop():
                while not stop.wait(self.interval_s):
                    self.check_once()

            self._thread = self._threading.Thread(
                target=loop, name="rtpu-failure-monitor", daemon=True
            )
            self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=5.0)
            if not t.is_alive():
                self._thread = None


class NodesGroup:
    """→ RedissonClient#getNodesGroup."""

    def __init__(self, client):
        self._client = client

    def _devices(self):
        engine = self._client._engine
        ctx = getattr(getattr(engine, "executor", None), "ctx", None)
        if ctx is not None:
            return list(ctx.devices)
        import jax

        try:
            return [jax.devices()[0]]
        except Exception:
            return []

    def get_nodes(self) -> list[Node]:
        return [Node(d, i) for i, d in enumerate(self._devices())]

    def ping_all(self) -> bool:
        nodes = self.get_nodes()
        return bool(nodes) and all(n.ping() for n in nodes)
